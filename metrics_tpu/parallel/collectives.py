"""State synchronisation over a named mesh axis — the ``gather_all_tensors`` analogue.

Parity: reference ``torchmetrics/utilities/distributed.py`` —
  * ``gather_all_tensors`` (:96)  -> ``all_gather_stack``/``all_gather_cat`` via
    ``jax.lax.all_gather`` (XLA schedules the collective; no barrier, no separate
    shape-gather: shapes are static under jit, which deletes the reference's
    2-collectives-per-state overhead at :123-145).
  * ``reduce`` (:21) and ``class_reduce`` (:43) -> same-named helpers below (pure jnp).

Beyond parity: ``fused_axis_sync`` merges ALL sum/min/max counter states of a whole
MetricCollection into one flat buffer per reduction and issues a single ``psum``
bundle — O(1) collectives where the reference issues O(metrics x states)
(``metric.py:240-245``).
"""
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.utils.data import METRIC_EPS

Array = jax.Array

# an axis spec: one mesh-axis name or a tuple of names (multi-axis collectives)
AxisSpec = Union[str, Tuple[str, ...]]


def _axis_names(axis_name: Any) -> Tuple[Any, ...]:
    """Normalize an axis spec (single name or tuple of names — multi-axis
    collectives like ``("dp", "grp")`` are first-class in XLA) to a tuple."""
    return tuple(axis_name) if isinstance(axis_name, (tuple, list)) else (axis_name,)


def in_mapped_context(axis_name: Optional[AxisSpec]) -> bool:
    """True if every axis in ``axis_name`` is bound by an enclosing shard_map/pmap."""
    if axis_name is None:
        return False
    names = _axis_names(axis_name)
    if not names:
        return False
    try:
        from jax._src.core import get_axis_env

        env = get_axis_env()
        return all(bool(env.axis_exists(n)) for n in names)
    except Exception:
        return False


def axis_size_or_one(axis_name: Optional[AxisSpec]) -> int:
    if not in_mapped_context(axis_name):
        return 1
    from jax._src.core import get_axis_env

    env = get_axis_env()
    size = 1
    for n in _axis_names(axis_name):
        size *= int(env.axis_size(n))
    return size


def all_gather_cat(x: Array, axis_name: AxisSpec) -> Array:
    """Gather shards along dim 0 (the "cat" reduction): (n,...) -> (world*n, ...)."""
    return lax.all_gather(x, axis_name, tiled=True)


def all_gather_stack(x: Array, axis_name: AxisSpec) -> Array:
    """Gather shards stacked on a new leading dim: (...,) -> (world, ...).

    Matches the reference's post-sync layout for ``dist_reduce_fx=None`` tensor states
    (``metric.py:249-252``: stacked, for the metric's own custom merge at compute).
    """
    return lax.all_gather(x, axis_name, tiled=False)


_REDUCE_COLLECTIVES: Dict[str, Callable] = {
    "sum": lax.psum,
    "mean": lax.pmean,
    "min": lax.pmin,
    "max": lax.pmax,
}


def sync_axis_state(reduce_fx: Any, value: Array, axis_name: AxisSpec) -> Array:
    """Lower one state's ``dist_reduce_fx`` to the matching XLA collective."""
    if reduce_fx in _REDUCE_COLLECTIVES:
        return _REDUCE_COLLECTIVES[reduce_fx](value, axis_name)
    if reduce_fx == "cat":
        return all_gather_cat(value, axis_name)
    if reduce_fx is None:
        return all_gather_stack(value, axis_name)
    if callable(reduce_fx):
        # custom reduce: gather replicas then fold pairwise with the user fn
        gathered = all_gather_stack(value, axis_name)
        out = gathered[0]
        for i in range(1, gathered.shape[0]):
            out = reduce_fx(out, gathered[i])
        return out
    raise ValueError(f"unknown dist_reduce_fx: {reduce_fx!r}")


def fused_axis_sync(
    leaves: List[Tuple[Any, Array]], axis_name: AxisSpec
) -> List[Array]:
    """Sync many (reduce_fx, value) state leaves with a minimal collective bundle.

    Exactly ONE collective per bucket:

    * 'sum'/'mean'/'min'/'max' leaves bucket per (reduction, dtype) — a psum
      does arithmetic, so dtypes cannot mix — raveled into one flat buffer and
      reduced with a single psum/pmean/pmin/pmax;
    * 'cat'/None/custom leaves bucket per BIT-WIDTH across dtypes (f32 and
      i32 share one uint32 carrier via a free bitcast): one stacked
      ``all_gather`` per width, then per-leaf views are reassembled locally —
      (world, n, ...) -> (world*n, ...) for 'cat', (world, ...) for None, and
      a pairwise fold for callables. Shapes and dtypes of one width share the
      buffer because gather is layout-agnostic over raveled bits.

    Returns synced values in input order. A MetricCollection of K metrics with
    S states issues O(reduce-dtype + gather-width buckets) collectives, not
    O(K*S) (the reference's pattern, ``metric.py:240-245``).
    """
    out: List[Optional[Array]] = [None] * len(leaves)
    reduce_buckets: Dict[Tuple[str, Any], List[int]] = {}
    gather_buckets: Dict[int, List[int]] = {}
    for i, (fx, v) in enumerate(leaves):
        if fx in _REDUCE_COLLECTIVES:
            reduce_buckets.setdefault((fx, jnp.asarray(v).dtype), []).append(i)
        else:
            gather_buckets.setdefault(_gather_width(jnp.asarray(v).dtype), []).append(i)

    for (fx, _dtype), idxs in reduce_buckets.items():
        vals = [jnp.ravel(jnp.asarray(leaves[i][1])) for i in idxs]
        sizes = [v.size for v in vals]
        flat = jnp.concatenate(vals) if len(vals) > 1 else vals[0]
        synced = _REDUCE_COLLECTIVES[fx](flat, axis_name)
        off = 0
        for i, n in zip(idxs, sizes):
            piece = lax.slice(synced, (off,), (off + n,))
            out[i] = piece.reshape(jnp.shape(leaves[i][1]))
            off += n

    for width, idxs in gather_buckets.items():
        # gathers are layout-agnostic: leaves of one bit-width bitcast (free —
        # no copy, no value change) to a common unsigned carrier and move as
        # ONE all_gather; a psum needs arithmetic and stays per-dtype
        payloads = [_to_carrier(leaves[i][1]) for i in idxs]
        sizes = [p.size for p in payloads]
        flat = jnp.concatenate(payloads) if len(payloads) > 1 else payloads[0]
        gathered = lax.all_gather(flat, axis_name, tiled=False)  # (world, total)
        world = gathered.shape[0]
        off = 0
        for i, n in zip(idxs, sizes):
            fx, v = leaves[i]
            v = jnp.asarray(v)
            shape = v.shape
            raw = lax.slice(gathered, (0, off), (world, off + n))
            piece = _from_carrier(raw.reshape((world,) + shape), v.dtype)
            off += n
            if fx == "cat":
                out[i] = piece.reshape((world * shape[0],) + shape[1:])
            elif fx is None:
                out[i] = piece
            elif callable(fx):
                acc = piece[0]
                for w in range(1, world):
                    acc = fx(acc, piece[w])
                out[i] = acc
            else:
                raise ValueError(f"unknown dist_reduce_fx: {fx!r}")
    return out  # type: ignore[return-value]


_CARRIERS = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}


def _gather_width(dtype: Any) -> int:
    return 1 if dtype == jnp.bool_ else jnp.dtype(dtype).itemsize


def _to_carrier(v: Array) -> Array:
    """Ravel a leaf to the flat unsigned carrier of its own bit-width."""
    v = jnp.asarray(v)
    if v.dtype == jnp.bool_:
        return jnp.ravel(v.astype(jnp.uint8))
    carrier = _CARRIERS[jnp.dtype(v.dtype).itemsize]
    if v.dtype == carrier:
        return jnp.ravel(v)
    return jnp.ravel(lax.bitcast_convert_type(v, carrier))


def _from_carrier(raw: Array, dtype: Any) -> Array:
    """Inverse of ``_to_carrier`` (shape already restored by the caller)."""
    if dtype == jnp.bool_:
        return raw.astype(jnp.bool_)
    if raw.dtype == dtype:
        return raw
    return lax.bitcast_convert_type(raw, dtype)


def reduce(x: Array, reduction: str) -> Array:
    """Elementwise->scalar reduction. Parity: ``utilities/distributed.py:21-40``."""
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "none" or reduction is None:
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Class-averaged fraction num/denom with micro/macro/weighted/none reduction.

    Parity: ``utilities/distributed.py:43-87``.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / (jnp.sum(denom) + METRIC_EPS)
    else:
        fraction = num / (denom + METRIC_EPS)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction == "none" or class_reduction is None:
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between {valid_reduction}")
