"""Sharded embedded-model forward: batch-parallel encoder execution on a mesh.

The BASELINE configs that matter at scale run a *model* inside the metric —
BERTScore's BERT encoder and FID/IS/KID's InceptionV3 (reference
``torchmetrics/functional/text/bert.py:256-341`` drives its encoder through a
host DataLoader; ``torchmetrics/image/fid.py:250-262`` runs inception per
process and all_gathers feature lists at sync). The TPU-native shape of that
pattern is: params replicated, batch sharded over the mesh's data axis, one
``shard_map``-ed forward per step, features re-assembled as a global array
whose consumer triggers the all-gather (or, better, consumes them sharded —
FID's streaming statistics reduce over the batch, so XLA can turn the feature
gather into a reduction of per-shard partial statistics).

``shard_batch_forward`` wraps any per-batch callable (a flax apply, a jitted
encoder, a lambda) so it runs under ``shard_map`` over ``mesh``'s ``axis``:

* positional arguments are split along their leading (batch) dimension, except
  ``replicated_argnums`` (model params), which are broadcast to every device;
* a batch not divisible by the axis size is zero-padded to the next multiple
  and the pad rows are sliced off the output (pad rows never reach the caller);
* the output is a global array laid out batch-sharded over ``axis`` — consuming
  it replicated (e.g. ``np.asarray``) performs the feature all-gather, while a
  downstream jitted reduction keeps it distributed. ``out_axis=None`` forces an
  explicit in-graph ``all_gather`` instead.

Used by ``InceptionFeatureExtractor(mesh=...)`` and ``bert_score(mesh=...)``;
mesh-parity (sharded == single-device on the same corpus) is proven in
``tests/parallel/test_sharded_embedded.py``.
"""
from functools import partial
from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array

AxisName = Union[str, Tuple[str, ...]]


def _axis_size(mesh: Mesh, axis: AxisName) -> int:
    if isinstance(axis, (tuple, list)):
        n = 1
        for a in axis:
            n *= mesh.shape[a]
        return n
    return mesh.shape[axis]


def shard_batch_forward(
    fn: Callable,
    mesh: Mesh,
    axis: AxisName = "dp",
    out_axis: Optional[AxisName] = "__same__",
    replicated_argnums: Sequence[int] = (),
) -> Callable:
    """Return ``fn`` running batch-parallel under ``shard_map`` over ``mesh``.

    Args:
        fn: per-batch callable; every non-replicated positional arg has a
            leading batch dimension.
        mesh: the device mesh to run under.
        axis: mesh axis name (or tuple of names) carrying the batch shards.
        out_axis: partition of the output's leading dim. The default keeps the
            output batch-sharded over ``axis``; ``None`` performs an explicit
            in-graph ``all_gather`` so the result leaves replicated; an
            IN-ORDER PREFIX of ``axis`` (e.g. ``"dp"`` when
            ``axis=("dp", "grp")``) gathers the trailing axes in-graph and
            leaves the output sharded over just the prefix (non-prefix
            subsets would permute rows and are rejected).
        replicated_argnums: positions of args broadcast whole to every device
            (the params pytree of a flax encoder).

    The wrapped callable pads the batch to a multiple of the axis size with
    zeros and slices the pad rows off the result, so any batch size works.
    """
    n = _axis_size(mesh, axis)
    rep = frozenset(int(i) for i in replicated_argnums)
    axes = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)
    if out_axis is None:
        gather_axes: Tuple[str, ...] = axes          # full in-body gather
        spec_out = P()
    elif out_axis == "__same__":
        gather_axes = ()
        spec_out = P(axis)
    else:
        # output sharded over a PREFIX of the input axes: the leftover (minor)
        # axes' shards are gathered in-body. Only an in-order prefix keeps row
        # order coherent — shard_map splits the batch axes-major, and a tiled
        # gather over non-trailing axes would interleave rows while P(out_axis)
        # stitches them as contiguous blocks (silent permutation under
        # check_vma=False).
        out_axes = tuple(out_axis) if isinstance(out_axis, (tuple, list)) else (out_axis,)
        if out_axes != axes[: len(out_axes)]:
            raise ValueError(
                f"out_axis {out_axes} must be an in-order prefix of the batch axes "
                f"{axes} (anything else would permute output rows); gather fully "
                "with out_axis=None instead."
            )
        gather_axes = axes[len(out_axes):]
        spec_out = P(out_axis)

    def _body(*args):
        out = fn(*args)
        if gather_axes:
            out = jax.lax.all_gather(out, gather_axes, tiled=True)
        return out

    @jax.jit
    def _padded(*args):
        in_specs = tuple(P() if i in rep else P(axis) for i in range(len(args)))
        sharded = partial(
            jax.shard_map, mesh=mesh, in_specs=in_specs, out_specs=spec_out,
            check_vma=False,
        )(_body)
        batch_ix = [i for i in range(len(args)) if i not in rep]
        if not batch_ix:
            raise ValueError("shard_batch_forward needs at least one batch argument")
        b = args[batch_ix[0]].shape[0]
        pad = (-b) % n
        if pad:
            args = tuple(
                jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], axis=0)
                if i in batch_ix else a
                for i, a in enumerate(args)
            )
        out = sharded(*args)
        return out[:b] if pad else out

    # Virtual CPU meshes (the 8-device test topology) deadlock when two async
    # executions of a collective-bearing executable overlap: the in-process
    # communicator's rendezvous needs all per-device threads of ONE run live
    # at once, and the timeshared host can leave a run one thread short (hard
    # 40 s abort in xla::cpu::InProcessCommunicator). Serialize on CPU; real
    # TPU meshes keep fully async dispatch.
    if mesh.devices.flat[0].platform == "cpu":
        def _synced(*args):
            out = _padded(*args)
            jax.block_until_ready(out)
            return out

        _synced.lower = _padded.lower  # keep AOT introspection (tests read HLO)
        return _synced
    return _padded


def data_parallel_mesh(axis: str = "dp") -> Mesh:
    """A 1-D mesh over every local device — the default embedded-model layout."""
    import numpy as np

    return Mesh(np.asarray(jax.devices()), (axis,))


def sharded_masked_step(
    metric,
    mesh: Mesh,
    axis: AxisName,
    payload_abs,
    mask_abs,
    layout=None,
) -> Callable:
    """Build the STEP-SYNC mesh streaming-engine step for one bucket signature.

    Returns a ``shard_map``-wrapped pure function
    ``(state, payload, mask) -> (new_state, token)`` where ``payload`` is the
    ``(args, kwargs)`` pytree of one PADDED bucket batch:

    * batch-carried leaves (leading dim == ``mask_abs.shape[0]``) and the mask
      shard over ``axis``; config scalars and the state replicate;
    * each device computes its shard's masked delta
      (``Metric.update_state_masked``), the deltas psum/pmin/pmax-merge
      in-step (``sync_states`` — states the metric's ``sync_precision``
      policy declares ``"q8_block"`` ride the block-scaled int8 section of
      the fused bundle, per-STEP deltas, so the quantization bound grows
      with step count; deferred sync quantizes whole states at boundaries
      instead), and the replicated GLOBAL state comes back — so a snapshot
      between any two steps is globally consistent and compute needs no
      further sync;
    * ``token`` is the global valid-row count — a tiny non-donated output the
      dispatcher blocks on (the state itself is donated into the next step).

    With ``layout`` (an ``engine.arena.ArenaLayout``) the carried state is the
    PACKED per-dtype arena dict instead of the per-leaf pytree: the body
    unpacks it with static slices (free after XLA fusion), and the step's
    donated arguments drop to one buffer per dtype.

    The caller (``engine/pipeline.py``) jits, lowers and AOT-compiles this
    once per (bucket, mesh, dtype) — the serving-side closed-program contract.
    """
    from jax.sharding import PartitionSpec as P

    from metrics_tpu.utils.data import is_batch_leaf

    n_rows = mask_abs.shape[0]
    payload_specs = jax.tree.map(
        lambda s: P(axis) if is_batch_leaf(s, n_rows) else P(),
        payload_abs,
    )
    state_template = layout.abstract() if layout is not None else metric.abstract_state()
    state_specs = jax.tree.map(lambda _: P(), state_template)
    axis_tuple = tuple(axis) if isinstance(axis, (tuple, list)) else (axis,)

    def body(state, payload, mask):
        a, kw = payload
        delta = metric.update_state_masked(metric.init_state(), *a, mask=mask, **kw)
        delta = metric.sync_states(delta, axis)  # psum/pmin/pmax the shard deltas
        token = jax.lax.psum(jnp.sum(mask.astype(jnp.int32)), axis_tuple)
        carried = metric.merge_states(layout.unpack(state), delta) if layout is not None else metric.merge_states(state, delta)
        return (layout.pack(carried) if layout is not None else carried), token

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, payload_specs, P(axis)),
        out_specs=(state_specs, P()), check_vma=False,
    )


def sharded_local_step(
    update_fn: Callable,
    mesh: Mesh,
    axis: AxisName,
    payload_abs,
    mask_abs,
    state_template,
    unpack: Optional[Callable] = None,
    pack: Optional[Callable] = None,
) -> Callable:
    """Build the DEFERRED-SYNC (collective-free) mesh streaming-engine step.

    The reference's core contract is per-process LOCAL accumulation with a
    cross-process merge only at compute (``dist_reduce_fx``); this is its mesh
    form. The carried state is shard-local: every leaf/buffer gains a leading
    shard axis sharded over ``axis`` (row ``k`` = device ``k``'s local state),
    and the step body runs entirely within the shard —

    * batch rows and mask shard over ``axis`` exactly as in
      :func:`sharded_masked_step`;
    * each device applies ``update_fn`` (the engine's masked/segmented update
      on the LOGICAL state tree) to its own local state with its own rows —
      no psum, no gather: the steady-state jaxpr contains ZERO cross-chip
      collectives (pinned by ``tests/engine/test_deferred_fast.py``);
    * the merge moves to explicit boundaries (:func:`sharded_state_merge`),
      so scan-strategy metrics (``AUROC(capacity=N)``'s cat-written buffers)
      become servable on mesh: each shard folds its rows sequentially into its
      own buffers and the boundary merge all-gathers them.

    ``token`` is the per-shard valid-row count, returned sharded ``(world,)``
    — the dispatcher blocks on it to bound in-flight depth, same contract as
    the step-sync scalar token. ``unpack``/``pack`` convert between the
    carried per-shard form (an arena row) and the logical tree ``update_fn``
    expects; None when the engine runs without arenas.
    """
    from jax.sharding import PartitionSpec as P

    from metrics_tpu.utils.data import is_batch_leaf

    n_rows = mask_abs.shape[0]
    payload_specs = jax.tree.map(
        lambda s: P(axis) if is_batch_leaf(s, n_rows) else P(),
        payload_abs,
    )
    state_specs = jax.tree.map(lambda _: P(axis), state_template)

    def body(state, payload, mask):
        a, kw = payload
        local = jax.tree.map(lambda x: x[0], state)  # this device's (1, ...) row
        tree = unpack(local) if unpack is not None else local
        new_tree = update_fn(tree, (a, kw), mask)
        new_local = pack(new_tree) if pack is not None else new_tree
        token = jnp.reshape(jnp.sum(mask.astype(jnp.int32)), (1,))
        return jax.tree.map(lambda x: x[None], new_local), token

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, payload_specs, P(axis)),
        out_specs=(state_specs, P(axis)), check_vma=False,
    )


def stream_sharded_step(
    update_fn: Callable,
    mesh: Mesh,
    axis: AxisName,
    payload_abs,
    mask_abs,
    state_template,
    unpack: Optional[Callable] = None,
    pack: Optional[Callable] = None,
) -> Callable:
    """Build the STREAM-SHARDED routed step (ISSUE 9): the stream axis itself
    is sharded over the mesh — shard ``k`` carries ONLY its own streams'
    state, as ``(world, resident, n)`` per-dtype paged-arena buffers dim-0
    sharded over ``axis``.

    The routing contract is entirely HOST-SIDE (``engine/multistream.py``):
    the dispatcher orders each megabatch's rows by home shard
    (``stream_id % world``) and pads per-shard segments to ``bucket/world``
    rows, so under the same ``P(axis)`` batch sharding as every other engine
    step each device receives EXACTLY the rows addressed to its streams —
    with slot indices (LOCAL, pager-assigned) as the segment ids. The body is
    then the ordinary shard-local segmented update: no psum, no gather, no
    cross-shard addressing — the steady routed step carries ZERO collectives
    at jaxpr and HLO level, the same contract as :func:`sharded_local_step`
    (and pinned by the same ``no-collectives-in-deferred-step`` rule).

    Mechanically this IS :func:`sharded_local_step` — the per-device view of
    a ``(world, resident, n)`` buffer is a ``(resident, n)`` slot-stacked
    arena, and ``unpack``/``pack`` are the per-stream layout's
    ``unpack_stacked``/``pack_stacked``. The delegation is deliberate: one
    collective-free step builder, two carried-state shapes.
    """
    return sharded_local_step(
        update_fn, mesh, axis, payload_abs, mask_abs,
        state_template=state_template, unpack=unpack, pack=pack,
    )


def sharded_state_merge(
    metric,
    mesh: Mesh,
    axis: AxisName,
    state_template,
    unpack: Optional[Callable] = None,
) -> Callable:
    """Build the deferred-sync BOUNDARY merge: shard-local states -> global.

    Each device unpacks its own carried row to the logical state tree and the
    whole tree rides ``metric.sync_states`` — ONE fused collective bundle
    (``parallel/collectives.py::fused_axis_sync``: all sum counters share a
    single psum, min/max one collective per (reduction, dtype), cat/gather
    states one u32-carrier all_gather, and states under a ``"q8_block"``
    ``sync_precision`` policy ride that same carrier as block-scaled int8 —
    the merge acts on whole accumulated STATES, so the quantization bound
    never grows with step count) per merge, however many metrics the
    collection serves. The output is the replicated GLOBAL state in the
    metric's own layout — ``cat`` buffers arrive concatenated across shards
    (``dist_reduce_fx="cat"`` semantics), so ``compute_from`` needs no
    further sync. Runs only at explicit boundaries (``result()``, snapshot,
    cross-topology restore), never in the steady state.
    """
    from jax.sharding import PartitionSpec as P

    state_specs = jax.tree.map(lambda _: P(axis), state_template)

    def body(state):
        local = jax.tree.map(lambda x: x[0], state)
        tree = unpack(local) if unpack is not None else local
        return metric.sync_states(tree, axis)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(state_specs,), out_specs=P(), check_vma=False
    )


def stem_tensor_batch_forward(
    stem_fn: Callable,
    trunk_fn: Callable,
    mesh: Mesh,
    axis: AxisName = "dp",
) -> Callable:
    """Hybrid tensor→data sharded embedded forward — the model host's
    Inception layout (ROADMAP item 2 / ISSUE 19).

    Stage 1, tensor-parallel stem: the image batch is REPLICATED to every
    device; the stem params enter channel-sharded (every leaf split on its
    LAST dim — conv kernels ``(kh, kw, cin, cout)`` on ``cout``, BN vectors
    ``(c,)`` on the channel dim), so each device computes a channel slice of
    every stem layer and ``stem_fn`` restores full channels with a tiled
    ``all_gather`` per layer. This is where PR 1's ``pad_stem_params`` 128-lane
    layout pays twice: the padded stem widths (128/128/128/128/192) divide
    evenly over the axis, and each device's slice still presents full MXU
    lanes.

    Stage 2, data-parallel trunk: each device slices its own batch shard of
    the post-stem activation (``axis_index``) and runs ``trunk_fn`` on it;
    the per-row outputs ``all_gather`` back to replicated.

    ``stem_fn(stem_vars_local, x, gather_axis) -> (x_stem, aux)`` — e.g.
    ``models.inception.stem_apply`` (``aux`` = the '64'/'192' taps, computed
    full-batch, already replicated). ``trunk_fn(trunk_vars, x_local) -> dict``
    of per-row outputs (leading batch dim). The returned
    ``fwd(stem_vars, trunk_vars, x)`` requires the batch divisible by the
    axis size (the host's bucket divisor guarantees it) and emits
    ``all_gather`` as its only collective.
    """
    world = _axis_size(mesh, axis)

    def _stem_spec(leaf):
        nd = getattr(leaf, "ndim", 0)
        if not nd:
            return P()
        return P(*([None] * (nd - 1) + [axis]))

    def body(stem_vars, trunk_vars, x):
        x_stem, aux = stem_fn(stem_vars, x, axis)
        b = x.shape[0] // world
        k = jax.lax.axis_index(axis)
        x_local = jax.lax.dynamic_slice_in_dim(x_stem, k * b, b, axis=0)
        out = trunk_fn(trunk_vars, x_local)
        out = jax.tree.map(
            lambda o: jax.lax.all_gather(o, axis, axis=0, tiled=True), out
        )
        out.update(aux)
        return out

    def fwd(stem_vars, trunk_vars, x):
        if x.shape[0] % world:
            raise ValueError(
                f"stem_tensor_batch_forward: batch {x.shape[0]} not divisible by "
                f"axis {axis!r} size {world} — serve it through a bucket set with "
                f"divisor={world}"
            )
        stem_specs = jax.tree.map(_stem_spec, stem_vars)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(stem_specs, P(), P()), out_specs=P(), check_vma=False,
        )(stem_vars, trunk_vars, x)

    return fwd


def pipeline_stage_forward(
    stage_fn: Callable,
    mesh: Mesh,
    axis: AxisName = "dp",
    microbatches: Optional[int] = None,
) -> Callable:
    """GPipe-style pipeline-parallel embedded forward — the model host's
    encoder layout, per the MPMD pipeline-parallelism paper (PAPERS.md).

    Stage ``s``'s params live ONLY on device ``s``: the stage pytree is
    stacked ``(S, ...)`` and dim-0-sharded over ``axis`` (one row per device),
    and activations hand off stage-to-stage with ``ppermute`` ring rotations —
    the ONLY collective this program ever emits (pinned by the
    ``host-collectives-pinned`` analysis rule).

    Schedule: the batch splits into ``M`` microbatches (default ``M = world``);
    the loop runs ``S + M - 1`` steps, device ``s`` processing microbatch
    ``t - s`` at step ``t`` (junk outside the valid window, masked from the
    output). The last stage's output buffer is then ring-rotated ``S - 1``
    steps so every device holds it — still ppermute-only — and the result
    leaves replicated.

    ``stage_fn(stage_params, x_mb) -> x_mb`` must preserve the microbatch
    shape (a residual-style encoder stage). The returned ``fwd(params, x)``
    requires ``x.shape[0]`` divisible by ``M``.
    """
    world = _axis_size(mesh, axis)
    perm = [(i, (i + 1) % world) for i in range(world)]

    def body(params, x):
        p = jax.tree.map(lambda a: a[0], params)  # this device's stage row
        s = jax.lax.axis_index(axis)
        m = microbatches or world
        mb = x.shape[0] // m

        def step(t, carry):
            state, out = carry
            feed = jax.lax.dynamic_slice_in_dim(
                x, jnp.clip(t, 0, m - 1) * mb, mb, axis=0
            )
            state = jnp.where((s == 0) & (t < m), feed, state)
            state = stage_fn(p, state)
            idx = t - (world - 1)
            emitted = jax.lax.dynamic_update_slice_in_dim(
                out, state, jnp.clip(idx, 0, m - 1) * mb, axis=0
            )
            out = jnp.where((s == world - 1) & (idx >= 0), emitted, out)
            state = jax.lax.ppermute(state, axis, perm)
            return state, out

        state0 = jnp.zeros((mb,) + x.shape[1:], x.dtype)
        _, out = jax.lax.fori_loop(
            0, m + world - 1, step, (state0, jnp.zeros_like(x))
        )
        # replicate the last stage's buffer with a ring broadcast: after k
        # rotations device d holds device (d - k) % world's buffer, so each
        # device latches the rotation where that source is the last stage
        result = jnp.where(s == world - 1, out, jnp.zeros_like(out))
        cur = out
        for k in range(1, world):
            cur = jax.lax.ppermute(cur, axis, perm)
            result = jnp.where((s - k) % world == world - 1, cur, result)
        return result

    def fwd(params, x):
        m = microbatches or world
        if x.shape[0] % m:
            raise ValueError(
                f"pipeline_stage_forward: batch {x.shape[0]} not divisible by "
                f"microbatch count {m} — serve it through a bucket set with "
                f"divisor={m}"
            )
        stage_specs = jax.tree.map(lambda _: P(axis), params)
        return jax.shard_map(
            body, mesh=mesh,
            in_specs=(stage_specs, P()), out_specs=P(), check_vma=False,
        )(params, x)

    return fwd


def boundary_merge_error(axis: AxisName, world: int, cause: BaseException) -> Exception:
    """Build the typed error for a failed deferred boundary merge, carrying
    the mesh topology an operator needs (axis, world size) — the engine
    chains ``cause`` onto it (``raise ... from cause``).

    The merge is a non-donated READ of the shard-local carried state, so any
    failure — injected, runtime, or collective — leaves the accumulation
    fully intact: the caller's next ``result()``/``state()`` serves the last
    consistent value. User errors pass through untouched (they are input
    properties, not merge failures).
    """
    from metrics_tpu.engine.faults import BoundaryMergeError
    from metrics_tpu.utils.exceptions import MetricsTPUUserError

    if isinstance(cause, (BoundaryMergeError, MetricsTPUUserError)):
        return cause
    return BoundaryMergeError(
        f"deferred boundary merge failed over mesh axis {axis!r} (world={world}): "
        f"{type(cause).__name__}: {cause}; the shard-local carried state is intact — "
        "result()/state() keep serving the last consistent value"
    )
