"""Distributed communication backend (L0) — JAX collectives over ICI/DCN mesh axes.

Replaces the reference's ``torch.distributed`` layer
(``torchmetrics/utilities/distributed.py``): instead of NCCL all_gather + barrier per
state tensor, state merge lowers to ``jax.lax.psum``/``pmin``/``pmax``/``all_gather``
inside the caller's ``shard_map``/``pjit`` region, and a MetricCollection syncs all its
counter states in ONE fused bundle.
"""
from metrics_tpu.parallel.collectives import (
    all_gather_cat,
    all_gather_stack,
    axis_size_or_one,
    fused_axis_sync,
    in_mapped_context,
    reduce,
    class_reduce,
    sync_axis_state,
)
from metrics_tpu.parallel.embedded import (
    data_parallel_mesh,
    shard_batch_forward,
    sharded_masked_step,
)
from metrics_tpu.parallel.mesh import (
    MeshConfig,
    current_metric_axis,
    metric_axis,
    set_metric_axis,
)

__all__ = [
    "MeshConfig",
    "all_gather_cat",
    "all_gather_stack",
    "axis_size_or_one",
    "class_reduce",
    "current_metric_axis",
    "data_parallel_mesh",
    "fused_axis_sync",
    "in_mapped_context",
    "metric_axis",
    "reduce",
    "set_metric_axis",
    "shard_batch_forward",
    "sharded_masked_step",
    "sync_axis_state",
]
