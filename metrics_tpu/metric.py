"""Metric runtime (L1): state registry, update/compute/reset protocol, axis sync.

Parity: reference ``torchmetrics/metric.py`` (Metric ABC: add_state :123, forward :192,
_sync_dist :232, sync/unsync/sync_context :268-358, _wrap_compute :360, reset :397,
state_dict :514, _filter_kwargs :554, operator overloads :595-698; CompositionalMetric
:705-815).

TPU-native redesign (SURVEY.md §7.1): a metric is fundamentally a **pytree state plus
pure functions** —

    state = m.init_state()                       # dict pytree of jnp arrays
    state = m.update_state(state, preds, target) # pure, jit/scan-safe
    value = m.compute_synced(state)              # pure; psum/all_gather over mesh axis
    state = m.merge_states(a, b)                 # pure pairwise merge

The familiar stateful facade (``m.update(...)``, ``m.compute()``, ``m.reset()``) is a
thin shell over those functions, so the same subclass definition (attribute-mutating
``update`` + ``compute``, exactly like the reference) serves both the eager API and the
compiled path. ``update_state`` works by temporarily loading the state pytree into the
instance attributes, running the subclass ``update`` under the current trace, and
snapshotting the attributes back — the stateful-looking subclass code *is* the pure
function body.

Key differences from the reference, by design:
  * ``forward`` computes the batch value from the **state delta** (one ``update`` per
    step, not two — reference ``metric.py:206,218`` runs update twice).
  * sync needs no barrier and no shape-gather (static shapes under XLA) — reference
    ``utilities/distributed.py:116-145``.
  * sync/unsync exist for API parity and the eager multi-host path, but in-trace sync
    is just a pure function application; local state is never overwritten.
"""
import functools
import inspect
import weakref
from copy import deepcopy
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops.kernels import (
    fold_rows_masked,
    reduce_identity as _reduce_identity,
    segment_reduce_masked,
    stack_reduce as _stack_reduce,
)
from metrics_tpu.parallel.collectives import (
    AxisSpec,
    SYNC_PRECISIONS,
    _sum_rider,
    axis_size_or_one,
    fused_axis_sync,
    in_mapped_context,
    q8_sum_error_bound,
    sync_axis_state,
)
from metrics_tpu.parallel.mesh import current_metric_axis
from metrics_tpu.utils.checks import deferred_message, deferred_value_checks
from metrics_tpu.utils.data import apply_to_collection, dim_zero_cat, is_batch_leaf
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array

_MERGEABLE_FX = ("sum", "min", "max", "cat")


@dataclass(frozen=True)
class GroupedField:
    """One per-row payload field of a group-keyed (ragged) metric.

    A grouped metric's unit of ingestion is a ROW tagged with a group key
    (retrieval: one ``(pred, target)`` document row keyed by query id;
    detection: one box row keyed by image id). ``shape`` is the per-row
    trailing shape (``()`` for scalars, ``(4,)`` for boxes); ``dtype`` is the
    buffered storage dtype. The ragged engine stores each field as a
    ``(capacity,) + shape`` buffer per group, rows valid up to the group's
    count."""

    name: str
    shape: Tuple[int, ...]
    dtype: Any


@dataclass(frozen=True)
class GroupedUpdateSpec:
    """Declaration a metric makes to serve through the ragged path
    (``metrics_tpu.engine.ragged.RaggedEngine``).

    ``fields`` lists the per-row payloads in the positional order
    :meth:`Metric.grouped_encode` emits them; ``capacity`` is the per-group
    row budget (AUROC cat-capacity precedent: rows past capacity overflow
    loudly rather than silently truncate). A metric returning a spec from
    :meth:`Metric.grouped_update_strategy <Metric.grouped_update_spec>` also
    implements:

    * ``grouped_encode(*update_args, **update_kwargs)`` ->
      ``(group_ids, field_0, ..., field_{k-1})`` — validate exactly like
      ``update`` and flatten the eager call into per-row arrays;
    * ``grouped_group_value(fields, count, capacity)`` — traced per-group
      compute over one group's ``(capacity, ...)`` buffers + valid count
      (what ``result(group_id)`` returns);
    * ``grouped_finalize(counts, fields, group_ids)`` — rebuild the metric's
      EAGER state pytree from the reconstructed per-group rows (host-side;
      the aggregate ``result()`` feeds it through ``compute_from`` so the
      served value is bit-exact vs the eager oracle).
    """

    fields: Tuple[GroupedField, ...]
    capacity: int

    def field_names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)


@dataclass(frozen=True)
class GroupedAggregateSpec:
    """Declaration that a grouped metric's AGGREGATE (the corpus-level
    ``result()``) can be computed as a device program instead of the host
    eager replay.

    ``kind`` selects the engine's device aggregate shape:

    * ``"fold"`` — the aggregate is a masked mean/sum of independent
      per-group scores.  The metric implements
      ``grouped_batch_scores(counts, fields, capacity)`` (traced, batched
      over the ``(G, capacity, ...)`` buffers, returning per-group
      ``{"value", "keep", "flag"}`` vectors) and
      ``grouped_aggregate_finish(value, kept, flagged)`` (host-side: raise
      deferred value errors / return the scalar).  The engine folds the
      scores with the masked row kernels so only one scalar bundle leaves
      the device.
    * ``"corpus"`` — the aggregate needs a corpus-level pass that is not a
      per-group mean (detection's PR curve).  The metric implements the
      ``grouped_corpus_*`` hook family (plan → device bundle → host
      finish); per-group match matrices run on device, only the final
      curve interpolation runs on host.
    """

    kind: str  # "fold" | "corpus"

# forward() auto-jit cache: instance -> {signature: compiled step | _EAGER_ONLY}.
# Keyed by weakref so compiled handles never interfere with pickling, deepcopy
# (clone()) or garbage collection of the metric itself.
_FORWARD_JIT_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_EAGER_ONLY = object()  # sentinel: this signature can't trace — stay eager forever
_PENDING = object()  # sentinel: first call seen eagerly; compile on the next one
_MISS = object()  # sentinel: fast path not taken this call


def _jit_cache_lookup(owner: Any, sig: Any, builder: Callable):
    """The per-signature compile protocol shared by ``Metric._forward_fast`` and
    ``MetricCollection._forward_fused``: 1st call registers _PENDING (caller runs
    eager validation), 2nd call invokes ``builder`` to compile, later calls reuse.

    Returns ``(entry, cache)`` — entry is None when the caller must stay eager
    this call (miss, pending-just-registered, eager-only, or cache full).
    """
    cache = _FORWARD_JIT_CACHE.get(owner)
    if cache is None:
        cache = {}
        try:
            _FORWARD_JIT_CACHE[owner] = cache
        except TypeError:  # owner not weakref-able
            return None, None
    entry = cache.get(sig)
    if entry is _EAGER_ONLY:
        return None, cache
    if entry is None:
        if len(cache) < Metric._FORWARD_JIT_MAX_SIGNATURES:
            cache[sig] = _PENDING
        return None, cache
    if entry is _PENDING:
        entry = builder()
        cache[sig] = entry
    return entry, cache


def _squeeze_if_scalar(x: Any) -> Any:
    """0-d-ify single-element arrays, mirroring reference ``metric.py:382``."""

    def _sq(v):
        if isinstance(v, jax.Array) and v.size == 1 and v.ndim > 0:
            return jnp.squeeze(v)
        return v

    return apply_to_collection(x, jax.Array, _sq)


def sync_precision_tag_of(precisions: Dict[str, str]) -> str:
    """THE canonical AOT-key tag of a sync-precision map (``"exact"`` or
    ``"q8:<digest>"`` over the sorted quantized paths) — one implementation
    shared by ``Metric`` and ``MetricCollection``, so the two can never
    drift on what a policy's program-key component looks like."""
    quantized = sorted(f"{k}={v}" for k, v in precisions.items() if v != "exact")
    if not quantized:
        return "exact"
    import hashlib

    return "q8:" + hashlib.sha256(";".join(quantized).encode()).hexdigest()[:10]


def distributed_available() -> bool:
    """True when metric state can differ across participants.

    Parity: reference ``metric.py:42-43``. In JAX this means either a bound mesh axis
    (in-trace) or a multi-process runtime (eager).
    """
    return jax.process_count() > 1


class Metric:
    """Base class for all metrics.

    Subclasses implement ``update(self, ...)`` (mutating registered state attributes)
    and ``compute(self)`` (reading them), exactly like the reference. States are
    registered with :meth:`add_state`.

    Compiled forward: after one eager warm-up call per input signature,
    ``forward`` runs the whole update→merge→compute(delta) step as a single XLA
    executable. The warm-up call validates input VALUES eagerly; afterwards the
    same checks run in-graph and raise deferred — at the next ``compute()``/
    ``sync()``, stickily until ``reset()``. Updates that cannot trace (host-side
    string/detection work, data-dependent control flow) fall back to the eager
    path permanently for that signature; metrics whose eager semantics must see
    every concrete batch (e.g. aggregators with ``nan_strategy='error'``)
    opt out via ``_forward_jit_safe``.

    Args:
        compute_on_step: return the metric value for the current batch from ``forward``.
        dist_sync_on_step: synchronise state across the mesh axis every ``forward``.
        sync_axis: named mesh axis to reduce over when called inside
            ``shard_map``/``pmap`` (the ``process_group`` analogue). If None, the
            ambient axis from ``metrics_tpu.parallel.metric_axis`` is used.
        dist_sync_fn: override for the leaf-sync function, signature
            ``(reduce_fx, value, axis_name) -> value``. Defaults to XLA collectives.
        sync_precision: per-metric quantized-sync policy (ISSUE 10, default
            exact — nothing quantizes silently). ``"q8_block"`` lets every
            ELIGIBLE state (float ``dist_reduce_fx="sum"`` accumulators —
            Gram/cov/sum matrices) ride the block-scaled int8 collective
            rider; counts, cat buffers and min/max states always stay
            bit-exact. A ``{state_name: precision}`` dict targets states
            explicitly and RAISES on ineligible ones. Also settable after
            construction via :meth:`set_sync_precision` (the only route for
            subclasses that don't forward the kwarg). Part of every engine
            AOT program key and of :func:`~metrics_tpu.engine.aot.
            metric_fingerprint` — two engines with different policies never
            exchange executables.
    """

    __jit_unsafe_attributes__ = ()
    is_differentiable: Optional[bool] = None
    higher_is_better: Optional[bool] = None
    full_state_update: Optional[bool] = None

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        sync_axis: Optional[str] = None,
        dist_sync_fn: Optional[Callable] = None,
        process_group: Optional[str] = None,
        sync_precision: Optional[Union[str, Dict[str, str]]] = None,
        **kwargs: Any,
    ) -> None:
        if kwargs:
            raise ValueError(f"Unexpected keyword arguments: {sorted(kwargs)}")
        if sync_axis is None and isinstance(process_group, str):
            sync_axis = process_group  # reference's process_group ≙ a named mesh axis
        self.compute_on_step = compute_on_step
        self.dist_sync_on_step = dist_sync_on_step
        self.sync_axis = sync_axis
        self.dist_sync_fn = dist_sync_fn

        self._defaults: Dict[str, Any] = {}
        self._persistent: Dict[str, bool] = {}
        self._reductions: Dict[str, Any] = {}
        # per-state sync precision (absent key = "exact"). The constructor
        # spec is applied by add_state as states register (subclass __init__
        # runs add_state AFTER super().__init__), so a blanket "q8_block"
        # catches every eligible state and a dict validates per name.
        self._sync_precision: Dict[str, str] = {}
        self._sync_precision_spec = self._check_sync_precision_spec(sync_precision)

        self._update_called = False
        self._computed: Any = None
        self._forward_cache: Any = None
        self._is_synced = False
        self._cache: Optional[Dict[str, Any]] = None
        self._to_sync = True
        self._should_unsync = True
        self._deferred_errcode: Any = None  # in-graph validation code from compiled forward

        # wrap the subclass methods once per instance (reference metric.py:102-103)
        self.update: Callable = self._wrap_update(self.update)  # type: ignore[method-assign]
        self.compute: Callable = self._wrap_compute(self.compute)  # type: ignore[method-assign]

    # ------------------------------------------------------------------ state registry

    def add_state(
        self,
        name: str,
        default: Any,
        dist_reduce_fx: Optional[Union[str, Callable]] = None,
        persistent: bool = False,
    ) -> None:
        """Register a named state. Parity: reference ``metric.py:123-190``.

        ``default`` is a jnp array (fixed-shape state) or an empty list (list state,
        the "cat"/gather pattern). ``dist_reduce_fx`` in {"sum","mean","min","max",
        "cat", None, callable}.
        """
        if name == self._CHILD_KEY:
            raise ValueError(f"state name {self._CHILD_KEY!r} is reserved for nested metric states")
        if not isinstance(default, (jax.Array, np.ndarray, list)) or (
            isinstance(default, list) and default
        ):
            raise ValueError("state variable must be an array or an empty list (where you can append arrays)")
        if isinstance(default, str) or not (
            dist_reduce_fx in ("sum", "mean", "min", "max", "cat", None) or callable(dist_reduce_fx)
        ):
            raise ValueError("`dist_reduce_fx` must be callable or one of ['mean', 'sum', 'cat', 'min', 'max', None]")
        if isinstance(default, np.ndarray):
            default = jnp.asarray(default)
        self._defaults[name] = default if isinstance(default, jax.Array) else list(default)
        self._persistent[name] = persistent
        self._reductions[name] = dist_reduce_fx
        setattr(self, name, default if isinstance(default, jax.Array) else list(default))
        spec = self._sync_precision_spec
        if isinstance(spec, str):
            # blanket policy: quantize what is eligible, leave the rest exact
            if spec != "exact" and self._sync_precision_ineligible_reason(name) is None:
                self._sync_precision[name] = spec
        elif isinstance(spec, dict) and name in spec:
            self._set_state_precision(name, spec[name])

    # ------------------------------------------------------- sync precision policy

    @staticmethod
    def _check_sync_precision_spec(spec: Any) -> Any:
        if spec is None or isinstance(spec, dict):
            return spec
        if isinstance(spec, str):
            if spec not in SYNC_PRECISIONS:
                raise ValueError(
                    f"unknown sync_precision {spec!r}; expected one of {SYNC_PRECISIONS}"
                )
            return spec
        raise ValueError(
            f"sync_precision must be a string or a {{state: precision}} dict, got {type(spec).__name__}"
        )

    def _sync_precision_ineligible_reason(self, name: str) -> Optional[str]:
        """None when state ``name`` may ride a quantized payload: a
        fixed-shape float ``dist_reduce_fx="sum"`` accumulator. Everything
        else must stay exact — counts are bit-exactness contracts, cat/None
        buffers carry values compute consumes verbatim, and min/max have no
        bounded-error quantized combine."""
        if name not in self._defaults:
            return f"no registered state named {name!r}"
        if isinstance(self._defaults[name], list):
            return "list (cat/gather) states must stay exact"
        fx = self._reductions[name]
        if fx != "sum":
            return f"dist_reduce_fx={fx!r} states must stay exact (only float 'sum' accumulators quantize)"
        if _sum_rider(jnp.asarray(self._defaults[name]).dtype) != "float":
            return "integer/count states must stay exact (they keep the bit-exact digit rider)"
        return None

    def _set_state_precision(self, name: str, prec: str) -> None:
        if prec not in SYNC_PRECISIONS:
            raise ValueError(
                f"unknown sync_precision {prec!r}; expected one of {SYNC_PRECISIONS}"
            )
        if prec == "exact":
            self._sync_precision.pop(name, None)
            return
        reason = self._sync_precision_ineligible_reason(name)
        if reason is not None:
            raise MetricsTPUUserError(
                f"state {name!r} of {type(self).__name__} cannot ride a quantized sync: {reason}"
            )
        self._sync_precision[name] = prec

    def set_sync_precision(self, spec: Union[str, Dict[str, str]]) -> "Metric":
        """Declare which states tolerate quantized sync (chainable).

        A blanket string (``"q8_block"``) applies to every ELIGIBLE state —
        float ``sum`` accumulators — on this metric AND its nested children,
        leaving counts/cat/min-max states exact; ``"exact"`` clears the
        policy everywhere. A ``{state_name: precision}`` dict targets this
        metric's own states and raises on ineligible ones. The policy is a
        trace constant: it changes the metric fingerprint and every engine
        AOT program key, so reconfiguring it never reuses stale executables.
        """
        spec = self._check_sync_precision_spec(spec)
        if spec is None:
            return self
        if isinstance(spec, str):
            for name in self._defaults:
                if spec == "exact":
                    self._sync_precision.pop(name, None)
                elif self._sync_precision_ineligible_reason(name) is None:
                    self._sync_precision[name] = spec
            self._for_each_child(lambda c: c.set_sync_precision(spec))
        else:
            for name, prec in spec.items():
                self._set_state_precision(name, prec)
        return self

    def _check_spec_consumed(self) -> None:
        """A constructor ``sync_precision`` DICT entry is applied as its
        state registers (``add_state``); once the policy is actually read, a
        key that never matched a registered state is a typo the contract
        says must RAISE — silently staying exact would look like a missing
        payload win, not an error."""
        spec = self._sync_precision_spec
        if isinstance(spec, dict):
            unknown = sorted(k for k in spec if k not in self._defaults)
            if unknown:
                raise MetricsTPUUserError(
                    f"sync_precision names states {type(self).__name__} never "
                    f"registered: {unknown} (registered: {sorted(self._defaults)})"
                )

    def state_sync_precisions(self) -> Dict[str, str]:
        """Flat ``{state_path: precision}`` for self and nested metrics
        (every registered state appears; default ``"exact"``)."""
        self._check_spec_consumed()
        out = {k: self._sync_precision.get(k, "exact") for k in self._defaults}
        for name, child in self._child_metrics().items():
            children = child if isinstance(child, list) else None
            if children is not None:
                for i, c in enumerate(children):
                    for k, v in c.state_sync_precisions().items():
                        out[f"{name}[{i}].{k}"] = v
            else:
                for k, v in child.state_sync_precisions().items():
                    out[f"{name}.{k}"] = v
        return out

    def sync_precision_tag(self) -> str:
        """Canonical short form of the policy for AOT program keys:
        ``"exact"`` when nothing quantizes, else ``"q8:<digest>"`` over the
        sorted quantized state paths — engines fold this into every program
        key so policies sharing one AotCache never exchange executables."""
        return sync_precision_tag_of(self.state_sync_precisions())

    def sync_leaf_info(self) -> List[Any]:
        """``(dist_reduce_fx, abstract_leaf, precision)`` per fixed-shape
        state leaf, in :meth:`sync_states` order (children appended) — the
        input of ``parallel/collectives.py::fused_sync_plan`` /
        ``sync_payload_bytes`` and of the ``quantized-sync-policy-honored``
        analysis rule. List (dynamic cat) states are skipped: their payload
        is data-dependent and no engine-served metric carries one."""
        abs_state = self.abstract_state()
        out: List[Any] = []
        for k in self._defaults:
            if isinstance(self._defaults[k], list):
                continue
            out.append((self._reductions[k], abs_state[k], self._sync_precision.get(k, "exact")))
        for child in self._child_metrics().values():
            children = child if isinstance(child, list) else [child]
            for c in children:
                out.extend(c.sync_leaf_info())
        return out

    def sync_error_bounds(self, stacked: Dict[str, Any]) -> Dict[str, Any]:
        """Per-element |error| bounds of a quantized sync/merge of ``stacked``
        (a shard-STACKED state pytree, leading axis = shard) vs the exact
        path — one entry per quantized state path, from the codec's declared
        bound (``q8_sum_error_bound``). THE per-metric bounded-error oracle
        the quantized gates (fuzz suite, ``make quant-smoke``) assert with;
        exact states never appear (they are byte-identical by contract)."""
        out: Dict[str, Any] = {}
        for k in self._defaults:
            if self._sync_precision.get(k, "exact") == "q8_block":
                out[k] = q8_sum_error_bound(np.asarray(stacked[k]))
        for name, child in self._child_metrics().items():
            children = child if isinstance(child, list) else None
            sub = stacked.get(self._CHILD_KEY, {}) if isinstance(stacked, dict) else {}
            if children is not None:
                for i, c in enumerate(children):
                    for k, v in c.sync_error_bounds(sub.get(name, [{}] * len(children))[i]).items():
                        out[f"{name}[{i}].{k}"] = v
            else:
                for k, v in child.sync_error_bounds(sub.get(name, {})).items():
                    out[f"{name}.{k}"] = v
        return out

    # ------------------------------------------------------------- functional core API

    _CHILD_KEY = "_children"

    def _child_metrics(self) -> Dict[str, Any]:
        """Child Metric instances held as attributes (wrapper/compositional
        metrics): name -> Metric, or name -> list of Metrics. The functional
        core recurses through these so ``init_state``/``update_state``/
        ``sync_states`` cover the FULL state of nested metrics — a MinMax or
        Multioutput wrapper's data lives in its inner metrics."""
        out: Dict[str, Any] = {}
        for name in sorted(self.__dict__):
            if name in self._defaults or name.startswith("__"):
                continue
            v = self.__dict__[name]
            if isinstance(v, Metric):
                out[name] = v
            elif isinstance(v, (list, tuple)) and v and all(isinstance(x, Metric) for x in v):
                out[name] = list(v)
        return out

    def init_state(self) -> Dict[str, Any]:
        """Fresh state pytree (a dict: name -> array or list of arrays; nested
        metrics appear under the reserved '_children' key).

        Leaves are COPIES: two states sharing a zeros-default must not alias the
        same buffer, or a jit step with donated state fails with
        "attempt to donate the same buffer twice".
        """
        state = {
            k: (jnp.array(v) if isinstance(v, jax.Array) else list(v))
            for k, v in self._defaults.items()
        }
        children = self._child_metrics()
        if children:
            state[self._CHILD_KEY] = {
                name: ([c.init_state() for c in child] if isinstance(child, list) else child.init_state())
                for name, child in children.items()
            }
        return state

    def _pack_state(self) -> Dict[str, Any]:
        state = {k: getattr(self, k) for k in self._defaults}
        children = self._child_metrics()
        if children:
            state[self._CHILD_KEY] = {
                name: ([c._pack_state() for c in child] if isinstance(child, list) else child._pack_state())
                for name, child in children.items()
            }
        return state

    def _load_state(self, state: Dict[str, Any]) -> None:
        children = self._child_metrics()
        for k, v in state.items():
            if k == self._CHILD_KEY:
                for name, child_state in v.items():
                    child = children.get(name)
                    if child is None:
                        continue
                    if isinstance(child, list):
                        for c, cs in zip(child, child_state):
                            c._load_state(cs)
                    else:
                        child._load_state(child_state)
                continue
            # list states copy shallowly; array-likes (jax, numpy — e.g. from
            # jax.device_get or a checkpoint) pass through as-is
            setattr(self, k, list(v) if isinstance(v, (list, tuple)) else v)

    _BOOKKEEPING_ATTRS = ("_computed", "_update_called", "_forward_cache")

    def _snapshot_bookkeeping(self) -> Dict[int, Dict[str, Any]]:
        """Snapshot host-side caches of self + all descendants so the pure API
        can restore them: a child's WRAPPED ``compute`` caches ``_computed``,
        and under a trace that cache would be a leaked tracer."""
        snap: Dict[int, Dict[str, Any]] = {}

        def visit(m: "Metric") -> None:
            snap[id(m)] = {a: getattr(m, a, None) for a in self._BOOKKEEPING_ATTRS}
            # unregistered mutable extras (e.g. MinMax's running extremes if a
            # subclass keeps any) are the subclass's responsibility: register
            # them with add_state so they travel/restore with the state pytree
            m._for_each_child(visit)

        visit(self)
        return snap

    def _restore_bookkeeping(self, snap: Dict[int, Dict[str, Any]]) -> None:
        def visit(m: "Metric") -> None:
            vals = snap.get(id(m))
            if vals is not None:
                for a, v in vals.items():
                    object.__setattr__(m, a, v)
            m._for_each_child(visit)

        visit(self)

    def _mark_updated(self) -> None:
        """Set post-update bookkeeping on self AND nested metrics — a wrapper's
        forward accumulates its children's state too, so their compute() must
        not warn about a missing update."""
        self._computed = None
        self._update_called = True
        self._for_each_child(lambda c: c._mark_updated())

    def update_state(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Pure update: ``new_state = f(state, batch)``. Safe inside jit/scan/shard_map.

        Runs the subclass ``update`` body with ``state`` loaded into the instance, then
        snapshots the result; REGISTERED state (incl. nested metrics') and the
        host-side bookkeeping caches are restored afterwards. Host-derived
        compute attributes (``_host_derived_compute_attrs``, e.g.
        ``Accuracy.mode``) deliberately KEEP whatever the update body latched —
        they are data-derived trace constants, and the streaming engine's
        first-batch latch (``engine/pipeline.py::_latch_host_attrs``) depends
        on this side effect to fold them into program identities. Do not add
        them to ``_BOOKKEEPING_ATTRS``.
        """
        saved = self._pack_state()
        book = self._snapshot_bookkeeping()
        self._load_state(state)
        try:
            self._inner_update(*args, **kwargs)
            return self._pack_state()
        finally:
            self._load_state(saved)
            self._restore_bookkeeping(book)

    def abstract_state(self) -> Dict[str, Any]:
        """``ShapeDtypeStruct`` pytree mirroring :meth:`init_state` — the lowering
        template for external AOT compilation (``metrics_tpu.engine``). No device
        buffers are materialised."""
        return jax.eval_shape(self.init_state)

    _MASKED_FX = ("sum", "min", "max")

    def masked_update_strategy(self) -> Optional[str]:
        """How :meth:`update_state_masked` will run for this metric:

        * ``"custom"`` — the subclass overrides it (fused masked form);
        * ``"delta"`` — the generic vmapped row-delta path (states reduce with
          sum/min/max, whose identity elements make pad rows inert);
        * ``"scan"`` — the sequential fold fallback: array states with
          reductions that have NO row-neutral identity (e.g. the static-
          capacity curve buffers' ``cat`` writes) fold row-by-row through the
          subclass ``update`` under ``lax.scan``, masked rows carrying the
          state through unchanged. Exact whenever a batch update equals the
          same rows applied one at a time — true for every array-state metric
          here — at the cost of serializing the rows;
        * ``"grouped"`` — the metric declares GROUP-KEYED state
          (:meth:`grouped_update_spec`): rows only mean anything relative to
          their group key (query id, image id) and the compute sorts/matches
          within each group, so there is no per-batch masked fold at all —
          the metric serves through the ragged engine
          (``metrics_tpu.engine.ragged.RaggedEngine``), which buffers rows
          per group under capacity semantics;
        * ``None`` — not maskable (list states grow with data;
          ``full_state_update`` reads the accumulated state per batch).
        """
        if type(self).update_state_masked is not Metric.update_state_masked:
            return "custom"
        if self._delta_masked_reason() is None:
            return "delta"
        if self._scan_masked_reason() is None:
            return "scan"
        if self.grouped_update_spec() is not None:
            return "grouped"
        return None

    def _delta_masked_reason(self) -> Optional[str]:
        """None when the vmapped row-delta masked path is exact."""
        if self.full_state_update:
            return "full_state_update metrics read the accumulated state in update; row deltas are not exact"
        for k, v in self._defaults.items():
            if isinstance(v, list):
                return f"state {k!r} is a list (cat/gather) state"
            if self._reductions[k] not in self._MASKED_FX:
                return f"state {k!r} has dist_reduce_fx={self._reductions[k]!r}"
        for name, child in self._child_metrics().items():
            children = child if isinstance(child, list) else [child]
            for c in children:
                r = c._delta_masked_reason() if type(c).update_state_masked is Metric.update_state_masked else None
                if r is not None:
                    return f"nested metric {name!r}: {r}"
        return None

    def _scan_masked_reason(self) -> Optional[str]:
        """None when the sequential scan-fold masked fallback is exact: every
        state (recursively) is a fixed-shape array and update does not consume
        whole-batch statistics (``full_state_update``)."""
        if self.full_state_update:
            return "full_state_update metrics read the accumulated state in update; a row fold is not exact"
        for k, v in self._defaults.items():
            if isinstance(v, list):
                return f"state {k!r} is a list (cat/gather) state with no static shape"
        for name, child in self._child_metrics().items():
            children = child if isinstance(child, list) else [child]
            for c in children:
                if c.masked_update_strategy() is None:
                    return f"nested metric {name!r}: {c._scan_masked_reason()}"
        return None

    def masked_update_unsupported_reason(self) -> Optional[str]:
        """None when :meth:`update_state_masked` applies (any strategy), else a
        human-readable reason. A subclass that overrides
        :meth:`update_state_masked` has taken responsibility for masking and is
        always supported. ``"grouped"`` metrics are NOT maskable here — their
        rows carry group keys the masked contract has no slot for — so they
        report a typed refusal that names the offending states and points at
        the ragged serving path instead of the generic delta/scan dead end."""
        strategy = self.masked_update_strategy()
        if strategy == "grouped":
            return self.grouped_refusal_reason()
        if strategy is not None:
            return None
        return self._scan_masked_reason() or self._delta_masked_reason()

    # ------------------------------------------------- grouped (ragged) serving hooks

    def grouped_update_spec(self) -> Optional[GroupedUpdateSpec]:
        """The metric's group-keyed state declaration, or None.

        Metrics whose state is a per-GROUP bag of rows that only sorts or
        matches at compute time (retrieval's per-query rank sort, detection's
        score sort + greedy IoU match) return a :class:`GroupedUpdateSpec`
        here; the ragged engine (``metrics_tpu.engine.ragged.RaggedEngine``)
        then serves them with per-group capacity buffers + validity masks,
        group keys riding the segmented stream machinery as micro-scale
        stream ids. Everything else returns None (the default)."""
        return None

    def grouped_refusal_reason(self) -> str:
        """The typed refusal a NON-ragged engine raises for a group-keyed
        metric: names the metric, the offending (list / unmergeable) states,
        and points at the ragged path — instead of the generic delta/scan
        message, which is a dead end for these domains."""
        offending = sorted(
            k
            for k, v in self._defaults.items()
            if isinstance(v, list) or self._reductions[k] not in _MERGEABLE_FX
        )
        states = ", ".join(repr(k) for k in offending) or "its group-keyed states"
        return (
            f"{type(self).__name__} accumulates group-keyed rows ({states}) that "
            "sort/match only at compute time; serve it through the ragged path — "
            "metrics_tpu.engine.ragged.RaggedEngine(metric, num_groups=...) — "
            "see docs/serving.md § Ragged serving"
        )

    def grouped_encode(self, *args: Any, **kwargs: Any) -> Tuple[Any, ...]:
        """Flatten one eager ``update(...)`` call into ragged-ingest arrays:
        ``(group_ids, field_0, ..., field_{k-1})`` in the spec's field order,
        all 1-row-per-row along axis 0. Validates exactly like ``update``.
        Implemented by metrics that declare :meth:`grouped_update_spec`."""
        raise MetricsTPUUserError(
            f"{type(self).__name__} declares no grouped_update_spec(); "
            "grouped_encode is only meaningful for group-keyed metrics"
        )

    def grouped_group_value(self, fields: Dict[str, Array], count: Array, capacity: int) -> Any:
        """Traced per-group compute over one group's ``(capacity, ...)``
        buffers (rows valid below ``count``) — what the ragged engine's
        ``result(group_id)`` returns. Implemented alongside
        :meth:`grouped_update_spec`."""
        raise MetricsTPUUserError(
            f"{type(self).__name__} declares no grouped_update_spec(); "
            "grouped_group_value is only meaningful for group-keyed metrics"
        )

    def grouped_finalize(
        self,
        counts: np.ndarray,
        fields: Dict[str, np.ndarray],
        group_ids: np.ndarray,
    ) -> Dict[str, Any]:
        """Host-side: rebuild this metric's EAGER state pytree from
        reconstructed per-group rows (``counts`` ``(G,)``, each field
        ``(G, capacity, ...)``, ``group_ids`` the logical key per group row).
        The ragged engine's aggregate ``result()`` feeds the returned state
        through :meth:`compute_from`, so the served value is bit-exact vs the
        eager oracle. Implemented alongside :meth:`grouped_update_spec`."""
        raise MetricsTPUUserError(
            f"{type(self).__name__} declares no grouped_update_spec(); "
            "grouped_finalize is only meaningful for group-keyed metrics"
        )

    def grouped_aggregate_spec(self) -> Optional["GroupedAggregateSpec"]:
        """The metric's device-aggregate declaration, or None.

        Grouped metrics whose corpus-level ``result()`` can run as a compiled
        device program (instead of the host eager replay through
        :meth:`grouped_finalize`) return a :class:`GroupedAggregateSpec` here;
        the ragged engine then serves the aggregate as one device program plus
        one scalar transfer, keeping the host path as the parity oracle.  The
        default is None: the engine stays on the oracle path."""
        return None

    def update_state_masked(self, state: Dict[str, Any], *args: Any, mask: Array, **kwargs: Any) -> Dict[str, Any]:
        """Pure mask-aware update: rows of the leading batch axis where ``mask``
        is False contribute NOTHING to the new state.

        This is the padding contract of the streaming engine
        (``metrics_tpu.engine``): batches are padded to a closed set of bucket
        shapes so the compiled-program set is finite, and the pad rows must be
        inert. The generic path runs the subclass ``update`` per row (a vmapped
        batch-of-1 update — exact for every delta-mergeable metric, since
        per-row deltas are the finest batch partition) and reduces the stacked
        row deltas with each state's own reduction, substituting that
        reduction's identity for masked-out rows. Every array leaf of
        ``args``/``kwargs`` whose leading dimension equals ``mask.shape[0]`` is
        treated as batch-carried; everything else broadcasts.

        Subclasses with a cheaper fused masked form (e.g. embedded-model
        metrics where per-row state copies would be prohibitive) override this.
        """
        strategy = self.masked_update_strategy()
        if strategy == "grouped":
            raise MetricsTPUUserError(
                f"{type(self).__name__} has no mask-aware update: "
                f"{self.grouped_refusal_reason()}."
            )
        if strategy is None:
            raise MetricsTPUUserError(
                f"{type(self).__name__} has no mask-aware update: "
                f"{self.masked_update_unsupported_reason()}. "
                "Override `update_state_masked` or stream it eagerly (unbucketed)."
            )
        mask = jnp.asarray(mask, bool)
        if strategy == "scan":
            return self._masked_update_scan(state, args, kwargs, mask)
        stacked = self._stacked_row_deltas(args, kwargs, mask.shape[0])
        return self._masked_reduce_into(state, stacked, mask)

    def _split_batch_leaves(self, args: Any, kwargs: Any, n_rows: int):
        """Flatten ``(args, kwargs)`` and classify leaves against ``n_rows``,
        reshaping each batch-carried leaf to ``(n_rows, 1, ...)`` so a per-row
        body sees exactly the batch-of-1 shapes the subclass validates.
        Returns ``(leaves, in_axes, treedef)`` — ``in_axes[i]`` is 0 for
        batch-carried leaves and None for broadcast leaves."""
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        batched: List[Any] = []
        in_axes: List[Optional[int]] = []
        for leaf in leaves:
            if is_batch_leaf(leaf, n_rows):
                batched.append(jnp.reshape(jnp.asarray(leaf), (n_rows, 1) + leaf.shape[1:]))
                in_axes.append(0)
            else:
                batched.append(leaf)
                in_axes.append(None)
        return batched, in_axes, treedef

    def _stacked_row_deltas(self, args: Any, kwargs: Any, n_rows: int) -> Dict[str, Any]:
        """Row-stacked state deltas (leading axis = rows): the subclass update
        vmapped over batch-of-1 rows — the finest batch partition, exact for
        every delta-mergeable metric. Shared by the masked path (reduce over
        rows) and the multi-stream segmented path (reduce into addressed
        stream rows)."""
        batched, in_axes, treedef = self._split_batch_leaves(args, kwargs, n_rows)

        def per_row(*row_leaves: Any) -> Dict[str, Any]:
            a, kw = jax.tree_util.tree_unflatten(treedef, list(row_leaves))
            return self.update_state(self.init_state(), *a, **kw)

        return jax.vmap(per_row, in_axes=tuple(in_axes))(*batched)

    def _masked_update_scan(
        self, state: Dict[str, Any], args: Any, kwargs: Any, mask: Array
    ) -> Dict[str, Any]:
        """Sequential masked fold for states with no row-neutral reduction
        identity (``cat``-written static buffers and friends): ``lax.scan``
        applies the subclass ``update`` one row at a time in submission order,
        carrying the state through unchanged where ``mask`` is False. Exact
        whenever a batch update equals its rows applied sequentially — the
        contract every array-state metric here satisfies (the static-capacity
        buffers write rows in order). Slower than the delta path (rows
        serialize); the engine only takes it for members that need it."""
        n_rows = mask.shape[0]
        batched, in_axes, treedef = self._split_batch_leaves(args, kwargs, n_rows)
        scanned = [b for b, ax in zip(batched, in_axes) if ax == 0]
        state = jax.tree.map(jnp.asarray, state)

        def fold(carry: Dict[str, Any], xs: Any):
            row_scanned, m = xs
            it = iter(row_scanned)
            row_leaves = [next(it) if ax == 0 else b for b, ax in zip(batched, in_axes)]
            a, kw = jax.tree_util.tree_unflatten(treedef, row_leaves)
            new = self.update_state(carry, *a, **kw)
            kept = jax.tree.map(
                lambda nv, cv: jnp.where(m, nv, cv).astype(cv.dtype), new, carry
            )
            return kept, None

        final, _ = jax.lax.scan(fold, state, (tuple(scanned), mask))
        return final

    def _masked_reduce_into(self, state: Dict[str, Any], stacked: Dict[str, Any], mask: Array) -> Dict[str, Any]:
        """Fold row-stacked deltas (leading axis = rows) into ``state``, skipping
        masked-out rows via each reduction's identity element.

        Each leaf's fold dispatches through the kernel library
        (``ops/kernels/dispatch.py``): a fused Pallas streaming reduction on
        TPU, the vmapped-fold XLA lowering elsewhere (and always under the
        ``xla`` backend) — same values either way, backend chosen at trace
        time."""
        out: Dict[str, Any] = {}
        if self._CHILD_KEY in stacked:
            children = self._child_metrics()
            out[self._CHILD_KEY] = {}
            for name, child_stacked in stacked[self._CHILD_KEY].items():
                child = children.get(name)
                child_state = state.get(self._CHILD_KEY, {}).get(name)
                if isinstance(child, list):
                    out[self._CHILD_KEY][name] = [
                        c._masked_reduce_into(cs, cd, mask)
                        for c, cs, cd in zip(child, child_state, child_stacked)
                    ]
                else:
                    out[self._CHILD_KEY][name] = child._masked_reduce_into(child_state, child_stacked, mask)
        for k in self._defaults:
            fx = self._reductions[k]
            if fx not in self._MASKED_FX:  # pragma: no cover - guarded by masked_update_unsupported_reason
                raise MetricsTPUUserError(f"no masked reduction for dist_reduce_fx={fx!r}")
            out[k] = fold_rows_masked(state[k], stacked[k], mask, fx)
        return out

    # ------------------------------------------------- multi-stream serving hooks

    def segmented_update_unsupported_reason(self) -> Optional[str]:
        """None when :meth:`update_state_segmented` applies: the generic
        row-delta path must hold (a custom fused masked form has no segmented
        counterpart, and scan-fallback metrics would serialize rows per
        stream — neither serves the one-executable multi-stream contract).
        Group-keyed metrics refuse here too, pointing at the ragged engine
        (their per-row keys are NOT the engine's stream ids)."""
        if self.grouped_update_spec() is not None:
            return self.grouped_refusal_reason()
        if type(self).update_state_masked is not Metric.update_state_masked:
            return "custom update_state_masked override has no segmented form"
        return self._delta_masked_reason()

    def update_state_segmented(
        self,
        state: Dict[str, Any],
        *args: Any,
        mask: Array,
        segment_ids: Array,
        num_segments: int,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Pure multi-stream update: ``state`` leaves carry a leading stream
        axis of length ``num_segments``; each batch row updates the stream row
        addressed by ``segment_ids`` (masked-out rows update nothing).

        This is the ``MultiStreamEngine`` step kernel
        (``metrics_tpu/engine/multistream.py``): one executable serves S
        independent streams by scatter-reducing the vmapped row deltas into
        the addressed state rows with each reduction's own operation
        (``.at[ids].add/min/max`` on an identity-filled base). Exact for the
        same metrics as the delta masked path, stream-by-stream.
        """
        reason = self.segmented_update_unsupported_reason()
        if reason is not None:
            raise MetricsTPUUserError(
                f"{type(self).__name__} has no segmented (multi-stream) update: {reason}."
            )
        mask = jnp.asarray(mask, bool)
        segment_ids = jnp.asarray(segment_ids, jnp.int32)
        stacked = self._stacked_row_deltas(args, kwargs, mask.shape[0])
        return self._segment_reduce_into(state, stacked, mask, segment_ids, num_segments)

    def _segment_reduce_into(
        self,
        state: Dict[str, Any],
        stacked: Dict[str, Any],
        mask: Array,
        segment_ids: Array,
        num_segments: int,
    ) -> Dict[str, Any]:
        """Scatter row-stacked deltas into the addressed stream rows of a
        stream-stacked ``state``, skipping masked rows via each reduction's
        identity element (masked rows carry pad ``segment_ids`` — the identity
        makes their target row a no-op regardless). Per-leaf dispatch through
        the kernel library (``ops/kernels``): a scatter-free Pallas
        compare-reduce on TPU, the ``.at[ids].add/min/max`` XLA scatter
        elsewhere."""
        out: Dict[str, Any] = {}
        if self._CHILD_KEY in stacked:
            children = self._child_metrics()
            out[self._CHILD_KEY] = {}
            for name, child_stacked in stacked[self._CHILD_KEY].items():
                child = children.get(name)
                child_state = state.get(self._CHILD_KEY, {}).get(name)
                if isinstance(child, list):
                    out[self._CHILD_KEY][name] = [
                        c._segment_reduce_into(cs, cd, mask, segment_ids, num_segments)
                        for c, cs, cd in zip(child, child_state, child_stacked)
                    ]
                else:
                    out[self._CHILD_KEY][name] = child._segment_reduce_into(
                        child_state, child_stacked, mask, segment_ids, num_segments
                    )
        for k in self._defaults:
            fx = self._reductions[k]
            if fx not in self._MASKED_FX:  # pragma: no cover - guarded by segmented_update_unsupported_reason
                raise MetricsTPUUserError(f"no segmented reduction for dist_reduce_fx={fx!r}")
            out[k] = segment_reduce_masked(
                state[k], stacked[k], mask, segment_ids, num_segments, fx
            )
        return out

    # --------------------------------------------------------- serving state hooks

    def arena_layout(self) -> Any:
        """Packing plan collapsing this metric's state pytree into one
        contiguous buffer per dtype (``engine/arena.py``): the streaming
        engine's step dispatch then carries 2–3 donated arrays instead of one
        per state leaf. Pure metadata, derived from :meth:`abstract_state`."""
        from metrics_tpu.engine.arena import ArenaLayout

        return ArenaLayout.for_state(self.abstract_state())

    #: compute-relevant attributes DERIVED FROM DATA during ``update`` (host
    #: side, outside the registered state pytree) — e.g. ``Accuracy``'s input-
    #: mode latch. Declared here so engine snapshots can persist and restore
    #: them (``engine/snapshot.py``), making a restored engine computable
    #: without replaying a batch first.
    _host_derived_compute_attrs: "tuple[str, ...]" = ()

    def host_compute_attrs(self) -> Dict[str, Any]:
        """Flat ``{path: value}`` of declared host-derived compute attributes
        for self and nested metrics (paths mirror the attribute tree)."""
        out: Dict[str, Any] = {}
        for a in self._host_derived_compute_attrs:
            out[a] = getattr(self, a, None)
        for name, child in self._child_metrics().items():
            if isinstance(child, list):
                for i, c in enumerate(child):
                    for k, v in c.host_compute_attrs().items():
                        out[f"{name}[{i}].{k}"] = v
            else:
                for k, v in child.host_compute_attrs().items():
                    out[f"{name}.{k}"] = v
        return out

    def restore_host_compute_attrs(self, attrs: Dict[str, Any]) -> None:
        """Inverse of :meth:`host_compute_attrs` — sets the declared
        attributes on self and nested metrics; unknown paths are ignored (a
        snapshot from an older metric layout must not crash restore)."""
        for a in self._host_derived_compute_attrs:
            if a in attrs:
                setattr(self, a, attrs[a])
        for name, child in self._child_metrics().items():
            if isinstance(child, list):
                for i, c in enumerate(child):
                    prefix = f"{name}[{i}]."
                    sub = {k[len(prefix):]: v for k, v in attrs.items() if k.startswith(prefix)}
                    if sub:
                        c.restore_host_compute_attrs(sub)
            else:
                prefix = f"{name}."
                sub = {k[len(prefix):]: v for k, v in attrs.items() if k.startswith(prefix)}
                if sub:
                    child.restore_host_compute_attrs(sub)

    def compute_from(self, state: Dict[str, Any]) -> Any:
        """Pure compute on an explicit (already-merged) state pytree."""
        saved = self._pack_state()
        book = self._snapshot_bookkeeping()
        self._load_state(state)
        try:
            return _squeeze_if_scalar(self._inner_compute())
        finally:
            self._load_state(saved)
            self._restore_bookkeeping(book)

    def compute_synced(self, state: Dict[str, Any], axis_name: Optional[AxisSpec] = None) -> Any:
        """Pure sync+compute for use inside ``shard_map``/``pmap`` regions."""
        axis = axis_name or self.sync_axis or current_metric_axis()
        return self.compute_from(self.sync_states(state, axis))

    def sync_states(self, state: Dict[str, Any], axis_name: Optional[AxisSpec]) -> Dict[str, Any]:
        """Apply each state's dist_reduce_fx as an XLA collective over ``axis_name``.

        List states are pre-concatenated (reference ``metric.py:236-238``) then
        all_gathered. Uses one fused collective bundle for all counter states.
        """
        if axis_name is None or not in_mapped_context(axis_name):
            return state
        self._check_spec_consumed()
        # nested metric states sync recursively with their own reductions
        synced_children: Optional[Dict[str, Any]] = None
        if self._CHILD_KEY in state:
            synced_children = self._sync_child_states(state[self._CHILD_KEY], axis_name)
        # pre-cat list states
        prepped: Dict[str, Any] = {}
        was_list: Dict[str, bool] = {}
        for k, v in state.items():
            if k == self._CHILD_KEY:
                continue
            was_list[k] = isinstance(v, list)
            prepped[k] = dim_zero_cat(v) if was_list[k] else v
        keys = list(prepped)
        # reference metric.py:249-252: gathered list states stay FLATTENED (tiled
        # cat gather); only tensor states under fx=None arrive stacked (world, ...)
        fxs = [
            ("cat" if self._reductions[k] is None and was_list[k] else self._reductions[k])
            for k in keys
        ]
        if self.dist_sync_fn is not None:
            # custom sync fns receive the raw (fx, value) contract and always
            # see the exact values — the quantized rider is a property of the
            # built-in fused bundle only
            out = {k: self.dist_sync_fn(fx, prepped[k], axis_name) for k, fx in zip(keys, fxs)}
        else:
            precs = [
                "exact" if was_list[k] else self._sync_precision.get(k, "exact")
                for k in keys
            ]
            synced = fused_axis_sync(
                list(zip(fxs, (prepped[k] for k in keys))), axis_name, precisions=precs
            )
            out = dict(zip(keys, synced))
        if synced_children is not None:
            out[self._CHILD_KEY] = synced_children
        return out

    def _sync_child_states(self, children_state: Dict[str, Any], axis_name: AxisSpec) -> Dict[str, Any]:
        """Sync a '_children' subtree: each nested metric applies its own
        reductions (shared by Metric.sync_states and MetricCollection's fused
        path, which fuses member leaves but must still recurse here)."""
        children = self._child_metrics()
        out: Dict[str, Any] = {}
        for name, child_state in children_state.items():
            child = children.get(name)
            if child is None:
                out[name] = child_state
            elif isinstance(child, list):
                out[name] = [c.sync_states(cs, axis_name) for c, cs in zip(child, child_state)]
            else:
                out[name] = child.sync_states(child_state, axis_name)
        return out

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        """Pairwise merge of two state pytrees (pure). Sum/min/max/cat are canned;
        metrics with custom merge semantics override ``_merge_state`` per state."""
        out: Dict[str, Any] = {}
        if self._CHILD_KEY in a or self._CHILD_KEY in b:
            children = self._child_metrics()
            a_children = a.get(self._CHILD_KEY, {})
            b_children = b.get(self._CHILD_KEY, {})
            merged_children: Dict[str, Any] = {}
            for name in {**a_children, **b_children}:
                ca, cb = a_children.get(name), b_children.get(name)
                child = children.get(name)
                if child is None or ca is None or cb is None:
                    merged_children[name] = ca if ca is not None else cb
                elif isinstance(child, list):
                    merged_children[name] = [
                        c.merge_states(x, y) for c, x, y in zip(child, ca, cb)
                    ]
                else:
                    merged_children[name] = child.merge_states(ca, cb)
            out[self._CHILD_KEY] = merged_children
        for k in self._defaults:
            fx = self._reductions[k]
            va, vb = a[k], b[k]
            if isinstance(self._defaults[k], list):
                out[k] = list(va) + list(vb)
            elif fx == "sum":
                out[k] = va + vb
            elif fx == "min":
                out[k] = jnp.minimum(va, vb)
            elif fx == "max":
                out[k] = jnp.maximum(va, vb)
            elif fx == "cat":
                out[k] = jnp.concatenate([jnp.atleast_1d(va), jnp.atleast_1d(vb)], axis=0)
            else:
                out[k] = self._merge_state(k, va, vb)
        return out

    def _merge_state(self, name: str, a: Any, b: Any) -> Any:
        raise MetricsTPUUserError(
            f"State '{name}' of {type(self).__name__} has a custom/None dist_reduce_fx and no "
            "_merge_state override; cannot merge pairwise."
        )

    def stacked_merge_unsupported_reason(self) -> Optional[str]:
        """None when :meth:`merge_stacked_states` applies: every state
        (recursively) is a fixed-shape array whose ``dist_reduce_fx`` is one
        of sum/min/max/cat. This is the deferred-sync mesh serving contract
        (``engine/pipeline.py``): shard-local states must have a well-defined
        stack-axis merge that equals the reference's ``dist_reduce_fx`` sync —
        list states have no static stacked form, and None/callable reductions
        have no canonical fold."""
        for k, v in self._defaults.items():
            if isinstance(v, list):
                return f"state {k!r} is a list (cat/gather) state with no static shape"
            if self._reductions[k] not in _MERGEABLE_FX:
                return f"state {k!r} has dist_reduce_fx={self._reductions[k]!r} (no stacked merge)"
        for name, child in self._child_metrics().items():
            children = child if isinstance(child, list) else [child]
            for c in children:
                r = c.stacked_merge_unsupported_reason()
                if r is not None:
                    return f"nested metric {name!r}: {r}"
        return None

    def merge_stacked_states(self, stacked: Dict[str, Any]) -> Dict[str, Any]:
        """Fold a leading STACK axis of per-replica states into one global state.

        The deferred-sync mesh engine carries one local state per shard
        (leading axis = shard); the boundary merge applies each state's
        ``dist_reduce_fx`` across that axis — the reference's per-process sync
        semantics (``metric.py:240-252``), moved from per-step deltas to
        whole states. sum/min/max fold with the kernel library's pairwise
        combine (``ops/kernels/common.py`` — the same identities the masked
        paths substitute, dtype-preserving); ``cat`` states flatten the stack
        axis into dim 0, matching ``all_gather_cat``'s tiled layout bit for
        bit. Traced or eager (the engine uses it on-device inside the merge
        shape derivation and on the host when restoring a deferred snapshot
        into a different topology).
        """
        out: Dict[str, Any] = {}
        if self._CHILD_KEY in stacked:
            children = self._child_metrics()
            out[self._CHILD_KEY] = {}
            for name, child_stacked in stacked[self._CHILD_KEY].items():
                child = children.get(name)
                if child is None:
                    # stale subtree (metric reconfigured since the states were
                    # produced): pass through verbatim — same policy as
                    # _sync_child_states — so the caller's shape validation
                    # reports the mismatch instead of an AttributeError here
                    out[self._CHILD_KEY][name] = child_stacked
                elif isinstance(child, list):
                    out[self._CHILD_KEY][name] = [
                        c.merge_stacked_states(cs) for c, cs in zip(child, child_stacked)
                    ]
                else:
                    out[self._CHILD_KEY][name] = child.merge_stacked_states(child_stacked)
        for k in self._defaults:
            fx = self._reductions[k]
            v = stacked[k]
            if isinstance(self._defaults[k], list) or fx not in _MERGEABLE_FX:
                raise MetricsTPUUserError(
                    f"{type(self).__name__} has no stacked state merge: "
                    f"{self.stacked_merge_unsupported_reason()}."
                )
            if fx == "cat":
                v = jnp.asarray(v)
                if v.ndim == 1:  # per-shard SCALAR cat state: the stack IS the cat
                    out[k] = v
                else:
                    out[k] = jnp.reshape(v, (v.shape[0] * v.shape[1],) + v.shape[2:])
            else:
                out[k] = _stack_reduce(v, fx)
        return out

    @property
    def _states_mergeable(self) -> bool:
        if self.full_state_update is not None:
            return not self.full_state_update
        for k, fx in self._reductions.items():
            if isinstance(self._defaults[k], list):
                continue  # lists always merge by extension
            if fx not in _MERGEABLE_FX and not self._overrides_merge_state():
                return False
        # a wrapper is only delta-mergeable if every nested metric is
        for child in self._child_metrics().values():
            children = child if isinstance(child, list) else [child]
            if not all(c._states_mergeable for c in children):
                return False
        return True

    def _overrides_merge_state(self) -> bool:
        return type(self)._merge_state is not Metric._merge_state

    # ------------------------------------------------------------------ stateful facade

    def _inner_update(self, *args: Any, **kwargs: Any) -> None:
        """The unwrapped subclass update."""
        type(self).update(self, *args, **kwargs)

    def _inner_compute(self) -> Any:
        return type(self).compute(self)

    def _wrap_update(self, update: Callable) -> Callable:
        # named profiler scope per metric: shows up in jax.profiler / XLA traces
        # (the reference has no tracing at all — SURVEY.md §5)
        scope = f"metrics_tpu.{type(self).__name__}.update"

        @functools.wraps(update)
        def wrapped_func(*args: Any, **kwargs: Any) -> None:
            if self._is_synced:
                raise MetricsTPUUserError(
                    "The Metric has already been synced. HINT: call unsync() before modifying state."
                )
            self._computed = None
            self._update_called = True
            with jax.profiler.TraceAnnotation(scope):
                update(*args, **kwargs)

        return wrapped_func

    def _wrap_compute(self, compute: Callable) -> Callable:
        @functools.wraps(compute)
        def wrapped_func(*args: Any, **kwargs: Any) -> Any:
            if not self._update_called:
                rank_zero_warn(
                    f"The ``compute`` method of metric {type(self).__name__} was called before "
                    "the ``update`` method which may lead to errors, as metric states have not "
                    "yet been updated.",
                    UserWarning,
                )
            if self._computed is not None:
                return self._computed
            self._raise_if_invalid()
            with self.sync_context(
                dist_sync_fn=self.dist_sync_fn,
                should_sync=self._to_sync,
                should_unsync=self._should_unsync,
            ):
                with jax.profiler.TraceAnnotation(f"metrics_tpu.{type(self).__name__}.compute"):
                    value = compute(*args, **kwargs)
                self._computed = _squeeze_if_scalar(value)
            return self._computed

        return wrapped_func

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        """Accumulate global state and (optionally) return the batch-local value.

        One ``update`` per call when states merge pairwise (the common case) — the
        batch value is computed from the fresh state *delta* and the delta merged into
        the global state (SURVEY.md §7.1; beats reference ``metric.py:206,218`` which
        runs update twice). Metrics with non-mergeable custom states fall back to the
        reference's snapshot/restore path.
        """
        if self._is_synced:
            raise MetricsTPUUserError("The Metric shouldn't be synced when performing ``forward``.")
        if self._states_mergeable:
            fast = self._forward_fast(args, kwargs)
            if fast is not _MISS:
                merged, value = fast
                self._load_state(merged)
                self._mark_updated()
                self._forward_cache = value if self.compute_on_step else None
                return self._forward_cache
            delta = self.update_state(self.init_state(), *args, **kwargs)
            merged = self.merge_states(self._pack_state(), delta)
            self._load_state(merged)
            self._mark_updated()
            if not self.compute_on_step:
                self._forward_cache = None
                return None
            if self.dist_sync_on_step:
                axis = self.sync_axis or current_metric_axis()
                delta = self.sync_states(delta, axis)
            self._forward_cache = self.compute_from(delta)
            return self._forward_cache
        # fallback: snapshot global state, compute batch value with a second update
        self.update(*args, **kwargs)
        if not self.compute_on_step:
            self._forward_cache = None
            return None
        cache = self._pack_state()
        in_sync = self.dist_sync_on_step
        self._to_sync = in_sync
        self._should_unsync = False
        self._load_state(self.init_state())
        self.update(*args, **kwargs)
        self._forward_cache = self.compute()
        self._load_state(cache)
        self._should_unsync = True
        self._to_sync = True
        # recursive: the batch-local compute cached _computed on self AND any
        # nested metrics — all of those caches describe the discarded batch
        # state, not the restored accumulated state
        self._mark_updated()
        self._is_synced = False
        return self._forward_cache

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    # ---------------------------------------------------------- forward auto-jit path

    _FORWARD_JIT_MAX_SIGNATURES = 64

    def _raise_if_invalid(self) -> None:
        """Raise any validation error recorded by a compiled forward step.

        The compiled path can't raise mid-graph; value checks run IN-graph and
        their error code accumulates on-device. This is the (deferred) raise
        point — called from compute() and sync(), CUDA-style."""
        code_arr = self._deferred_errcode
        if code_arr is None:
            return
        code = int(code_arr)
        if code:
            # sticky: the merged state contains the invalid batch, so every
            # compute()/sync() until reset() must keep raising — a caught-and-
            # retried compute must not return a corrupted value
            self._deferred_errcode = code
            raise ValueError(
                deferred_message(code) + " (detected by a compiled forward step; raised deferred)"
            )
        self._deferred_errcode = None

    def _forward_jit_safe(self) -> bool:
        """Override to opt a metric out of the compiled forward path when its
        eager semantics depend on concrete VALUES beyond input validation (e.g.
        aggregators with ``nan_strategy='error'`` must raise on every batch)."""
        for child in self._child_metrics().values():
            children = child if isinstance(child, list) else [child]
            if not all(c._forward_jit_safe() for c in children):
                return False
        return True

    def _has_list_state(self) -> bool:
        if any(isinstance(v, list) for v in self._defaults.values()):
            return True
        for child in self._child_metrics().values():
            children = child if isinstance(child, list) else [child]
            if any(c._has_list_state() for c in children):
                return True
        return False

    @staticmethod
    def _forward_signature(args: Any, kwargs: Any):
        """Hashable call signature, or None if the call can't use the jit path.

        Array leaves are keyed by (shape, dtype) and passed as jit arguments;
        every other hashable leaf (python scalars, None) is keyed by VALUE and
        baked into the trace as a constant. Strings (text metrics) and tracers
        (forward already inside a user trace) opt out.
        """
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig: List[Any] = []
        array_idx: List[int] = []
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, jax.core.Tracer) or isinstance(leaf, str):
                return None
            if isinstance(leaf, (jax.Array, np.ndarray)):
                sig.append((leaf.shape, str(leaf.dtype)))
                array_idx.append(i)
            elif isinstance(leaf, float) and not isinstance(leaf, bool):
                # data-like scalar (per-step loss values etc.): pass as a traced
                # argument, NOT a baked constant — one compile covers all values
                sig.append(float)
                array_idx.append(i)
            elif isinstance(leaf, (bool, int, type(None))):
                sig.append((type(leaf), leaf))
            else:
                return None
        return (treedef, tuple(sig)), tuple(array_idx), leaves

    def _forward_fast(self, args: Any, kwargs: Any):
        """Compiled whole-step forward: one XLA executable instead of dozens of
        eager op dispatches (the reference pays TWO eager updates per forward —
        ``metric.py:206,218``; we pay one compiled call).

        Protocol per input signature: 1st call runs the eager path (so eager
        value validation fires at least once per shape/dtype pattern), 2nd call
        traces + compiles ``update→merge→compute(delta)``, later calls reuse the
        executable. Updates that can't trace (host-side text/detection work,
        data-dependent branching) permanently fall back to eager. Returns
        ``(merged_state, batch_value)`` or ``_MISS``.
        """
        if self.dist_sync_on_step or self.dist_sync_fn is not None or not self._defaults:
            return _MISS
        # static per instance configuration — computed once, not per batch
        path_ok = getattr(self, "_fwd_path_ok", None)
        if path_ok is None:
            path_ok = self._forward_jit_safe() and not self._has_list_state()
            self._fwd_path_ok = path_ok
        if not path_ok:
            return _MISS
        parsed = self._forward_signature(args, kwargs)
        if parsed is None:
            return _MISS
        sig, array_idx, leaves = parsed
        sig = (sig, bool(self.compute_on_step))  # compute_on_step is baked into the step
        entry, cache = _jit_cache_lookup(self, sig, lambda: self._build_forward_step(sig, array_idx, leaves))
        if entry is None:
            return _MISS
        packed = self._pack_state()
        try:
            merged, value, errcode = entry(packed, [leaves[i] for i in array_idx])
        except Exception:
            # Trace-time failure (untraceable update, genuine input error):
            # nothing was donated, the state buffers are intact — stay eager;
            # the eager path re-raises real user errors with their message.
            # EXECUTION-time failure on an accelerator is different: the step
            # donates the state (see _build_forward_step), so the old buffers
            # may already be invalidated — falling back to eager would read
            # deleted arrays and silently corrupt the metric. Surface it.
            if any(
                getattr(leaf, "is_deleted", lambda: False)()
                for leaf in jax.tree_util.tree_leaves(packed)
            ):
                raise
            cache[sig] = _EAGER_ONLY
            return _MISS
        # accumulate the in-graph validation code on-device (async, no transfer);
        # checked + raised at the next compute()/sync() — see _raise_if_invalid
        self._deferred_errcode = (
            errcode if self._deferred_errcode is None else jnp.maximum(self._deferred_errcode, errcode)
        )
        return merged, value

    def _build_forward_step(self, sig: Any, array_idx: Sequence[int], leaves: Sequence[Any]):
        treedef = sig[0][0]  # sig = ((treedef, leaf_sig), compute_on_step)
        n_leaves = len(leaves)
        consts = {i: leaf for i, leaf in enumerate(leaves) if i not in array_idx}
        compute_on_step = self.compute_on_step
        # weak binding: the compiled step must NOT strongly reference self, or
        # the _FORWARD_JIT_CACHE value would pin its own key alive forever
        wself = weakref.ref(self)

        def step(state: Dict[str, Any], arrays: Sequence[Any]):
            m = wself()
            assert m is not None  # caller holds a strong ref for the call's duration
            merged_leaves: List[Any] = [None] * n_leaves
            for i, arr in zip(array_idx, arrays):
                merged_leaves[i] = arr
            for i, c in consts.items():
                merged_leaves[i] = c
            a, kw = jax.tree_util.tree_unflatten(treedef, merged_leaves)
            with deferred_value_checks() as checks:
                delta = m.update_state(m.init_state(), *a, **kw)
            merged = m.merge_states(state, delta)
            value = m.compute_from(delta) if compute_on_step else None
            return merged, value, checks.combined()

        # DONATE the incoming state: forward() immediately rebinds the metric's
        # attributes to the returned merged state, so the old buffers are dead
        # the moment the step returns — donation lets XLA write the merge in
        # place instead of allocating a second copy. For streaming-stat metrics
        # this is material HBM (FID's float-float covariance state is 4 full
        # feature_dim^2 f32 buffers, ~67 MB at 2048). init_state() already
        # copies default leaves precisely so donated states never alias
        # (metric.py:240-242). Consequence on accelerators: an EXTERNAL
        # reference to a state array taken between forwards (e.g. holding
        # `m.total` and calling forward again) reads as deleted — snapshot
        # with np.asarray/state_dict() instead of borrowing live attributes.
        # CPU doesn't implement donation and would warn on
        # every compile, so the hint is only attached on accelerators.
        donate = (0,) if jax.default_backend() != "cpu" else ()
        return jax.jit(step, donate_argnums=donate)

    def reset(self) -> None:
        """Reset state to defaults. Parity: reference ``metric.py:397-418``."""
        self._update_called = False
        self._forward_cache = None
        self._computed = None
        self._load_state(self.init_state())
        self._is_synced = False
        self._cache = None
        self._deferred_errcode = None

    # ----------------------------------------------------------------------- eager sync

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ) -> None:
        """Eagerly replace local state with the cross-process merged state.

        Parity: reference ``metric.py:268-302``. In-trace (inside shard_map) this is a
        no-op here — sync happens functionally in ``compute_synced``. Eager multi-host
        sync uses ``jax.experimental.multihost_utils.process_allgather``.
        """
        if self._is_synced and should_sync:
            raise MetricsTPUUserError("The Metric has already been synced.")
        self._raise_if_invalid()
        is_distributed = (
            distributed_available_fn() if distributed_available_fn is not None else distributed_available()
        )
        axis = self.sync_axis or current_metric_axis()
        in_trace = in_mapped_context(axis)
        if not should_sync or (not is_distributed and not in_trace):
            return
        self._cache = self._pack_state()
        if in_trace:
            self._load_state(self.sync_states(self._pack_state(), axis))
        else:
            self._load_state(self._multihost_sync(self._pack_state(), dist_sync_fn))
        self._is_synced = True

    def _multihost_sync(self, state: Dict[str, Any], dist_sync_fn: Optional[Callable]) -> Dict[str, Any]:
        from jax.experimental import multihost_utils

        out: Dict[str, Any] = {}
        for k, v in state.items():
            if k == self._CHILD_KEY:
                # child states pass through UNSYNCED: in the eager path each
                # nested metric syncs itself when its own wrapped compute runs
                # (reference semantics — the wrapper never gathers for its
                # children; recursing here would double-sync sums/counts)
                out[k] = v
                continue
            fx = self._reductions[k]
            was_list = isinstance(v, list)
            v = dim_zero_cat(v) if was_list else v
            gathered = multihost_utils.process_allgather(v)  # (procs, ...)
            if fx == "sum":
                merged = jnp.sum(gathered, axis=0)
            elif fx == "mean":
                merged = jnp.mean(gathered, axis=0)
            elif fx == "min":
                merged = jnp.min(gathered, axis=0)
            elif fx == "max":
                merged = jnp.max(gathered, axis=0)
            elif fx == "cat":
                merged = jnp.reshape(gathered, (-1,) + gathered.shape[2:])
            elif fx is None:
                merged = jnp.reshape(gathered, (-1,) + gathered.shape[2:]) if was_list else gathered
            elif callable(fx):
                merged = gathered[0]
                for i in range(1, gathered.shape[0]):
                    merged = fx(merged, gathered[i])
            else:
                merged = gathered
            out[k] = [merged] if was_list else merged
        return out

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore rank-local state after :meth:`sync`. Parity: ``metric.py:304-324``."""
        if not should_unsync:
            return
        if not self._is_synced:
            raise MetricsTPUUserError("The Metric has already been un-synced.")
        if self._cache is None:
            raise MetricsTPUUserError("The internal cache should exist to unsync the Metric.")
        self._load_state(self._cache)
        self._is_synced = False
        self._cache = None

    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available_fn: Optional[Callable] = None,
    ):
        """Context manager: synced state inside, local state restored on exit."""
        metric = self

        class _Ctx:
            def __enter__(self):
                metric.sync(
                    dist_sync_fn=dist_sync_fn,
                    should_sync=should_sync,
                    distributed_available_fn=distributed_available_fn,
                )
                return metric

            def __exit__(self, *exc):
                metric.unsync(should_unsync=metric._is_synced and should_unsync)
                return False

        return _Ctx()

    # ---------------------------------------------------------------- misc protocol bits

    def _for_each_child(self, fn: Callable[["Metric"], Any]) -> None:
        for child in self._child_metrics().values():
            if isinstance(child, list):
                for c in child:
                    fn(c)
            else:
                fn(child)

    def persistent(self, mode: bool = False) -> None:
        for k in self._persistent:
            self._persistent[k] = mode
        self._for_each_child(lambda c: c.persistent(mode=mode))

    def state_dict(self, prefix: str = "") -> Dict[str, Any]:
        """Serializable snapshot of persistent states (as numpy), recursing into
        nested metrics with dotted prefixes (the reference gets this via
        nn.Module recursion). Parity: metric.py:514."""
        out = {}
        for k in self._defaults:
            if not self._persistent[k]:
                continue
            v = getattr(self, k)
            if isinstance(v, list):
                out[prefix + k] = [np.asarray(x) for x in v]
            else:
                out[prefix + k] = np.asarray(v)
        for name, child in self._child_metrics().items():
            if isinstance(child, list):
                for i, c in enumerate(child):
                    out.update(c.state_dict(prefix=f"{prefix}{name}.{i}."))
            else:
                out.update(child.state_dict(prefix=f"{prefix}{name}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any], prefix: str = "") -> None:
        for k in self._defaults:
            key = prefix + k
            if key in state_dict:
                v = state_dict[key]
                if isinstance(v, list):
                    setattr(self, k, [jnp.asarray(x) for x in v])
                else:
                    setattr(self, k, jnp.asarray(v))
        for name, child in self._child_metrics().items():
            if isinstance(child, list):
                for i, c in enumerate(child):
                    c.load_state_dict(state_dict, prefix=f"{prefix}{name}.{i}.")
            else:
                child.load_state_dict(state_dict, prefix=f"{prefix}{name}.")

    def clone(self) -> "Metric":
        return deepcopy(self)

    def to_device(self, device) -> "Metric":
        """Move all states (incl. nested metrics') to ``device``."""
        for k in self._defaults:
            v = getattr(self, k)
            if isinstance(v, list):
                setattr(self, k, [jax.device_put(x, device) for x in v])
            else:
                setattr(self, k, jax.device_put(v, device))
        self._for_each_child(lambda c: c.to_device(device))
        return self

    def astype(self, dtype) -> "Metric":
        """Cast floating-point states (incl. nested metrics'). Analogue of
        reference half()/float()/double()."""
        for k in self._defaults:
            v = getattr(self, k)
            if isinstance(v, list):
                setattr(self, k, [x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x for x in v])
            elif jnp.issubdtype(v.dtype, jnp.floating):
                setattr(self, k, v.astype(dtype))
        self._for_each_child(lambda c: c.astype(dtype))
        return self

    def _filter_kwargs(self, **kwargs: Any) -> Dict[str, Any]:
        """Keep only kwargs the (unwrapped) update accepts. Parity: metric.py:554-574."""
        sig = inspect.signature(type(self).update)
        params = sig.parameters
        has_var_kw = any(p.kind == inspect.Parameter.VAR_KEYWORD for p in params.values())
        if has_var_kw:
            return kwargs
        return {
            k: v
            for k, v in kwargs.items()
            if k in params and params[k].kind in (inspect.Parameter.POSITIONAL_OR_KEYWORD, inspect.Parameter.KEYWORD_ONLY)
        }

    def __getstate__(self) -> Dict[str, Any]:
        # drop wrapped bound methods (reference metric.py:420-429); numpy-ify states
        state = self.__dict__.copy()
        state.pop("update", None)
        state.pop("compute", None)
        state["_deferred_errcode"] = None  # device array; validation status is session-local
        for k in self._defaults:
            v = state.get(k)
            if isinstance(v, jax.Array):
                state[k] = np.asarray(v)
            elif isinstance(v, list):
                state[k] = [np.asarray(x) if isinstance(x, jax.Array) else x for x in v]
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        for k in self._defaults:
            v = getattr(self, k, None)
            if isinstance(v, np.ndarray):
                setattr(self, k, jnp.asarray(v))
            elif isinstance(v, list):
                setattr(self, k, [jnp.asarray(x) if isinstance(x, np.ndarray) else x for x in v])
        self.update = self._wrap_update(type(self).update.__get__(self))
        self.compute = self._wrap_compute(type(self).compute.__get__(self))

    def __setattr__(self, name: str, value: Any) -> None:
        if name in ("higher_is_better", "is_differentiable"):
            raise RuntimeError(f"Can't change const `{name}`.")
        object.__setattr__(self, name, value)

    def __hash__(self) -> int:
        hash_vals = [type(self).__name__, id(self)]
        return hash(tuple(hash_vals))

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"

    # subclass contract ---------------------------------------------------------------

    def update(self, *args: Any, **kwargs: Any) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def compute(self) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    # operator overloads -> CompositionalMetric (reference metric.py:595-698) ----------

    def __add__(self, other): return CompositionalMetric(jnp.add, self, other)
    def __radd__(self, other): return CompositionalMetric(jnp.add, other, self)
    def __sub__(self, other): return CompositionalMetric(jnp.subtract, self, other)
    def __rsub__(self, other): return CompositionalMetric(jnp.subtract, other, self)
    def __mul__(self, other): return CompositionalMetric(jnp.multiply, self, other)
    def __rmul__(self, other): return CompositionalMetric(jnp.multiply, other, self)
    def __truediv__(self, other): return CompositionalMetric(jnp.true_divide, self, other)
    def __rtruediv__(self, other): return CompositionalMetric(jnp.true_divide, other, self)
    def __floordiv__(self, other): return CompositionalMetric(jnp.floor_divide, self, other)
    def __rfloordiv__(self, other): return CompositionalMetric(jnp.floor_divide, other, self)
    def __mod__(self, other): return CompositionalMetric(jnp.mod, self, other)
    def __rmod__(self, other): return CompositionalMetric(jnp.mod, other, self)
    def __pow__(self, other): return CompositionalMetric(jnp.power, self, other)
    def __rpow__(self, other): return CompositionalMetric(jnp.power, other, self)
    def __matmul__(self, other): return CompositionalMetric(jnp.matmul, self, other)
    def __rmatmul__(self, other): return CompositionalMetric(jnp.matmul, other, self)
    def __and__(self, other): return CompositionalMetric(jnp.bitwise_and, self, other)
    def __rand__(self, other): return CompositionalMetric(jnp.bitwise_and, other, self)
    def __or__(self, other): return CompositionalMetric(jnp.bitwise_or, self, other)
    def __ror__(self, other): return CompositionalMetric(jnp.bitwise_or, other, self)
    def __xor__(self, other): return CompositionalMetric(jnp.bitwise_xor, self, other)
    def __rxor__(self, other): return CompositionalMetric(jnp.bitwise_xor, other, self)
    def __eq__(self, other): return CompositionalMetric(jnp.equal, self, other)
    def __ne__(self, other): return CompositionalMetric(jnp.not_equal, self, other)
    def __lt__(self, other): return CompositionalMetric(jnp.less, self, other)
    def __le__(self, other): return CompositionalMetric(jnp.less_equal, self, other)
    def __gt__(self, other): return CompositionalMetric(jnp.greater, self, other)
    def __ge__(self, other): return CompositionalMetric(jnp.greater_equal, self, other)
    def __abs__(self): return CompositionalMetric(jnp.abs, self, None)
    def __neg__(self): return CompositionalMetric(_neg, self, None)
    def __pos__(self): return CompositionalMetric(jnp.abs, self, None)
    def __invert__(self): return CompositionalMetric(jnp.logical_not, self, None)
    def __getitem__(self, idx): return CompositionalMetric(lambda x: x[idx], self, None)


# _reduce_identity moved to metrics_tpu/ops/kernels/common.py (imported above):
# the kernel library's Pallas bodies and XLA reference lowerings must fold
# masked rows with the SAME identity elements this module always used.


def _neg(x: Array) -> Array:
    return -jnp.abs(x)


class CompositionalMetric(Metric):
    """Lazy arithmetic composition of metrics. Parity: reference ``metric.py:705-815``.

    Delegates update/reset to operand metrics; compute applies ``operator`` to operand
    computes. Has no state of its own, hence no sync (reference ``:737``).
    """

    def __init__(
        self,
        operator: Callable,
        metric_a: Union[Metric, int, float, Array],
        metric_b: Union[Metric, int, float, Array, None],
    ) -> None:
        super().__init__()
        self.op = operator
        self.metric_a = metric_a if isinstance(metric_a, Metric) else (
            jnp.asarray(metric_a) if metric_a is not None else None)
        self.metric_b = metric_b if isinstance(metric_b, Metric) else (
            jnp.asarray(metric_b) if metric_b is not None else None)

    def _sync_dist(self, *args: Any, **kwargs: Any) -> None:
        pass  # No syncing required here. syncing will be done in metric_a and metric_b

    def sync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def unsync(self, *args: Any, **kwargs: Any) -> None:
        pass

    def update(self, *args: Any, **kwargs: Any) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.update(*args, **self.metric_a._filter_kwargs(**kwargs))
        if isinstance(self.metric_b, Metric):
            self.metric_b.update(*args, **self.metric_b._filter_kwargs(**kwargs))

    def compute(self) -> Any:
        val_a = self.metric_a.compute() if isinstance(self.metric_a, Metric) else self.metric_a
        val_b = self.metric_b.compute() if isinstance(self.metric_b, Metric) else self.metric_b
        if val_b is None:
            return self.op(val_a)
        return self.op(val_a, val_b)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        val_a = (
            self.metric_a(*args, **self.metric_a._filter_kwargs(**kwargs))
            if isinstance(self.metric_a, Metric)
            else self.metric_a
        )
        val_b = (
            self.metric_b(*args, **self.metric_b._filter_kwargs(**kwargs))
            if isinstance(self.metric_b, Metric)
            else self.metric_b
        )
        if val_a is None:
            self._forward_cache = None
        elif val_b is None:
            if isinstance(self.metric_b, Metric):
                self._forward_cache = None
            else:
                self._forward_cache = self.op(val_a)
        else:
            self._forward_cache = self.op(val_a, val_b)
        return self._forward_cache

    def reset(self) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.reset()
        if isinstance(self.metric_b, Metric):
            self.metric_b.reset()
        self._update_called = False
        self._forward_cache = None
        self._computed = None

    def persistent(self, mode: bool = False) -> None:
        if isinstance(self.metric_a, Metric):
            self.metric_a.persistent(mode=mode)
        if isinstance(self.metric_b, Metric):
            self.metric_b.persistent(mode=mode)

    def __repr__(self) -> str:
        _op_metrics = f"(\n  {self.op.__name__ if hasattr(self.op, '__name__') else 'fn'}(\n    {repr(self.metric_a)},\n    {repr(self.metric_b)}\n  )\n)"
        return self.__class__.__name__ + _op_metrics
