"""Shared static-capacity buffer machinery for exact curve metrics.

``AUROC(capacity=N)`` / ``AveragePrecision(capacity=N)`` keep identical
``(preds_buf, target_buf, valid_buf, count, overflow)`` states; this mixin owns
the registration, the masked buffer writes and the overflow→NaN contract so the
two metrics cannot drift (they briefly did — one-hot condition and averaging
semantics diverged in the first cut).
"""
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


class CapacityCurveStateMixin:
    """Mixin for metrics with a static ``(capacity, ...)`` score buffer."""

    capacity: Optional[int]
    num_classes: Optional[int]

    def _capacity_num_columns(self) -> Optional[int]:
        return self.num_classes if (self.num_classes or 0) > 1 else None

    def _validate_capacity_kwargs(self, pos_label, average) -> None:
        """Shared up-front rejections for eager-only options."""
        if average == "micro":
            raise ValueError("`average='micro'` is not supported in static-capacity mode")
        if pos_label not in (None, 1):
            raise ValueError(
                "`pos_label` is not supported in static-capacity mode (positives are `target > 0`);"
                " use the default eager mode"
            )

    def _compute_capacity_with(self, binary_kernel, multilabel_kernel):
        """Dispatch compute over the shared buffer layout: per-column kernel for
        declared multiclass/multilabel, binary kernel otherwise; NaN on overflow."""
        if self._capacity_num_columns():
            value = multilabel_kernel(
                self.preds_buf, self.target_buf, self.valid_buf,
                average=self.average if self.average in ("macro", "weighted") else "none",
            )
        else:
            value = binary_kernel(self.preds_buf, self.target_buf, self.valid_buf)
        return self._capacity_guard_nan(value)

    def _init_capacity_states(self) -> None:
        c = self._capacity_num_columns()
        capacity = self.capacity
        if not isinstance(capacity, int) or capacity <= 0:
            raise ValueError(f"`capacity` must be a positive int, got {capacity}")
        score_shape = (capacity, c) if c else (capacity,)
        # multiclass labels are stored one-hot: the per-column kernels then read
        # the same layout multilabel targets arrive in
        self.add_state("preds_buf", default=jnp.zeros(score_shape, jnp.float32), dist_reduce_fx="cat")
        self.add_state("target_buf", default=jnp.zeros(score_shape, jnp.int32), dist_reduce_fx="cat")
        self.add_state("valid_buf", default=jnp.zeros((capacity,), bool), dist_reduce_fx="cat")
        self.add_state("count", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")
        self.add_state("overflow", default=jnp.asarray(0, jnp.int32), dist_reduce_fx="sum")

    def _capacity_write(self, preds: Array, target: Array) -> None:
        """Write one canonicalized batch (binary ``(N,)`` or per-column
        ``(N, C)`` with one-hot/multilabel targets) at the current fill point.

        A single batch larger than the whole buffer is a static-shape error —
        raised at trace time with a clear message rather than crashing inside
        ``dynamic_update_slice``. Cumulative overflow across batches sets the
        flag (in-trace code cannot raise) and compute returns NaN.
        """
        c = self._capacity_num_columns()
        n = preds.shape[0]
        if n > self.capacity:
            raise ValueError(
                f"A single batch of {n} samples cannot fit the capacity-{self.capacity} buffer of"
                f" {type(self).__name__}; raise `capacity` to at least the largest batch size."
            )
        start = self.count
        # an overflowing write is a NO-OP (dynamic_update_slice would clamp the
        # start index and silently overwrite valid tail entries): the buffers
        # stay intact for anyone reading partial results, the flag still forces
        # NaN at compute
        fits = start + n <= self.capacity
        if c:
            preds_new = jax.lax.dynamic_update_slice(self.preds_buf, preds.astype(jnp.float32), (start, 0))
            target_new = jax.lax.dynamic_update_slice(self.target_buf, target.astype(jnp.int32), (start, 0))
        else:
            preds_new = jax.lax.dynamic_update_slice(self.preds_buf, preds.astype(jnp.float32), (start,))
            target_new = jax.lax.dynamic_update_slice(self.target_buf, target.astype(jnp.int32), (start,))
        valid_new = jax.lax.dynamic_update_slice(self.valid_buf, jnp.ones((n,), bool), (start,))
        self.preds_buf = jnp.where(fits, preds_new, self.preds_buf)
        self.target_buf = jnp.where(fits, target_new, self.target_buf)
        self.valid_buf = jnp.where(fits, valid_new, self.valid_buf)
        self.overflow = self.overflow + (~fits).astype(jnp.int32)
        self.count = jnp.where(fits, start + n, start)

    def _capacity_curve_precheck(self, preds: Array) -> None:
        """Friendly layout check on the RAW inputs, before canonicalization
        (whose multilabel branch would otherwise crash with a bare IndexError
        on mismatched shapes)."""
        c = self._capacity_num_columns()
        nd = jnp.ndim(preds)
        if c is not None and nd < 2:
            raise ValueError(
                f"Static-capacity {type(self).__name__} needs `num_classes` matching the data:"
                f" num_classes={self.num_classes} expects (N, {self.num_classes}) scores, got"
                f" shape {jnp.shape(preds)} — leave num_classes unset/1 for binary inputs"
            )
        if c is None and nd > 1:
            raise ValueError(
                f"Static-capacity {type(self).__name__} needs `num_classes` matching the data:"
                f" multi-column scores of shape {jnp.shape(preds)} need num_classes=C"
            )

    def _capacity_curve_write(self, preds: Array, target: Array) -> None:
        """Shared update path for curve metrics: validate the declared layout
        against the canonicalized batch, one-hot multiclass labels, write."""
        from metrics_tpu.utils.data import to_onehot

        c = self._capacity_num_columns()
        if (preds.ndim == 1) != (c is None):
            raise ValueError(
                f"Static-capacity {type(self).__name__} needs `num_classes` matching the data:"
                f" leave it unset/1 for binary inputs, set it to C for multiclass — got"
                f" num_classes={self.num_classes} with preds of shape {preds.shape}"
            )
        if c and target.ndim == 1:
            target = to_onehot(target, c)
        self._capacity_write(preds, target)

    def _compute_capacity_curve_with(self, kernel):
        """Dispatch a 3-output curve kernel over the shared buffer layout:
        per-column vmap for declared multiclass, plain call otherwise."""
        if self._capacity_num_columns():
            a, b, c = jax.vmap(
                lambda p_col, t_col: kernel(p_col, t_col, self.valid_buf), in_axes=(1, 1)
            )(self.preds_buf, self.target_buf)
        else:
            a, b, c = kernel(self.preds_buf, self.target_buf, self.valid_buf)
        return self._capacity_guard_nan(a), self._capacity_guard_nan(b), self._capacity_guard_nan(c)

    def _capacity_guard_nan(self, value: Array) -> Array:
        """Warn eagerly on overflow; mask the result to NaN either way."""
        from metrics_tpu.utils.checks import _is_tracer
        from metrics_tpu.utils.prints import rank_zero_warn

        if not _is_tracer(self.overflow) and int(self.overflow) > 0:
            rank_zero_warn(
                f"{type(self).__name__}(capacity={self.capacity}) overflowed — more samples were"
                " updated than the buffer holds; returning NaN. Raise `capacity`.", UserWarning,
            )
        return jnp.where(self.overflow > 0, jnp.nan, value)
