"""HingeLoss module metric (+ deprecated Hinge alias).

Parity: reference ``torchmetrics/classification/hinge.py:23,132``.
"""
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.hinge import MulticlassMode, _hinge_compute, _hinge_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class HingeLoss(Metric):
    """Mean hinge loss, with Crammer-Singer or one-vs-all multiclass modes.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HingeLoss
        >>> preds = jnp.asarray([-2.0, 1.5, 2.2])
        >>> target = jnp.asarray([0, 1, 1])
        >>> hinge = HingeLoss()
        >>> print(f"{float(hinge(preds, target)):.4f}")
        0.0000
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(
        self,
        squared: bool = False,
        multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.add_state("measure", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

        if multiclass_mode not in (None, MulticlassMode.CRAMMER_SINGER, MulticlassMode.ONE_VS_ALL):
            raise ValueError(
                "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
                "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
                f" got {multiclass_mode}."
            )
        self.squared = squared
        self.multiclass_mode = multiclass_mode

    def update(self, preds: Array, target: Array) -> None:
        measure, total = _hinge_update(preds, target, squared=self.squared, multiclass_mode=self.multiclass_mode)
        self.measure = measure + self.measure
        self.total = total + self.total

    def compute(self) -> Array:
        return _hinge_compute(self.measure, self.total)


class Hinge(HingeLoss):
    """Deprecated alias. Parity: reference ``hinge.py:132``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`Hinge` was renamed to `HingeLoss` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)
