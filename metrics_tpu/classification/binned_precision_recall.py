"""Binned (constant-memory, static-shape) precision-recall curve metrics.

Parity: reference ``torchmetrics/classification/binned_precision_recall.py``
(_recall_at_precision :24, BinnedPrecisionRecallCurve :45 with states :147-152,
BinnedAveragePrecision :191, BinnedRecallAtFixedPrecision :245).

This family is the **TPU-native template for curve metrics** (SURVEY.md §7.1): states
are fixed ``(C, T)`` counters with sum-reduce, so the whole update/compute/sync path
is jit/scan/shard_map-safe with one psum — unlike the exact curve metrics whose
gathered cat-state has data-dependent length. The reference iterates one threshold at
a time "to conserve memory" (``:169-174``); here the counting goes through
``metrics_tpu/ops/binned_update.binned_counts`` — a streaming Pallas kernel on TPU
(N blocked through VMEM, thresholds looped on the VPU), and the fused jnp
compare+mask+reduce formulation elsewhere.

Deviation from the reference: ``thresholds`` defaults to 100 bins (the reference has
no default and crashes with ``thresholds=None``).
"""
from typing import Any, List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.metric import Metric
from metrics_tpu.ops.binned_update import binned_counts
from metrics_tpu.utils.data import METRIC_EPS, to_onehot

Array = jax.Array


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Max recall subject to precision >= min_precision (vectorized, static-shape).

    Parity: reference ``:24-42`` (which iterates ``zip(precision, recall,
    thresholds)`` — i.e. only the first ``len(thresholds)`` curve points count).
    """
    n = thresholds.shape[0]
    p, r = precision[:n], recall[:n]
    valid = p >= min_precision
    masked_recall = jnp.where(valid, r, -jnp.inf)
    # max() tie-break in the reference picks the max (r, p, t) tuple: highest recall,
    # then highest precision, then highest threshold
    best_r = jnp.max(masked_recall)
    tie = masked_recall == best_r
    masked_p = jnp.where(tie, p, -jnp.inf)
    best_p = jnp.max(masked_p)
    tie2 = tie & (masked_p == best_p)
    best_t = jnp.max(jnp.where(tie2, thresholds, -jnp.inf))
    any_valid = jnp.any(valid)
    max_recall = jnp.where(any_valid, best_r, 0.0)
    best_threshold = jnp.where(any_valid, best_t, 0.0)
    best_threshold = jnp.where(max_recall == 0.0, 1e6, best_threshold)
    return max_recall, best_threshold


class BinnedPrecisionRecallCurve(Metric):
    """Precision-recall pairs at T fixed thresholds; states are (C, T) sum counters."""

    is_differentiable = False
    higher_is_better = None

    TPs: Array
    FPs: Array
    FNs: Array

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Array, List[float]] = 100,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = jnp.linspace(0, 1.0, thresholds)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, jax.Array)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            self.thresholds = jnp.asarray(thresholds)
            self.num_thresholds = self.thresholds.size
        else:
            raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name=name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        """preds (N,) or (N, C) probabilities; target (N,) labels or (N, C) binary."""
        preds = jnp.asarray(preds)
        target = jnp.asarray(target)
        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)
        # streaming (N,C)x(T,) count kernel: Pallas on TPU, fused jnp elsewhere
        tps, fps, fns = binned_counts(preds, target == 1, self.thresholds)
        self.TPs = self.TPs + tps
        self.FPs = self.FPs + fps
        self.FNs = self.FNs + fns

    def _stacked_curves(self) -> Tuple[Array, Array]:
        """The curves in stacked ``(C, T+1)`` form — subclasses that reduce
        per class consume THIS (one batched program), not the list form of
        :meth:`compute` (whose per-class split unrolls into C slice eqns)."""
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)
        t_ones = jnp.ones((self.num_classes, 1), dtype=precisions.dtype)
        precisions = jnp.concatenate([precisions, t_ones], axis=1)
        t_zeros = jnp.zeros((self.num_classes, 1), dtype=recalls.dtype)
        recalls = jnp.concatenate([recalls, t_zeros], axis=1)
        return precisions, recalls

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        precisions, recalls = self._stacked_curves()
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], self.thresholds
        return list(precisions), list(recalls), [self.thresholds for _ in range(self.num_classes)]


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Average precision summarised from the binned curve. Parity: reference ``:191``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedAveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> binned_ap = BinnedAveragePrecision(num_classes=1, thresholds=5)
        >>> print(f"{float(binned_ap(preds, target)):.4f}")
        0.8333
    """

    def compute(self) -> Union[List[Array], Array]:
        precisions, recalls, _ = super().compute()
        return _average_precision_compute_with_precision_recall(
            precisions, recalls, self.num_classes, average=None
        )


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall subject to a minimum precision. Parity: reference ``:245``."""

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, Array, List[float]] = 100,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, **kwargs)
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        """Returns (max_recall, best_threshold) per class (scalars for binary).

        The per-class search is one ``vmap`` over the stacked curves — a
        Python loop of ``.at[i].set`` here would emit one HLO slice-update
        chain per class, so program size (and compile time) scaled with
        ``num_classes`` (guarded by
        ``tests/classification/test_binned_compile_size.py``).
        """
        precisions, recalls = self._stacked_curves()
        if self.num_classes == 1:
            return _recall_at_precision(precisions[0], recalls[0], self.thresholds, self.min_precision)
        return jax.vmap(_recall_at_precision, in_axes=(0, 0, None, None))(
            precisions, recalls, self.thresholds, self.min_precision
        )
