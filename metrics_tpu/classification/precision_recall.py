"""Precision and Recall module metrics.

Parity: reference ``torchmetrics/classification/precision_recall.py:23,174``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.precision_recall import _precision_compute, _recall_compute

Array = jax.Array


class Precision(StatScores):
    """Precision = TP / (TP + FP).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Precision
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> precision = Precision()
        >>> print(f"{float(precision(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _precision_compute(tp, fp, fn, self.average, self.mdmc_reduce)


class Recall(StatScores):
    """Recall = TP / (TP + FN).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Recall
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> recall = Recall()
        >>> print(f"{float(recall(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _recall_compute(tp, fp, fn, self.average, self.mdmc_reduce)
