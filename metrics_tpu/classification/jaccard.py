"""JaccardIndex module metric (+ deprecated IoU alias).

Parity: reference ``torchmetrics/classification/jaccard.py:23``, ``iou.py:22``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.jaccard import _jaccard_from_confmat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class JaccardIndex(ConfusionMatrix):
    """Jaccard index (intersection-over-union) from an accumulated confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import JaccardIndex
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> jaccard = JaccardIndex(num_classes=2)
        >>> print(f"{float(jaccard(preds, target)):.4f}")
        0.5833
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        multilabel: bool = False,
        reduction: str = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            multilabel=multilabel,
            **kwargs,
        )
        self.reduction = reduction
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def compute(self) -> Array:
        return _jaccard_from_confmat(
            self.confmat, self.num_classes, self.ignore_index, self.absent_score, self.reduction
        )


class IoU(JaccardIndex):
    """Deprecated alias of JaccardIndex. Parity: reference ``iou.py:22``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`IoU` was renamed to `JaccardIndex` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)
