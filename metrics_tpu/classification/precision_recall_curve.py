"""PrecisionRecallCurve module metric.

Parity: reference ``torchmetrics/classification/precision_recall_curve.py:28``.
Like ``ROC``, an opt-in ``capacity=N`` computes the EXACT curve fully inside
jit/shard_map with fixed-length outputs: tie-group interiors interpolate the
cumulative counts linearly (the standard PR interpolation), group endpoints
are exact, padding repeats the final point (``ops/masked_curves.py``).
"""
from typing import Any, List, Optional, Tuple, Union

import jax

from metrics_tpu.classification._capacity import CapacityCurveStateMixin
from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class PrecisionRecallCurve(CapacityCurveStateMixin, Metric):
    """Precision-recall pairs at distinct thresholds."""

    is_differentiable = False
    higher_is_better = None

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.capacity = capacity
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self._validate_capacity_kwargs(pos_label, None)  # curves average nothing
            self._init_capacity_states()

    def update(self, preds: Array, target: Array) -> None:
        if self.capacity is not None:
            self._capacity_curve_precheck(preds)
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label
        )
        if self.capacity is None:
            self.preds.append(preds)
            self.target.append(target)
            self.num_classes = num_classes
            self.pos_label = pos_label
            return
        self._capacity_curve_write(preds, target)

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        if self.capacity is not None:
            return self._compute_capacity()
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if not self.num_classes:
            raise ValueError(f"`num_classes` bas to be positive number, but got {self.num_classes}")
        return _precision_recall_curve_compute(preds, target, self.num_classes, self.pos_label)

    def _compute_capacity(self) -> Tuple[Array, Array, Array]:
        from metrics_tpu.ops.masked_curves import masked_binary_pr_curve

        return self._compute_capacity_curve_with(masked_binary_pr_curve)
