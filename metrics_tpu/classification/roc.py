"""ROC module metric.

Parity: reference ``torchmetrics/classification/roc.py:24``. Like ``AUROC``,
an opt-in ``capacity=N`` switches to SURVEY §7.1's static-capacity state so the
EXACT curve computes fully inside jit/shard_map: outputs are fixed-length
``(capacity+1,)`` arrays (per class: ``(C, capacity+1)``) whose points overlay
the classic distinct-threshold curve — tie-group interiors are collinear
interpolations, padding repeats the final point — so trapezoid integration and
plotting match the eager curve exactly (``ops/masked_curves.py``).
"""
from typing import Any, List, Optional, Tuple, Union

import jax

from metrics_tpu.classification._capacity import CapacityCurveStateMixin
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class ROC(CapacityCurveStateMixin, Metric):
    """Receiver operating characteristic curve."""

    is_differentiable = False
    higher_is_better = None

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.capacity = capacity
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self._validate_capacity_kwargs(pos_label, None)  # curves average nothing
            self._init_capacity_states()

    def update(self, preds: Array, target: Array) -> None:
        if self.capacity is not None:
            self._capacity_curve_precheck(preds)
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        if self.capacity is None:
            self.preds.append(preds)
            self.target.append(target)
            self.num_classes = num_classes
            self.pos_label = pos_label
            return
        self._capacity_curve_write(preds, target)

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        if self.capacity is not None:
            return self._compute_capacity()
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if not self.num_classes:
            raise ValueError(f"`num_classes` bas to be positive number, but got {self.num_classes}")
        return _roc_compute(preds, target, self.num_classes, self.pos_label)

    def _compute_capacity(self) -> Tuple[Array, Array, Array]:
        from metrics_tpu.ops.masked_curves import masked_binary_roc

        return self._compute_capacity_curve_with(masked_binary_roc)
