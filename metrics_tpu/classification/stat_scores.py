"""StatScores module metric.

Parity: reference ``torchmetrics/classification/stat_scores.py:24-309`` — same
reduce/mdmc_reduce-dependent state layout: fixed sum-counters for micro/macro with
global mdmc (→ a single fused psum on sync), cat-lists for samplewise/samples.
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _stat_scores_compute, _stat_scores_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


class StatScores(Metric):
    """Computes [tp, fp, tn, fn, support] with configurable reduction.

    Args mirror the reference (threshold, top_k, reduce, num_classes, ignore_index,
    mdmc_reduce, multiclass) plus the runtime kwargs (sync_axis etc.).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import StatScores
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> stat_scores = StatScores()
        >>> stat_scores(preds, target).tolist()  # [tp, fp, tn, fn, support]
        [3, 1, 3, 1, 4]
    """

    is_differentiable = False
    higher_is_better = None

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)

        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        if mdmc_reduce != "samplewise" and reduce != "samples":
            zeros_shape = [] if reduce == "micro" else [num_classes]
            default: Any = jnp.zeros(zeros_shape, dtype=jnp.int32)
            reduce_fn: Optional[str] = "sum"
            self._list_states = False
        else:
            default = []
            reduce_fn = "cat"
            self._list_states = True

        for s in ("tp", "fp", "tn", "fn"):
            self.add_state(s, default=default if isinstance(default, list) else default, dist_reduce_fx=reduce_fn)

    def update(self, preds: Array, target: Array) -> None:
        """Update counters from a batch. Parity: reference ``:194-227``."""
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        if not self._list_states:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn
        else:
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        """Concatenate list states if needed. Parity: reference ``:229-235``."""
        tp = dim_zero_cat(self.tp) if isinstance(self.tp, list) else self.tp
        fp = dim_zero_cat(self.fp) if isinstance(self.fp, list) else self.fp
        tn = dim_zero_cat(self.tn) if isinstance(self.tn, list) else self.tn
        fn = dim_zero_cat(self.fn) if isinstance(self.fn, list) else self.fn
        return tp, fp, tn, fn

    def compute(self) -> Array:
        """Return the [..., 5] stat-score tensor. Parity: reference ``:237-309``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)
