"""AUROC module metric.

Parity: reference ``torchmetrics/classification/auroc.py:27`` (cat-list states of
preds/target at :152-153; mode check at compute). List states gather by all_gather at
sync; the exact sort-based compute runs eagerly on the gathered state (the jit-static
alternative is BinnedAveragePrecision / binned curves).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.enums import DataType

Array = jax.Array


class AUROC(Metric):
    """Area under the ROC curve (binary, multiclass ovr, multilabel).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> auroc = AUROC()
        >>> print(f"{float(auroc(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, "macro", "weighted", "micro")
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode: Optional[DataType] = None
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mode = _auroc_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def compute(self) -> Array:
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )
