"""AUROC module metric.

Parity: reference ``torchmetrics/classification/auroc.py:27`` (cat-list states of
preds/target at :152-153; mode check at compute). Two state layouts:

* default — cat-list states exactly like the reference; the exact sort-based
  compute runs eagerly on the gathered state (data-dependent length);
* ``capacity=N`` — SURVEY §7.1's static-capacity mode: a ``(capacity, ...)``
  buffer + valid mask + count, so update, mesh sync (fixed-shape cat
  all_gather) and the EXACT tie-aware compute (``ops/masked_curves.py``) all
  run inside jit/shard_map. Overflowing the capacity yields NaN (in-trace code
  cannot raise; an eager compute also warns). Values match sklearn to f32
  rounding — tested in ``tests/classification/test_capacity_curves.py``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification._capacity import CapacityCurveStateMixin
from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat, to_onehot
from metrics_tpu.utils.enums import DataType

Array = jax.Array


class AUROC(CapacityCurveStateMixin, Metric):
    """Area under the ROC curve (binary, multiclass ovr, multilabel).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> auroc = AUROC()
        >>> print(f"{float(auroc(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True
    # `mode` is latched from the DATA during update and compute refuses to run
    # without it — declared so engine snapshots persist/restore it (same
    # contract as Accuracy; matters for the servable capacity=N layout)
    _host_derived_compute_attrs = ("mode",)

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr
        self.capacity = capacity

        allowed_average = (None, "macro", "weighted", "micro")
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode: Optional[DataType] = None
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            if max_fpr is not None:
                raise ValueError("`max_fpr` is not supported in static-capacity mode (use the default eager mode)")
            self._validate_capacity_kwargs(pos_label, average)
            self._init_capacity_states()

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mode = _auroc_update(preds, target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode
        if self.capacity is None:
            self.preds.append(preds)
            self.target.append(target)
            return

        c = self._capacity_num_columns()
        if (mode == DataType.BINARY) != (c is None):
            raise ValueError(
                "Static-capacity AUROC needs `num_classes` matching the data: leave it unset/1 for"
                f" binary inputs, set it to C for multiclass/multilabel — got num_classes={self.num_classes}"
                f" with {mode} data"
            )
        if c and target.ndim == 1:
            # multiclass (and multidim-multiclass, already flattened by
            # _auroc_update) labels become one-hot columns
            target = to_onehot(target, c)
        self._capacity_write(preds, target)

    def compute(self) -> Array:
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        if self.capacity is not None:
            return self._compute_capacity()
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )

    def _compute_capacity(self) -> Array:
        from metrics_tpu.ops.masked_curves import masked_binary_auroc, masked_multilabel_auroc

        return self._compute_capacity_with(masked_binary_auroc, masked_multilabel_auroc)
