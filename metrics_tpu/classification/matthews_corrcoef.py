"""MatthewsCorrCoef module metric (+ deprecated MatthewsCorrcoef alias).

Parity: reference ``torchmetrics/classification/matthews_corrcoef.py:27,116``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.matthews_corrcoef import (
    _matthews_corrcoef_compute,
    _matthews_corrcoef_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class MatthewsCorrCoef(Metric):
    """Matthews correlation coefficient from an accumulated confusion matrix.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MatthewsCorrCoef
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> mcc = MatthewsCorrCoef(num_classes=2)
        >>> print(f"{float(mcc(preds, target)):.4f}")
        0.5774
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(self, num_classes: int, threshold: float = 0.5, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.threshold = threshold
        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        confmat = _matthews_corrcoef_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _matthews_corrcoef_compute(self.confmat)


class MatthewsCorrcoef(MatthewsCorrCoef):
    """Deprecated alias. Parity: reference ``matthews_corrcoef.py:116``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn(
            "`MatthewsCorrcoef` was renamed to `MatthewsCorrCoef` and it will be removed.", DeprecationWarning
        )
        super().__init__(*args, **kwargs)
