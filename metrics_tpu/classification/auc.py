"""AUC module metric (generic trapezoidal area under x/y points).

Parity: reference ``torchmetrics/classification/auc.py:24``.
"""
from typing import Any

import jax

from metrics_tpu.functional.classification.auc import _auc_compute, _auc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class AUC(Metric):
    """Area under any curve given (x, y) points.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUC
        >>> x = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> y = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        >>> auc = AUC()
        >>> print(f"{float(auc(x, y)):.4f}")
        4.0000
    """

    is_differentiable = False
    higher_is_better = None

    def __init__(self, reorder: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reorder = reorder
        self.add_state("x", default=[], dist_reduce_fx="cat")
        self.add_state("y", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        # arg names match the reference (``classification/auc.py:75``) for
        # kwarg-routing parity; semantically these are the curve's x/y points
        x, y = _auc_update(preds, target)
        self.x.append(x)
        self.y.append(y)

    def compute(self) -> Array:
        x = dim_zero_cat(self.x)
        y = dim_zero_cat(self.y)
        return _auc_compute(x, y, reorder=self.reorder)
