"""AveragePrecision module metric.

Parity: reference ``torchmetrics/classification/avg_precision.py:28``.
"""
from typing import Any, List, Optional, Union

import jax

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class AveragePrecision(Metric):
    """Average precision (area under the PR curve by step integration).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> average_precision = AveragePrecision()
        >>> print(f"{float(average_precision(preds, target)):.4f}")
        0.8333
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Array, List[Array]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if not self.num_classes:
            raise ValueError(f"`num_classes` bas to be positive number, but got {self.num_classes}")
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)
