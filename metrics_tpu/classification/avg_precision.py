"""AveragePrecision module metric.

Parity: reference ``torchmetrics/classification/avg_precision.py:28``. Like
``AUROC``, an opt-in ``capacity=N`` switches to SURVEY §7.1's static-capacity
state (buffer + valid mask) so the exact step-integrated AP runs inside
jit/shard_map (``ops/masked_curves.py``); overflow yields NaN.
"""
from typing import Any, List, Optional, Union

import jax

from metrics_tpu.classification._capacity import CapacityCurveStateMixin
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat, to_onehot

Array = jax.Array


class AveragePrecision(CapacityCurveStateMixin, Metric):
    """Average precision (area under the PR curve by step integration).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> average_precision = AveragePrecision()
        >>> print(f"{float(average_precision(preds, target)):.4f}")
        0.8333
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self.capacity = capacity
        if capacity is None:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")
        else:
            self._validate_capacity_kwargs(pos_label, average)
            self._init_capacity_states()

    def update(self, preds: Array, target: Array) -> None:
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        if self.capacity is None:
            self.preds.append(preds)
            self.target.append(target)
            self.num_classes = num_classes
            self.pos_label = pos_label
            return

        c = self._capacity_num_columns()
        if (preds.ndim == 1) != (c is None):
            raise ValueError(
                "Static-capacity AveragePrecision needs `num_classes` matching the data: leave it"
                f" unset/1 for binary inputs, set it to C for multiclass — got num_classes="
                f"{self.num_classes} with preds of shape {preds.shape}"
            )
        if c and target.ndim == 1:
            target = to_onehot(target, c)
        self._capacity_write(preds, target)

    def compute(self) -> Union[Array, List[Array]]:
        if self.capacity is not None:
            return self._compute_capacity()
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        if not self.num_classes:
            raise ValueError(f"`num_classes` bas to be positive number, but got {self.num_classes}")
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)

    def _compute_capacity(self) -> Array:
        from metrics_tpu.ops.masked_curves import (
            masked_binary_average_precision,
            masked_multilabel_average_precision,
        )

        return self._compute_capacity_with(
            masked_binary_average_precision, masked_multilabel_average_precision
        )
