"""Accuracy module metric.

Parity: reference ``torchmetrics/classification/accuracy.py:31-277`` (extends
StatScores; adds subset-accuracy correct/total counters and per-instance mode
tracking).
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.accuracy import (
    _accuracy_compute,
    _accuracy_update,
    _check_subset_validity,
    _mode,
    _subset_accuracy_compute,
    _subset_accuracy_update,
)
from metrics_tpu.utils.enums import DataType

Array = jax.Array


class Accuracy(StatScores):
    """Accuracy (micro/macro/weighted/samples, top-k, subset accuracy).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> accuracy = Accuracy()
        >>> print(f"{float(accuracy(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    # `mode` is latched from the DATA during update (host side, outside the
    # state pytree) and compute refuses to run without it — declare it so
    # engine snapshots persist/restore it (no post-restore batch needed)
    _host_derived_compute_attrs = ("mode",)
    higher_is_better = True

    def __init__(
        self,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        average: str = "micro",
        mdmc_average: Optional[str] = "global",
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        subset_accuracy: bool = False,
        **kwargs: Any,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average
        self.add_state("correct", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.subset_accuracy = subset_accuracy
        self.mode: Optional[DataType] = None

    def update(self, preds: Array, target: Array) -> None:
        """Parity: reference ``accuracy.py:218-268``."""
        mode = _mode(preds, target, self.threshold, self.top_k, self.num_classes, self.multiclass)
        if not self.mode:
            self.mode = mode
        elif self.mode != mode:
            raise ValueError(f"You can not use {mode} inputs with {self.mode} inputs.")

        if self.subset_accuracy and not _check_subset_validity(self.mode):
            self.subset_accuracy = False

        if self.subset_accuracy:
            correct, total = _subset_accuracy_update(
                preds, target, threshold=self.threshold, top_k=self.top_k,
                num_classes=self.num_classes, multiclass=self.multiclass,
            )
            self.correct = self.correct + correct
            self.total = self.total + total
        else:
            tp, fp, tn, fn = _accuracy_update(
                preds,
                target,
                reduce=self.reduce,
                mdmc_reduce=self.mdmc_reduce,
                threshold=self.threshold,
                num_classes=self.num_classes,
                top_k=self.top_k,
                multiclass=self.multiclass,
                ignore_index=self.ignore_index,
                mode=self.mode,
            )
            if not self._list_states:
                self.tp = self.tp + tp
                self.fp = self.fp + fp
                self.tn = self.tn + tn
                self.fn = self.fn + fn
            else:
                self.tp.append(tp)
                self.fp.append(fp)
                self.tn.append(tn)
                self.fn.append(fn)

    def compute(self) -> Array:
        """Parity: reference ``accuracy.py:270-277``."""
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        if self.subset_accuracy:
            return _subset_accuracy_compute(self.correct, self.total)
        tp, fp, tn, fn = self._get_final_stats()
        return _accuracy_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce, self.mode)
