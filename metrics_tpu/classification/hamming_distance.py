"""HammingDistance module metric.

Parity: reference ``torchmetrics/classification/hamming_distance.py:23``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.hamming_distance import (
    _hamming_distance_compute,
    _hamming_distance_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class HammingDistance(Metric):
    """Average Hamming distance (loss) between targets and predictions.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HammingDistance
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> hamming = HammingDistance()
        >>> print(f"{float(hamming(preds, target)):.4f}")
        0.2500
    """

    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        threshold: float = 0.5,
        num_classes: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.add_state("correct", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.threshold = threshold
        # static-shape hints (this build's jit contract); not in the reference
        self.num_classes = num_classes
        self.multiclass = multiclass

    def update(self, preds: Array, target: Array) -> None:
        correct, total = _hamming_distance_update(
            preds, target, self.threshold, self.num_classes, self.multiclass
        )
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> Array:
        return _hamming_distance_compute(self.correct, self.total)
