"""FBeta / F1 module metrics.

Parity: reference ``torchmetrics/classification/f_beta.py:25,178,306`` (FBeta,
F1Score, deprecated alias F1).
"""
from typing import Any, Optional

import jax

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.f_beta import _fbeta_compute
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class FBeta(StatScores):
    """F-beta score with configurable beta.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import FBeta
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> fbeta = FBeta(beta=0.5)
        >>> print(f"{float(fbeta(preds, target)):.4f}")
        0.7500
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: Optional[int] = None,
        beta: float = 1.0,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        self.beta = beta
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            **kwargs,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _fbeta_compute(tp, fp, tn, fn, self.beta, self.ignore_index, self.average, self.mdmc_reduce)


class F1Score(FBeta):
    """F1 = F-beta with beta=1.0.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import F1Score
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> f1 = F1Score()
        >>> print(f"{float(f1(preds, target)):.4f}")
        0.7500
    """

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            beta=1.0,
            threshold=threshold,
            average=average,
            mdmc_average=mdmc_average,
            ignore_index=ignore_index,
            top_k=top_k,
            multiclass=multiclass,
            **kwargs,
        )


class F1(F1Score):
    """Deprecated alias of F1Score. Parity: reference ``f_beta.py:306``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`F1` was renamed to `F1Score` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)
