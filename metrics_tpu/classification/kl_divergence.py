"""KLDivergence module metric.

Parity: reference ``torchmetrics/classification/kl_divergence.py:24``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.kl_divergence import _kld_compute, _kld_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class KLDivergence(Metric):
    """KL divergence D_KL(P||Q) with mean/sum/none reduction.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import KLDivergence
        >>> p = jnp.asarray([[0.3, 0.7], [0.6, 0.4]])
        >>> q = jnp.asarray([[0.5, 0.5], [0.5, 0.5]])
        >>> kl = KLDivergence()
        >>> print(f"{float(kl(p, q)):.4f}")
        0.0512
    """

    is_differentiable = True
    higher_is_better = False

    def __init__(self, log_prob: bool = False, reduction: Optional[str] = "mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.log_prob = log_prob

        allowed_reduction = ["mean", "sum", "none", None]
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction

        if self.reduction in ["mean", "sum"]:
            self.add_state("measures", jnp.asarray(0.0), dist_reduce_fx="sum")
        else:
            self.add_state("measures", [], dist_reduce_fx="cat")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, p: Array, q: Array) -> None:
        measures, total = _kld_update(p, q, self.log_prob)
        if self.reduction is None or self.reduction == "none":
            self.measures.append(measures)
        else:
            self.measures = self.measures + jnp.sum(measures)
            self.total = self.total + total

    def compute(self) -> Array:
        measures = dim_zero_cat(self.measures) if self.reduction in (None, "none") else self.measures
        return _kld_compute(measures, self.total, self.reduction)
