from metrics_tpu.classification.accuracy import Accuracy
from metrics_tpu.classification.auc import AUC
from metrics_tpu.classification.auroc import AUROC
from metrics_tpu.classification.avg_precision import AveragePrecision
from metrics_tpu.classification.binned_precision_recall import (
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
)
from metrics_tpu.classification.calibration_error import CalibrationError
from metrics_tpu.classification.cohen_kappa import CohenKappa
from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.classification.f_beta import F1, F1Score, FBeta
from metrics_tpu.classification.hamming_distance import HammingDistance
from metrics_tpu.classification.hinge import Hinge, HingeLoss
from metrics_tpu.classification.jaccard import IoU, JaccardIndex
from metrics_tpu.classification.kl_divergence import KLDivergence
from metrics_tpu.classification.matthews_corrcoef import MatthewsCorrcoef, MatthewsCorrCoef
from metrics_tpu.classification.precision_recall import Precision, Recall
from metrics_tpu.classification.precision_recall_curve import PrecisionRecallCurve
from metrics_tpu.classification.roc import ROC
from metrics_tpu.classification.specificity import Specificity
from metrics_tpu.classification.stat_scores import StatScores
