from metrics_tpu.classification.accuracy import Accuracy
from metrics_tpu.classification.f_beta import F1, F1Score, FBeta
from metrics_tpu.classification.hamming_distance import HammingDistance
from metrics_tpu.classification.precision_recall import Precision, Recall
from metrics_tpu.classification.specificity import Specificity
from metrics_tpu.classification.stat_scores import StatScores
