"""CohenKappa module metric.

Parity: reference ``torchmetrics/classification/cohen_kappa.py:23``.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.cohen_kappa import _cohen_kappa_compute, _cohen_kappa_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CohenKappa(Metric):
    """Cohen's kappa with optional linear/quadratic weighting.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CohenKappa
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> kappa = CohenKappa(num_classes=2)
        >>> print(f"{float(kappa(preds, target)):.4f}")
        0.5000
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        num_classes: int,
        weights: Optional[str] = None,
        threshold: float = 0.5,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.weights = weights
        self.threshold = threshold

        allowed_weights = ("linear", "quadratic", "none", None)
        if weights not in allowed_weights:
            raise ValueError(f"Argument weights needs to one of the following: {allowed_weights}")

        self.add_state("confmat", default=jnp.zeros((num_classes, num_classes), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        confmat = _cohen_kappa_update(preds, target, self.num_classes, self.threshold)
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _cohen_kappa_compute(self.confmat, self.weights)
