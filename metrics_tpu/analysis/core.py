"""Findings, reports, baselines — the shared vocabulary of both analysis planes.

A **finding** is one violated invariant: a stable rule id, a severity, a
location (``where`` — a program name or ``file:line``), an optional structural
path into the program (``path`` — the eqn/op chain for program-plane findings),
a one-line message and a fix hint. Findings are DATA, not exceptions: rules
return lists of them, the CLI (``tools/analyze.py``) renders/serializes them,
and tests assert on them — the same rule object backs the CI gate and the
regression suites that used to pin these invariants ad hoc.

Two escape hatches keep the gate honest instead of noisy:

* **Inline suppressions** (source plane): ``# analysis: disable=rule-id --
  reason`` on the offending line (or the line directly above) suppresses that
  rule there. The reason is REQUIRED — a disable without one is itself a
  finding (``suppression-missing-reason``), so every silenced warning carries
  its justification in the diff that silenced it.
* **Baseline file** (both planes): a committed JSON map of finding keys to
  reasons (``tools/analysis_baseline.json``). The gate subtracts baselined
  findings, so it starts green on an imperfect tree and RATCHETS — new
  findings fail CI, old ones are visible debt with a written reason. An entry
  without a reason fails the gate too (zero unexplained baseline entries).
"""
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Finding",
    "Report",
    "Baseline",
    "filter_suppressed",
    "parse_suppressions",
    "SUPPRESS_RE",
]

#: ``# analysis: disable=rule-a,rule-b -- why this is fine here``
SUPPRESS_RE = re.compile(
    r"#\s*analysis:\s*disable=(?P<rules>[\w,-]+)(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One violated invariant, locatable and stable under re-runs."""

    rule: str               # rule id, e.g. "no-collectives-in-deferred-step"
    severity: str           # "error" | "warning"
    where: str              # program name or "path/to/file.py:LINE"
    message: str            # what is wrong, with the concrete evidence
    path: str = ""          # eqn/op path inside the program ("" for source findings)
    hint: str = ""          # how to fix (or why this class of bug matters)

    def key(self) -> str:
        """Stable identity for baselining: rule + location (not the message,
        which may carry counts that drift)."""
        return f"{self.rule}|{self.where}|{self.path}"

    def render(self) -> str:
        loc = f"{self.where}" + (f" [{self.path}]" if self.path else "")
        out = f"{self.severity.upper():7s} {self.rule}: {loc}\n        {self.message}"
        if self.hint:
            out += f"\n        hint: {self.hint}"
        return out


@dataclass
class Report:
    """An ordered bag of findings plus non-finding notes (skipped checks)."""

    findings: List[Finding] = field(default_factory=list)
    notes: List[str] = field(default_factory=list)

    def extend(self, findings: Iterable[Finding]) -> "Report":
        self.findings.extend(findings)
        return self

    def note(self, msg: str) -> None:
        self.notes.append(msg)

    def merge(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.notes.extend(other.notes)
        return self

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, Any]:
        return {
            "findings": [
                {
                    "rule": f.rule, "severity": f.severity, "where": f.where,
                    "path": f.path, "message": f.message, "hint": f.hint,
                    "key": f.key(),
                }
                for f in self.findings
            ],
            "notes": list(self.notes),
        }

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines += [f"note: {n}" for n in self.notes]
        if not self.findings:
            lines.append("no findings")
        return "\n".join(lines)


class Baseline:
    """The committed debt ledger: ``{finding_key: reason}``.

    ``filter`` splits findings into (new, baselined); keys present in the
    file but carrying no reason are surfaced as findings themselves — the
    gate's "zero unexplained baseline entries" contract.
    """

    def __init__(self, entries: Optional[Dict[str, str]] = None, path: str = ""):
        self.entries: Dict[str, str] = dict(entries or {})
        self.path = path

    @classmethod
    def load(cls, path: Optional[str]) -> "Baseline":
        if not path or not os.path.exists(path):
            return cls({}, path or "")
        with open(path) as fh:
            raw = json.load(fh)
        if not isinstance(raw, dict):
            raise ValueError(f"baseline {path} must be a JSON object of key->reason")
        return cls({str(k): str(v or "") for k, v in raw.items()}, path)

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        with open(path, "w") as fh:
            json.dump(self.entries, fh, indent=2, sort_keys=True)
            fh.write("\n")
        return path

    def unexplained(self) -> List[str]:
        # a TODO placeholder (what --write-baseline seeds) is NOT an
        # explanation — counting it as one would let the gate go green
        # forever with the debt never justified
        return sorted(
            k for k, reason in self.entries.items()
            if not reason.strip() or reason.strip().upper().startswith("TODO")
        )

    def filter(self, findings: Iterable[Finding]) -> Tuple[List[Finding], List[Finding]]:
        new, old = [], []
        for f in findings:
            (old if f.key() in self.entries else new).append(f)
        return new, old


def filter_suppressed(
    findings: Iterable[Finding],
    suppressions_by_file: Dict[str, Dict[int, Tuple[Tuple[str, ...], str, int]]],
) -> List[Finding]:
    """Apply inline suppressions to findings — the ONE implementation of the
    directive contract, shared by the source and concurrency planes: a
    reasoned directive silences the named rules on the lines it covers; an
    unreasoned one suppresses nothing and is itself a finding (reported once
    per directive). ``suppressions_by_file`` maps the filename part of each
    finding's ``where`` to that file's :func:`parse_suppressions` table."""
    kept: List[Finding] = []
    reasonless_reported: set = set()
    for f in findings:
        try:
            fn, line_s = f.where.rsplit(":", 1)
            line = int(line_s)
        except (IndexError, ValueError):
            kept.append(f)
            continue
        entry = suppressions_by_file.get(fn, {}).get(line)
        if entry is None or f.rule not in entry[0]:
            kept.append(f)
            continue
        rules_listed, reason, directive_line = entry
        if not reason:
            kept.append(f)  # an unreasoned directive suppresses nothing
            if (fn, directive_line) not in reasonless_reported:
                reasonless_reported.add((fn, directive_line))
                kept.append(Finding(
                    rule="suppression-missing-reason", severity="error",
                    where=f"{fn}:{directive_line}",
                    message=(
                        f"`# analysis: disable={','.join(rules_listed)}` has no "
                        "`-- reason`"
                    ),
                    hint="suppressions document debt: say why this occurrence is safe",
                ))
    return kept


def parse_suppressions(source: str) -> Dict[int, Tuple[Tuple[str, ...], str, int]]:
    """Map line number -> (rule ids, reason, directive line) for every line a
    suppression covers: the directive's own line AND the line below it (so a
    comment directly above the offending statement works for long lines)."""
    out: Dict[int, Tuple[Tuple[str, ...], str, int]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(",") if r.strip())
        reason = (m.group("reason") or "").strip()
        entry = (rules, reason, i)
        out[i] = entry
        # ONLY a comment-only directive line suppresses the NEXT line; a
        # directive trailing a statement covers that statement alone —
        # otherwise it would silently swallow an independent violation on
        # the following line with no reason attached to it
        if line.lstrip().startswith("#"):
            out.setdefault(i + 1, entry)
    return out
