"""Static analysis: declarative jaxpr/HLO invariants + repo-wide trace lint.

This repo's performance and correctness story rests on STRUCTURAL program
properties — collective placement per sync mode, scatter-free Pallas
lowerings, honored donations, fingerprint-covered trace constants, fused
arena packs, the closed program set — that used to be pinned ad hoc, one
regex or jaxpr walk per test file. This package makes each of them a named,
reusable rule with structured findings (rule id, severity, eqn/op path, fix
hint), evaluated by three planes:

* **Program plane** (:mod:`~metrics_tpu.analysis.program` +
  :mod:`~metrics_tpu.analysis.rules`): walk traced jaxprs (recursing into
  ``pjit``/``pallas_call``/``scan`` sub-programs via the PR-1 cost-walk
  traversal) and compiled HLO text. :class:`EngineAnalysis`\\ ``.check(engine)``
  audits any built engine.
* **Source plane** (:mod:`~metrics_tpu.analysis.source`): an AST lint over
  ``metrics_tpu/`` for the known trace-hazard classes — Python branches on
  traced values, closure-identity trace-cache reuse, lock discipline in the
  engine, tuple-message raises, wall-clock/RNG in jitted builders.
* **Concurrency plane** (:mod:`~metrics_tpu.analysis.concurrency` +
  :mod:`~metrics_tpu.analysis.rules.locks`): per-class lock declarations
  (which attributes each engine lock guards, which methods run lock-held,
  whether dispatch is legal under a hold) checked package-wide by four
  rules — lockset, lock-order (may-acquire-under cycles + forbidden
  nestings), no-dispatch-under-lock, check-then-act.

One CLI drives both as the CI gate: ``python tools/analyze.py`` (wired as
``make analyze``), with ``# analysis: disable=rule -- reason`` suppressions
and a committed baseline that starts green and ratchets. Rule catalog:
``docs/analysis.md``.
"""
from metrics_tpu.analysis.concurrency import (
    FORBIDDEN_NESTINGS,
    check_concurrency_sources,
    check_concurrency_tree,
)
from metrics_tpu.analysis.core import Baseline, Finding, Report
from metrics_tpu.analysis.program import (
    EngineAnalysis,
    iter_eqns,
    primitive_counts,
    primitive_names,
    trace_primitive_counts,
)
from metrics_tpu.analysis.rules import (
    COLLECTIVE_PRIMITIVES,
    CONCURRENCY_SPECS,
    RULES,
    RuleInfo,
    check_arena_pack_fused,
    check_collective_multiset,
    check_compile_cap,
    check_donation_honored,
    check_megastep_launch_count,
    check_no_baked_host_constants,
    check_no_collectives,
    check_no_scatter_under_pallas,
    check_pallas_call_count,
    check_quantized_policy_honored,
    collective_counts,
    expected_step_sync_collectives,
    expected_sync_payload,
    hlo_collective_counts,
)
from metrics_tpu.analysis.source import check_source_text, check_source_tree

__all__ = [
    "Baseline",
    "COLLECTIVE_PRIMITIVES",
    "CONCURRENCY_SPECS",
    "EngineAnalysis",
    "FORBIDDEN_NESTINGS",
    "Finding",
    "Report",
    "RULES",
    "RuleInfo",
    "check_arena_pack_fused",
    "check_concurrency_sources",
    "check_concurrency_tree",
    "check_collective_multiset",
    "check_compile_cap",
    "check_donation_honored",
    "check_megastep_launch_count",
    "check_no_baked_host_constants",
    "check_no_collectives",
    "check_no_scatter_under_pallas",
    "check_pallas_call_count",
    "check_quantized_policy_honored",
    "check_source_text",
    "check_source_tree",
    "collective_counts",
    "expected_step_sync_collectives",
    "expected_sync_payload",
    "hlo_collective_counts",
    "iter_eqns",
    "primitive_counts",
    "primitive_names",
    "trace_primitive_counts",
]
