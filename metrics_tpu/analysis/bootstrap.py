"""Bootstrap engine matrix for the program-plane CI gate (``make analyze``).

``tools/analyze.py`` cannot audit the engines a user will build — it audits a
REPRESENTATIVE matrix spanning every serving mode the rules discriminate:

    {step, deferred} x {arena, per-leaf} x {single, multistream}
                     x kernel backends {xla, pallas_interpret}

"step" runs meshless (the default serving shape; step-sync mesh placement is
covered by the 8-device ``make mesh-smoke`` — bootstrapping a virtual mesh
here would double that gate); "deferred" runs on a 1-device mesh, which
lowers the REAL shard-local step and boundary merge programs (the same
trace the 8-device mesh compiles, minus devices — exactly what the jaxpr
rules inspect). The stream-SHARDED serving mode (ISSUE 9) joins the matrix
the same way: a 1-device-mesh ``stream_shard=True`` MultiStreamEngine with a
resident cap below its stream count, so the audited routed step is the real
paged-arena program (slot-addressed segmented update over ``(world,
resident, n)`` buffers) — the ``no-collectives-in-deferred-step`` rule then
pins the routed path at jaxpr AND HLO level exactly like the deferred one.
Each engine serves a few ragged batches so its program set is built, then
``EngineAnalysis.check`` runs the full rule set. CPU-safe by construction;
the whole matrix is small buckets and tiny traffic.
"""
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["bootstrap_engines", "analyze_bootstrap_matrix"]

_BACKENDS = ("xla", "pallas_interpret")


def bootstrap_engines(
    backends: Iterable[str] = _BACKENDS,
) -> List[Tuple[str, object]]:
    """Build + drive the matrix; returns ``(label, engine)`` pairs with every
    engine's program set compiled (traffic served, result read)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import EngineConfig, MultiStreamEngine, StreamingEngine

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    rng = np.random.RandomState(0)
    batches = [
        (rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in (5, 8, 3, 6)
    ]

    out: List[Tuple[str, object]] = []
    for backend in backends:
        for sync in ("step", "deferred"):
            mesh_kw = (
                {"mesh": mesh, "axis": "dp", "mesh_sync": "deferred"}
                if sync == "deferred"
                else {}
            )
            for arena in (True, False):
                for kind in ("single", "multistream"):
                    label = f"{sync}/{'arena' if arena else 'per-leaf'}/{kind}/{backend}"
                    cfg = EngineConfig(
                        buckets=(8,), use_arena=arena, kernel_backend=backend, **mesh_kw
                    )
                    if kind == "single":
                        engine = StreamingEngine(
                            MetricCollection([Accuracy(), MeanSquaredError()]), cfg
                        )
                    else:
                        engine = MultiStreamEngine(Accuracy(), num_streams=2, config=cfg)
                    with engine:
                        for i, b in enumerate(batches):
                            if kind == "multistream":
                                engine.submit(i % 2, *b)
                            else:
                                engine.submit(*b)
                        if kind == "multistream":
                            engine.result(0)
                        else:
                            engine.result()
                    out.append((label, engine))
        # stream-sharded paged serving (ISSUE 9): resident cap below the
        # stream count, so the audited step is the REAL slot-addressed paged
        # program and the traffic actually exercises the pager
        engine = MultiStreamEngine(
            Accuracy(), num_streams=4,
            config=EngineConfig(
                buckets=(8,), kernel_backend=backend,
                mesh=mesh, axis="dp", mesh_sync="deferred",
            ),
            stream_shard=True, resident_streams=2,
        )
        with engine:
            for i, b in enumerate(batches):
                engine.submit(i % 4, *b)
            engine.result(0)
            engine.results()
        out.append((f"sshard/arena/multistream/{backend}", engine))
        # POST-RESHARD engine (ISSUE 11): a live reshard() rebuilds every
        # program against the new topology — the audited programs here are
        # the ones a resharded engine actually serves with, so a reshard
        # that smuggled a collective into the steady step (or broke arena
        # fusion) fails the same named rules as a fresh build (broken-
        # fixture proof: tests/analysis/test_engine_audit.py).
        engine = StreamingEngine(
            MetricCollection([Accuracy(), MeanSquaredError()]),
            EngineConfig(
                buckets=(8,), kernel_backend=backend,
                mesh=mesh, axis="dp", mesh_sync="deferred",
            ),
        )
        with engine:
            for b in batches[:2]:
                engine.submit(*b)
            engine.flush()
            engine.reshard(world=1)  # full snapshot->swap->restore cycle
            for b in batches[2:]:
                engine.submit(*b)
            engine.result()
        out.append((f"reshard/arena/single/{backend}", engine))
        # FLEET host engine (ISSUE 15): a degenerate 1-host FleetEngine whose
        # per-host ingestion engine runs a 1-device LOCAL deferred mesh —
        # the audited steady step is the REAL collective-free shard-local
        # program a fleet host serves with (the fleet axis only ever appears
        # in the boundary fold), so `no-collectives-in-deferred-step` pins
        # the fleet contract at jaxpr AND HLO level (broken-fixture proof: a
        # psum smuggled into the fleet host's traced update fails the rule —
        # tests/analysis/test_engine_audit.py)
        from metrics_tpu.engine.fleet import FleetConfig, FleetEngine

        fleet = FleetEngine(
            Accuracy(),
            FleetConfig(
                num_streams=2,
                engine=EngineConfig(
                    buckets=(8,), kernel_backend=backend,
                    mesh=mesh, axis="dp", mesh_sync="deferred",
                ),
            ),
        )
        with fleet:
            for i, b in enumerate(batches):
                fleet.ingest(i % 2, *b)
            fleet.results()
        out.append((f"fleet/arena/multistream/{backend}", fleet.engine))
        # STREAM-SHARDED WINDOWED FLEET host (ISSUE 20): the tenancy
        # configuration — a paged, pane-extended arena whose rotations ride
        # the shared plan cursor — serves through the same routed steady
        # step, so the audited program set is the one a fleet-scale tenant
        # host actually runs: collective-free slot-addressed updates (the
        # hierarchical fold's cross leg lives ONLY in the boundary
        # programs). Broken-fixture proof: a psum smuggled into this routed
        # step fails `no-collectives-in-deferred-step` —
        # tests/analysis/test_engine_audit.py.
        from metrics_tpu.engine import WindowPolicy

        fleet = FleetEngine(
            Accuracy(),
            FleetConfig(
                num_streams=4, stream_shard=True, resident_streams=2,
                engine=EngineConfig(
                    buckets=(8,), kernel_backend=backend,
                    mesh=mesh, axis="dp", mesh_sync="deferred",
                    window=WindowPolicy.tumbling(pane_batches=2, n_panes=2),
                ),
            ),
        )
        with fleet:
            for i, b in enumerate(batches):
                fleet.ingest(i % 4, *b)
            fleet.results()
        out.append((f"fleet-sshard/arena/multistream/{backend}", fleet.engine))
        # WINDOWED engine (ISSUE 13): a sliding pane ring driven through TWO
        # real rotations — the audited step is the runtime-pane-indexed
        # ring update ((panes, n) carried buffers, one dynamic-update per
        # dtype), the fold/rotate programs are in the owned set, and the
        # compile-cap rule pins that two rotations compiled NOTHING new (a
        # rotation that retraced would blow the windowed cap; broken-fixture
        # proof: tests/analysis/test_engine_audit.py)
        from metrics_tpu.engine import WindowPolicy

        engine = StreamingEngine(
            MetricCollection([Accuracy(), MeanSquaredError()]),
            EngineConfig(
                buckets=(8,), kernel_backend=backend, coalesce=1,
                window=WindowPolicy.sliding(n_panes=2, pane_batches=2),
            ),
        )
        with engine:
            for b in batches:  # 4 batches -> rotations at 2 and 4
                engine.submit(*b)
            engine.result()
        out.append((f"windowed/arena/single/{backend}", engine))
        # RAGGED engine (ISSUE 17): group-keyed ingestion — the audited step
        # is the REAL grouped capacity write (one stable lexsort + mode="drop"
        # scatters over (groups, cap) buffers) on a 1-device deferred mesh,
        # so `no-collectives-in-deferred-step` pins the grouped steady step
        # at jaxpr AND HLO level exactly like the dense engines (broken-
        # fixture proof: a psum smuggled into the grouped step fails the
        # rule — tests/analysis/test_engine_audit.py). The served aggregate()
        # compiles the DEVICE fold program (ISSUE 18), so the audit also
        # walks the re-traced batched-read aggregate: no host callbacks, no
        # collectives, bounded kernel launches (broken-fixture proof: a
        # pure_callback smuggled into grouped_batch_scores fails
        # `no-host-callback-in-aggregate` — tests/analysis/test_engine_audit.py)
        from metrics_tpu import RetrievalMAP
        from metrics_tpu.engine import RaggedEngine

        engine = RaggedEngine(
            RetrievalMAP(), num_groups=4,
            config=EngineConfig(
                buckets=(8,), kernel_backend=backend,
                mesh=mesh, axis="dp", mesh_sync="deferred",
            ),
            capacity=16,
        )
        with engine:
            for i, (p, t) in enumerate(batches):
                gids = (np.arange(p.shape[0]) % 4).astype(np.int32)
                engine.submit(gids, p, t.astype(np.float32))
            engine.result(0)
            engine.aggregate()
        out.append((f"ragged/arena/grouped/{backend}", engine))
    # MEGASTEP engines (ISSUE 16): the whole-step fused tier joins the matrix
    # outside the backend loop — megastep is arena-only and opt-in (the
    # interpret tier refuses ineligible layouts outright), so the per-leaf /
    # unsharded-multistream axes of the grid do not apply. Two serving shapes
    # cover the two fused forms: the single-engine FOLD grid, and the
    # stream-sharded SEGMENT grid with q8-resident cold rows (compressed
    # spills seated by the in-grid decode-on-touch). The megastep rule forms
    # (`pallas-call-per-leaf` megastep pin, `arena-pack-fused` fused-pack
    # pin) key off these engines' resolved backend.
    engine = StreamingEngine(
        MetricCollection([Accuracy(), MeanSquaredError()]),
        EngineConfig(buckets=(8,), kernel_backend="megastep_interpret"),
    )
    with engine:
        for b in batches:
            engine.submit(*b)
        engine.result()
    out.append(("step/arena/single/megastep_interpret", engine))
    engine = MultiStreamEngine(
        Accuracy(), num_streams=4,
        config=EngineConfig(
            buckets=(8,), kernel_backend="megastep_interpret",
            mesh=mesh, axis="dp", mesh_sync="deferred", compress_payloads=True,
        ),
        stream_shard=True, resident_streams=2,
    )
    with engine:
        for i, b in enumerate(batches):
            engine.submit(i % 4, *b)
        engine.result(0)
        engine.results()
    out.append(("sshard/arena/multistream/megastep_interpret", engine))
    # EMBEDDED-MODEL HOST engine (ISSUE 19): a deferred 1-device engine whose
    # traffic is FEATURES served by a pipeline-staged encoder ModelHost — the
    # audited steady metric step stays collective-free exactly like every
    # other deferred engine, while the host's OWN stage program (re-traced
    # from its recorded abstract signature) is audited against its declared
    # ppermute-only allowance by `host-collectives-pinned` (broken-fixture
    # proof: widening the forward with an undeclared psum — or clearing the
    # allowance under the real ppermute handoff — fails the rule:
    # tests/analysis/test_engine_audit.py)
    from metrics_tpu.engine import ModelHostConfig, encoder_host

    def _stage_fn(w, x):
        return x @ w

    host = encoder_host(
        stage_fn=_stage_fn,
        stage_params=np.eye(4, dtype=np.float32)[None] * 1.5,
        config=ModelHostConfig(
            buckets=(8,), mesh=mesh, coalesce_window_ms=0.0
        ),
        fingerprint="bootstrap-pipeline-encoder",
        shared=False,
    )
    engine = StreamingEngine(
        MeanSquaredError(),
        EngineConfig(
            buckets=(8,), mesh=mesh, axis="dp", mesh_sync="deferred"
        ),
    )
    engine.model_host = host
    with engine:
        for p, t in batches:
            ids = np.tile(p[:, None], (1, 4)).astype(np.float32)
            feats = host.infer(ids, np.ones_like(ids))
            engine.submit(np.asarray(feats).mean(axis=1), t.astype(np.float32))
        engine.result()
    host.close()
    out.append(("modelhost/arena/single/xla", engine))
    return out


def analyze_bootstrap_matrix(backends: Iterable[str] = _BACKENDS):
    """Run :class:`~metrics_tpu.analysis.program.EngineAnalysis` over the
    whole matrix; returns one merged Report."""
    from metrics_tpu.analysis.core import Report
    from metrics_tpu.analysis.program import EngineAnalysis

    report = Report()
    analysis = EngineAnalysis()
    engines = bootstrap_engines(backends)
    for label, engine in engines:
        report.merge(analysis.check(engine, label=label))
    report.note(f"program plane: {len(engines)} bootstrap engines audited")
    return report
