"""Program-plane engine: walk jaxprs/HLO, evaluate rules, audit built engines.

Two layers:

* **Walkers** — :func:`iter_eqns` recurses through every sub-jaxpr one
  equation can carry (``pjit``/``scan``/``while`` bodies, ``cond`` branches,
  ``pallas_call`` kernel bodies), reusing the PR-1 cost-walk's sub-program
  discovery (``ops/profiling.py::eqn_subjaxprs``) so the analyzer and the
  profiler can never disagree about what counts as "inside the program".
  :func:`trace_primitive_counts` traces a callable with a FRESH closure per
  call — the safe form of "what does this lower to?" that cannot hit the
  closure-identity trace cache (the PR-4 footgun).

* **:class:`EngineAnalysis`** — audit any BUILT engine that has served
  traffic: every memoized update program is re-traced to a jaxpr (from the
  memo key's abstract signature — no live data needed) and paired with its
  compiled HLO, then the applicable rules run: collective placement per sync
  mode, scatter/pallas invariants per kernel backend, donation aliasing,
  arena fusion, host-constant/fingerprint coverage, and the compile cap.
  ``EngineAnalysis().check(engine)`` returns a :class:`~metrics_tpu.analysis.
  core.Report`; ``tools/analyze.py`` drives it over the bootstrap matrix as
  the CI gate.
"""
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from metrics_tpu.analysis.core import Finding, Report
from metrics_tpu.ops.profiling import eqn_subjaxprs

__all__ = [
    "EngineAnalysis",
    "iter_eqns",
    "primitive_counts",
    "primitive_names",
    "trace_primitive_counts",
    "unwrap_jaxpr",
]


def unwrap_jaxpr(jaxpr: Any) -> Any:
    """Accept a ClosedJaxpr, a raw Jaxpr, or anything ``make_jaxpr`` returned."""
    inner = getattr(jaxpr, "jaxpr", None)
    return inner if inner is not None and hasattr(inner, "eqns") else jaxpr


def iter_eqns(jaxpr: Any, path: str = "") -> Iterator[Tuple[str, Any]]:
    """Yield ``(eqn_path, eqn)`` for every equation at every nesting depth.

    ``eqn_path`` is the structural location — e.g.
    ``pjit@2/scan@0.jaxpr/psum@4`` — stable across traces of the same
    program, so findings anchored on it survive re-runs and baselining.
    """
    for i, eqn in enumerate(unwrap_jaxpr(jaxpr).eqns):
        here = f"{path}/{eqn.primitive.name}@{i}" if path else f"{eqn.primitive.name}@{i}"
        yield here, eqn
        for tag, sub in eqn_subjaxprs(eqn):
            yield from iter_eqns(sub, f"{here}.{tag}")


def primitive_counts(jaxpr: Any) -> Dict[str, int]:
    """Multiset of primitive names at every depth."""
    acc: Dict[str, int] = {}
    for _, eqn in iter_eqns(jaxpr):
        acc[eqn.primitive.name] = acc.get(eqn.primitive.name, 0) + 1
    return acc


def primitive_names(jaxpr: Any) -> List[str]:
    """Flat pre-order list of primitive names at every depth."""
    return [eqn.primitive.name for _, eqn in iter_eqns(jaxpr)]


def trace_primitive_counts(fn: Any, *args: Any, **kwargs: Any) -> Dict[str, int]:
    """``primitive_counts`` of ``fn(*args)``'s jaxpr, traced through a FRESH
    closure so repeated calls under different lowering contexts (kernel
    backends) can never reuse a cached trace — the safe spelling of the
    ``jax.make_jaxpr(lambda *a: fn(*a))`` idiom the dispatch tests used."""
    import jax

    return primitive_counts(jax.make_jaxpr(lambda *a: fn(*a))(*args, **kwargs))


# ------------------------------------------------------------------ signatures


def _strip_shardings(tree: Any) -> Any:
    import jax

    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype) if hasattr(s, "shape") else s,
        tree,
    )


def _leaf_from_sig(entry: Tuple[Any, Any]) -> Any:
    """One abstract leaf back from an ``AotCache.signature_of`` entry."""
    import jax
    import jax.numpy as jnp

    a, b = entry
    if isinstance(a, tuple):  # (shape, dtype_str) — an array leaf
        return jax.ShapeDtypeStruct(tuple(a), jnp.dtype(b))
    if a in ("bool", "int", "float", "str"):
        return b
    raise ValueError(f"cannot reconstruct an abstract leaf from signature entry {entry!r}")


def _payload_from_sig(sig: Tuple[Any, Any]) -> Any:
    """Rebuild the abstract payload pytree a memoized update program was
    compiled for, from its ``(treedef, leaf_sig)`` program-memo key."""
    import jax

    treedef, leaf_sigs = sig
    return jax.tree_util.tree_unflatten(treedef, [_leaf_from_sig(e) for e in leaf_sigs])


def _sig_structure(sig: Tuple[Any, Any]) -> Tuple[Any, ...]:
    """Bucket-count-insensitive payload structure: treedef + leaf dtypes (the
    compile-cap groups update programs by this — different buckets of one
    stream share a structure; a different metric signature does not)."""
    treedef, leaf_sigs = sig
    return (str(treedef),) + tuple(
        str(e[1]) if isinstance(e[0], tuple) else repr(e) for e in leaf_sigs
    )


# -------------------------------------------------------------- engine audit


class EngineAnalysis:
    """Audit a built :class:`~metrics_tpu.engine.StreamingEngine` (or
    :class:`MultiStreamEngine`) against the program-plane rule set.

    The engine must have served traffic (its update programs are compiled and
    memoized); the audit is read-only — it re-traces jaxprs from abstract
    signatures and reads compiled HLO, never touching live state.

    Args:
        host_attr_alternates: optional ``{attr_path: [values]}`` overriding
            the default perturbations of ``no-baked-host-constants`` (enums
            perturb automatically; exotic attr types need explicit values).
    """

    def __init__(self, host_attr_alternates: Optional[Dict[str, Sequence[Any]]] = None):
        self._alternates = host_attr_alternates

    def check(self, engine: Any, label: Optional[str] = None) -> Report:
        import jax

        from metrics_tpu.analysis import rules as R

        report = Report()
        label = label or f"{type(engine).__name__}[{type(engine._metric).__name__}]"
        memo = dict(engine._program_memo)
        if not memo:
            report.note(
                f"{label}: no compiled update programs — submit traffic before auditing"
            )
        deferred = engine._deferred
        mesh = engine._cfg.mesh
        kernel_backend = engine._kernel_tag()
        state_abs = _strip_shardings(engine._abstract_state())

        structures = set()
        for (sig, mask_shape), compiled in memo.items():
            structures.add(_sig_structure(sig))
            where = f"{label}/update[bucket={mask_shape[0]}]"
            try:
                payload_abs = _payload_from_sig(sig)
            except ValueError as e:
                report.note(f"{where}: skipped (unreconstructable payload: {e})")
                continue
            mask_abs = jax.ShapeDtypeStruct(tuple(mask_shape), bool)
            with engine._kernel_scope():
                jaxpr = jax.make_jaxpr(engine._step_callable(payload_abs, mask_abs))(
                    state_abs, payload_abs, mask_abs
                )
            hlo = None
            try:
                hlo = compiled.as_text()
            except Exception as e:  # noqa: BLE001 - backend-dependent
                report.note(f"{where}: compiled HLO unavailable ({type(e).__name__})")

            if deferred:
                report.extend(R.check_no_collectives(jaxpr=jaxpr, hlo_text=hlo, where=where))
            elif mesh is not None:
                try:
                    expected = R.expected_step_sync_collectives(engine._metric)
                except ValueError as e:
                    report.note(f"{where}: collective multiset not derivable ({e})")
                else:
                    report.extend(R.check_collective_multiset(jaxpr, expected, where=where))
                # the quantized-sync policy audit: the step's fused bundle
                # must size exactly as the declared per-state precisions imply
                info = self._sync_leaf_info(engine)
                if info is not None:
                    report.extend(R.check_quantized_policy_honored(
                        jaxpr, info, engine._world, where=where
                    ))
            megastep_keys = self._megastep_fused_keys(engine)
            if kernel_backend != "xla":
                report.extend(R.check_no_scatter_under_pallas(jaxpr, where=where))
                if megastep_keys is not None:
                    # megastep form (ISSUE 16): one fused grid per eligible
                    # dtype, total launches O(dtypes) — the per-primitive
                    # budget covers kernels a delta body calls itself (e.g.
                    # the histogram MXU kernel), at most one per state leaf
                    # that is NOT covered by a fused grid
                    n_leaves = len(jax.tree_util.tree_leaves(state_abs))
                    report.extend(R.check_megastep_launch_count(
                        jaxpr, n_dtypes=len(megastep_keys),
                        extra=max(0, n_leaves - len(megastep_keys)),
                        where=where,
                    ))
                elif self._kernel_path_expected(engine):
                    report.extend(R.check_pallas_call_count(jaxpr, min_count=1, where=where))
            if engine._layout is not None:
                shard_shapes = None
                if getattr(engine, "_stream_shard", False):
                    # the paged arena's carried forms: per-device (resident, n)
                    # and global (world, resident, n) — the flat (n,) form
                    # never exists inside a routed step
                    shard_shapes = set()
                    for k, n in engine._layout.buffer_sizes().items():
                        shard_shapes.add(((engine._resident, n), k))
                        shard_shapes.add(((engine._world, engine._resident, n), k))
                else:
                    # the unsharded multistream step's segmented update
                    # legitimately scatter-reduces into (S, ...)-stacked
                    # state LEAVES; when one dtype's whole arena buffer is a
                    # single leaf (buffer size == S, e.g. a collection with
                    # exactly one f32 state) the flat buffer signature
                    # collides with that leaf and the rule would flag the
                    # update itself — the same imprecision class the
                    # stream-shard/pane-ring overrides fix, resolved in the
                    # rule INPUTS: SUBTRACT the stacked leaf signatures from
                    # whichever signature set applies (the pane-ring set for
                    # windowed engines, the default carried forms otherwise —
                    # the two overrides COMPOSE for a windowed multistream)
                    leaf_sigs = set()
                    if getattr(engine, "_num_streams", None) is not None:
                        leaf_sigs = {
                            (tuple(int(d) for d in leaf.shape), str(leaf.dtype))
                            for leaf in jax.tree_util.tree_leaves(
                                engine._kind_abstract_state_tree()
                            )
                        }
                    if getattr(engine, "_win_stacked", False):
                        # the PANE-RING carried forms (ISSUE 13): the windowed
                        # step's ONE runtime-indexed dynamic-update per dtype
                        # into the (panes, n) ring is the design, not a
                        # degradation — only per-leaf writes into the flat
                        # (n,) pane ROW mean the pack fell apart (and on a
                        # 1-device deferred mesh (panes, n) can collide with
                        # the default (world, n) signature, so the explicit
                        # set is required)
                        shard_shapes = {
                            ((n,), k)
                            for k, n in engine._layout.buffer_sizes().items()
                        } - leaf_sigs
                    elif leaf_sigs:
                        from metrics_tpu.analysis.rules.arena import _arena_avals

                        shard_shapes = (
                            _arena_avals(
                                engine._layout,
                                (engine._world,) if deferred else (),
                            )
                            - leaf_sigs
                        )
                report.extend(R.check_arena_pack_fused(
                    jaxpr, engine._layout, where=where,
                    worlds=(engine._world,) if deferred else (),
                    state_leaves=len(jax.tree_util.tree_leaves(state_abs)),
                    buffer_shapes=shard_shapes,
                    fused_dtypes=megastep_keys or (),
                ))
            if engine._donate and hlo is not None:
                n_donated = (
                    engine._layout.num_buffers
                    if engine._layout is not None
                    else len(jax.tree_util.tree_leaves(state_abs))
                )
                report.extend(R.check_donation_honored(hlo, n_donated, where=where))
        if not engine._donate:
            report.note(f"{label}: donation off (CPU or config) — donation-honored skipped")

        # deferred engines bear their collectives in the BOUNDARY MERGE — the
        # quantized-sync policy audit re-traces it (read-only, from abstract
        # signatures). Stream-sharded engines route host-side and have no
        # merge program; their at-rest codec is policy-checked at restore.
        if (
            deferred
            and not getattr(engine, "_stream_shard", False)
            and hasattr(engine, "_merge_callable")
        ):
            info = self._sync_leaf_info(engine)
            if info is not None:
                with engine._kernel_scope():
                    merge_jaxpr = jax.make_jaxpr(engine._merge_callable())(state_abs)
                report.extend(R.check_quantized_policy_honored(
                    merge_jaxpr, info, engine._world, where=f"{label}/merge"
                ))

        # device-aggregate programs (ISSUE 18): ragged engines re-trace their
        # batched fold / corpus bundle FRESH on every audit (so a
        # monkeypatched metric hook is seen) — host callbacks are banned
        # outright (each one is a synchronous round-trip per dispatch),
        # deferred meshes stay collective-free in the aggregate exactly like
        # the steady step, and a kernel-backed FOLD aggregate keeps its
        # launch count bounded (batched-read form: a handful of masked
        # column folds, never O(groups); the corpus bundle is pure XLA —
        # greedy matching has no kernel form — so the launch pin skips it)
        agg_fn = getattr(engine, "_aggregate_audit_jaxprs", None)
        if agg_fn is not None:
            from metrics_tpu.ops.kernels.dispatch import resolve_backend

            agg_kernel = (
                resolve_backend(getattr(engine, "_agg_backend", None)) != "xla"
            )
            for agg_label, agg_jaxpr in agg_fn():
                agg_where = f"{label}/{agg_label}"
                report.extend(R.check_no_host_callbacks(agg_jaxpr, where=agg_where))
                if deferred:
                    report.extend(R.check_no_collectives(
                        jaxpr=agg_jaxpr, hlo_text=None, where=agg_where
                    ))
                if agg_kernel and agg_label != "aggregate/corpus":
                    report.extend(R.check_pallas_call_count(
                        agg_jaxpr, min_count=1, max_count=8, where=agg_where
                    ))

        # embedded-model hosts feeding this engine's streams (ISSUE 19): each
        # host program is re-traced from its recorded abstract signature and
        # audited against the sharding mode's declared collective allowance —
        # the steady metric step above stays collective-free, the host's stage
        # programs carry ONLY their declared handoff (all_gather / ppermute)
        for host in self._attached_hosts(engine):
            report.extend(R.check_host_collectives_pinned(
                host, where=f"{label}/model_host[{host.kind}]"
            ))

        # compile cap: programs this engine owns in its (possibly shared) cache
        cap_detail = ""
        n_owned = self._owned_programs(engine)
        if n_owned is not None:
            multistream = hasattr(engine, "num_streams")
            # windowed engines (ISSUE 13) own a bounded fixed set of EXTRA
            # programs — one rotate/decay plus the window fold variants —
            # and NOTHING per rotation: a rotation that retraced the step
            # (pane index baked as a constant, policy drifting the key)
            # blows past this cap exactly like any other open program set
            windowed = getattr(engine, "_window", None) is not None
            win_extra = 0
            if windowed:
                win_extra = 1  # rotate (ring) or decay (ewma)
                if engine._window.kind == "sliding":
                    win_extra += 1  # indexed pane_value / sliding row folds
                if getattr(engine, "_stream_shard", False) and engine._window.stacked:
                    win_extra += 1  # batched sliding fold over reassembled rows
            # device aggregates (ISSUE 18) own a small fixed allowance too:
            # the fold program, the paged block+final pair, or the corpus
            # bundle's padded-class buckets — declared by the engine itself
            agg_extra = int(getattr(engine, "_aggregate_program_cap", lambda: 0)())
            cap = (
                len(engine._cfg.buckets) * max(1, len(structures))
                + 1                           # compute
                + (1 if deferred else 0)      # boundary merge
                + (1 if multistream else 0)   # batched all-streams compute
                + win_extra
                + agg_extra
            )
            cap_detail = (
                f"{len(engine._cfg.buckets)} buckets x {max(1, len(structures))} "
                f"payload structures + compute"
                + (" + merge" if deferred else "")
                + (" + batched results" if multistream else "")
                + (f" + {win_extra} window programs" if win_extra else "")
                + (f" + {agg_extra} aggregate programs" if agg_extra else "")
            )
            report.extend(R.check_compile_cap(
                n_owned, cap, where=f"{label}/programs", detail=cap_detail
            ))

        # host-constant coverage (the PR-3 collision class)
        if getattr(engine, "_needs_attr_latch", False):
            report.note(f"{label}: host attrs not yet latched — no-baked-host-constants skipped")
        else:
            report.extend(R.check_no_baked_host_constants(
                engine._metric, where=f"{label}/compute", alternates=self._alternates
            ))
        return report

    @staticmethod
    def _attached_hosts(engine: Any) -> List[Any]:
        """Model hosts declared on the engine (``engine.model_hosts`` list or a
        single ``engine.model_host``) — how the bootstrap matrix and serving
        code hand the audit the embedded-model plane."""
        hosts = getattr(engine, "model_hosts", None)
        if hosts:
            return list(hosts)
        host = getattr(engine, "model_host", None)
        return [host] if host is not None else []

    @staticmethod
    def _sync_leaf_info(engine: Any) -> Optional[Any]:
        """The metric's declared ``(fx, leaf, precision)`` triples for the
        quantized-policy audit — None when the flat model does not apply
        (wrapper metrics with nested children sync their subtrees in
        SEPARATE recursive bundles, so the flat size check would be wrong)."""
        metric = engine._metric
        info_fn = getattr(metric, "sync_leaf_info", None)
        if info_fn is None:
            return None
        members = (
            [m for _, m in metric.items(keep_base=True)]
            if hasattr(metric, "items") and not hasattr(metric, "_defaults")
            else [metric]
        )
        if any(m._child_metrics() for m in members):
            return None
        info = info_fn()
        # unsharded MultiStreamEngines sync the (S, ...)-STACKED state: every
        # leaf the bundle carries has a leading stream axis, so the expected
        # payload scales accordingly (stream-sharded engines never merge)
        n_streams = getattr(engine, "num_streams", None)
        if n_streams and not getattr(engine, "_stream_shard", False):
            import jax

            info = [
                (fx, jax.ShapeDtypeStruct((int(n_streams),) + tuple(leaf.shape), leaf.dtype), prec)
                for fx, leaf, prec in info
            ]
        # ring windows stack the pane axis OUTSIDE the stream axis — the
        # deferred boundary merge moves pane-stacked states, so the expected
        # bundle scales by the live pane count too (ISSUE 13)
        if getattr(engine, "_win_stacked", False):
            import jax

            panes = int(engine._panes)
            info = [
                (fx, jax.ShapeDtypeStruct((panes,) + tuple(leaf.shape), leaf.dtype), prec)
                for fx, leaf, prec in info
            ]
        return info

    @staticmethod
    def _megastep_fused_keys(engine: Any) -> Optional[Tuple[str, ...]]:
        """The arena dtype keys riding the engine's fused megastep grids
        (eligible keys minus per-dtype degradation verdicts), or None when
        the engine is not on a megastep backend / fell back engine-level —
        the audit then applies the per-leaf rule forms instead."""
        from metrics_tpu.ops.kernels.dispatch import MEGASTEP_BACKENDS

        if engine._kernel_tag() not in MEGASTEP_BACKENDS:
            return None
        plan = getattr(engine, "_megastep_plan", None)
        if plan is None:
            return None
        fall = engine._megastep_fallback_reasons()
        return tuple(k for k in plan.eligible_keys() if k not in fall)

    @staticmethod
    def _kernel_path_expected(engine: Any) -> bool:
        """Whether a Pallas-backend engine's step should trace >=1 kernel:
        only delta-strategy metrics route their fold through the dispatcher,
        and only supported dtypes stay on the kernel path."""
        from metrics_tpu.ops.kernels.common import supported_dtype

        metric = engine._metric
        strategies = (
            metric.masked_update_strategies()
            if hasattr(metric, "masked_update_strategies")
            else {type(metric).__name__: metric.masked_update_strategy()}
        )
        if any(s != "delta" for s in strategies.values()):
            return False
        import jax

        leaves = jax.tree_util.tree_leaves(metric.abstract_state())
        return all(supported_dtype(l.dtype) for l in leaves if hasattr(l, "dtype"))

    @staticmethod
    def _owned_programs(engine: Any) -> Optional[int]:
        """How many compiled programs in the engine's AotCache belong to it
        (same metric fingerprint, mesh, sync mode). None when the cache does
        not expose its keys."""
        from metrics_tpu.engine.aot import _mesh_fingerprint

        keys = getattr(engine._aot, "program_keys", None)
        if keys is None:
            return None
        mesh_fp = _mesh_fingerprint(engine._cfg.mesh)
        sync = engine._sync_tag()
        return sum(
            1
            for k in keys()
            if len(k) >= 6 and k[1] == engine._metric_fp and k[3] == mesh_fp and k[5] == sync
        )
