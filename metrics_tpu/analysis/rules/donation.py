"""Donation rule: declared donations must survive into the compiled HLO.

``donate_argnums`` is a REQUEST: XLA silently drops any donation it cannot
use (no same-shape/dtype output to alias, unsupported backend), and the step
then allocates a second state copy per dispatch — the exact regression the
arena + donation work of PR 3 exists to prevent, invisible today unless
someone profiles allocations. The compiled module records what actually
happened in its ``input_output_alias`` table; this rule diffs that table
against the declaration.
"""
import re
from typing import List, Set

from metrics_tpu.analysis.core import Finding

__all__ = ["parse_hlo_aliased_params", "check_donation_honored"]

_ALIAS_HEADER = "input_output_alias={"
# one alias entry: "{out_index}: (param_number, {param_index}[, kind])"
_ALIAS_ENTRY_RE = re.compile(r":\s*\((\d+)\s*,")


def parse_hlo_aliased_params(hlo_text: str) -> Set[int]:
    """Parameter numbers the compiled module actually aliases to outputs.

    Parses the ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` table
    in the HloModule header (balanced-brace scan — entries contain braces).
    Empty set = XLA honored no donation at all.
    """
    start = hlo_text.find(_ALIAS_HEADER)
    if start < 0:
        return set()
    i = start + len(_ALIAS_HEADER) - 1  # at the opening brace
    depth = 0
    for j in range(i, len(hlo_text)):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                body = hlo_text[i + 1 : j]
                return {int(m.group(1)) for m in _ALIAS_ENTRY_RE.finditer(body)}
    return set()


def check_donation_honored(
    hlo_text: str, expected_donated: int, where: str = ""
) -> List[Finding]:
    """Rule ``donation-honored``: a program compiled with ``expected_donated``
    donated input buffers must alias at least that many distinct parameters
    to outputs in its HLO. Fires when XLA silently dropped some (or all) of
    the donation — the state is then double-buffered on every step."""
    if expected_donated <= 0:
        return []
    aliased = parse_hlo_aliased_params(hlo_text)
    if len(aliased) >= expected_donated:
        return []
    return [Finding(
        rule="donation-honored", severity="error", where=where,
        path=f"hlo:input_output_alias({sorted(aliased)})",
        message=(
            f"{expected_donated} buffer(s) declared donated but compiled HLO "
            f"aliases only {len(aliased)} parameter(s) — XLA dropped the rest"
        ),
        hint=(
            "donation needs an output with identical shape/dtype(/sharding) for "
            "each donated input; a changed carried-state layout, an added dtype "
            "cast, or an unsupported backend silently reverts the step to "
            "double-buffered state (docs/serving.md, 'State arenas': the "
            "donation invariant)"
        ),
    )]
