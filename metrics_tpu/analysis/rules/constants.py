"""Baked-host-constant rule: trace constants must be covered by the fingerprint.

The PR-3 AotCache collision class: a host-derived attribute (e.g.
``Accuracy.mode``, latched from the first batch) becomes a TRACE CONSTANT of
the compute program. If the attribute can change the trace while the metric
FINGERPRINT (``engine/aot.py::metric_fingerprint`` — every program key's
identity) stays the same, two engines serving different traffic through one
shared cache exchange executables with the wrong constant baked in: same key,
silently wrong value. Found by accident in PR 3; this rule finds it by
construction — trace the program twice under perturbed host attrs and demand
that any jaxpr drift comes with a fingerprint drift.
"""
import copy
import enum
from typing import Any, Callable, Dict, List, Optional, Sequence

from metrics_tpu.analysis.core import Finding

__all__ = ["check_no_baked_host_constants", "default_attr_alternates"]


def default_attr_alternates(value: Any) -> Sequence[Any]:
    """Best-effort perturbations for one host attr value. Enums try every
    other member (the real case: ``Accuracy.mode`` is a ``DataType``); bools
    flip; ints/floats shift. Strings and exotic types yield nothing — a
    caller who wants them perturbed must pass explicit alternates."""
    if isinstance(value, enum.Enum):
        return [m for m in type(value) if m != value]
    if isinstance(value, bool):
        return [not value]
    if isinstance(value, int):
        return [value + 1]
    if isinstance(value, float):
        return [value + 1.0]
    return []


def _default_trace(metric: Any) -> str:
    """The compute program's jaxpr text — where host attrs bake in."""
    import jax

    abs_state = metric.abstract_state()
    return str(jax.make_jaxpr(lambda s: metric.compute_from(s))(abs_state))


def check_no_baked_host_constants(
    metric: Any,
    where: str = "",
    alternates: Optional[Dict[str, Sequence[Any]]] = None,
    trace: Optional[Callable[[Any], str]] = None,
    fingerprint: Optional[Callable[[Any], str]] = None,
) -> List[Finding]:
    """Rule ``no-baked-host-constants``.

    For every declared host-derived compute attribute
    (``Metric.host_compute_attrs``) with a latched (non-None) value: deep-copy
    the metric, perturb the attribute, and re-trace the program with a FRESH
    closure. If the two traces differ (the attr IS a baked constant) while the
    two fingerprints agree, that constant lives outside the program identity —
    the PR-3 shared-cache collision — and the rule fires. Attributes no
    alternate value can trace (invalid perturbations raise at trace time) are
    skipped: unevaluated, not passed.
    """
    from metrics_tpu.engine.aot import metric_fingerprint

    trace = trace or _default_trace
    fingerprint = fingerprint or metric_fingerprint
    attrs = metric.host_compute_attrs() if hasattr(metric, "host_compute_attrs") else {}
    findings: List[Finding] = []
    base_trace: Optional[str] = None
    base_fp: Optional[str] = None
    for path, value in sorted(attrs.items()):
        if value is None:
            continue  # unlatched: the engine's first-batch latch guards this
        cands = list((alternates or {}).get(path, default_attr_alternates(value)))
        for alt in cands:
            perturbed = copy.deepcopy(metric)
            perturbed.restore_host_compute_attrs({path: alt})
            try:
                alt_trace = trace(perturbed)
            except Exception:  # noqa: BLE001 - invalid perturbation: try next
                continue
            if base_trace is None:
                base = copy.deepcopy(metric)  # trace may mutate bookkeeping
                base_trace = trace(base)
                base_fp = fingerprint(metric)
            if alt_trace == base_trace:
                # THIS alternate happens to lower identically — it proves
                # nothing about the others (a 3-member enum can trace A==B
                # while C drifts); keep probing until one differs
                continue
            if fingerprint(perturbed) == base_fp:
                findings.append(Finding(
                    rule="no-baked-host-constants", severity="error",
                    where=where, path=f"host_attr:{path}",
                    message=(
                        f"host attr {path!r} ({value!r} -> {alt!r}) changes the traced "
                        "program but NOT the metric fingerprint — two engines sharing "
                        "an AotCache would exchange executables with the wrong "
                        "constant baked in"
                    ),
                    hint=(
                        "store the attribute where engine/aot.py::metric_fingerprint "
                        "hashes it (a plain instance attribute, not a skipped "
                        "bookkeeping slot), and declare it in "
                        "_host_derived_compute_attrs so snapshots carry it"
                    ),
                ))
            break  # one trace-DIFFERING alternate settles this attr
    return findings
