"""Collective-placement rules: WHERE cross-chip communication may appear.

The deferred-sync serving contract (PR 5, docs/serving.md "Mesh sync modes")
is structural: the steady-state step carries ZERO collectives at any nesting
depth — in the jaxpr AND in the compiled HLO — while the step-sync step
carries EXACTLY its fused bundle (one psum for all sum states + the token
psum + one collective per extra (reduction, dtype)). Both used to be pinned
by one-off jaxpr walks and regexes scattered across test files; these rules
are the single named implementation every gate calls.
"""
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.analysis.core import Finding

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "collective_counts",
    "collective_eqn_paths",
    "hlo_collective_counts",
    "check_no_collectives",
    "check_collective_multiset",
    "check_host_collectives_pinned",
    "expected_step_sync_collectives",
]

#: every cross-device communication primitive jax can trace today — the
#: deferred steady step must contain NONE of them, at any nesting depth
#: (formerly pinned inline in ``tests/engine/test_deferred_fast.py``)
COLLECTIVE_PRIMITIVES = {
    "psum", "psum2", "pmin", "pmax", "pmean", "ppermute", "pbroadcast",
    "all_gather", "all_gather_invariant", "all_to_all", "reduce_scatter",
}


def collective_counts(jaxpr: Any) -> Dict[str, int]:
    """Multiset of collective primitives anywhere in a (closed) jaxpr."""
    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    acc: Dict[str, int] = {}
    for _, eqn in iter_eqns(unwrap_jaxpr(jaxpr)):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            acc[name] = acc.get(name, 0) + 1
    return acc


def collective_eqn_paths(jaxpr: Any) -> List[Tuple[str, str]]:
    """``(eqn_path, primitive_name)`` for every collective in the jaxpr."""
    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    return [
        (path, eqn.primitive.name)
        for path, eqn in iter_eqns(unwrap_jaxpr(jaxpr))
        if eqn.primitive.name in COLLECTIVE_PRIMITIVES
    ]


def hlo_collective_counts(hlo_text: str) -> Dict[str, int]:
    """Multiset of cross-chip collective ops in compiled HLO text, keyed by
    the HLO op name (``all-reduce``, ``all-gather``, ...). The pattern is the
    canonical ``parallel/collectives.py::HLO_COLLECTIVE_RE`` every placement
    gate shares."""
    from metrics_tpu.parallel.collectives import HLO_COLLECTIVE_RE

    acc: Dict[str, int] = {}
    for m in HLO_COLLECTIVE_RE.finditer(hlo_text):
        acc[m.group(1)] = acc.get(m.group(1), 0) + 1
    return acc


def check_no_collectives(
    jaxpr: Any = None, hlo_text: Optional[str] = None, where: str = ""
) -> List[Finding]:
    """Rule ``no-collectives-in-deferred-step``: a deferred-sync steady step
    must be collective-free in its jaxpr (any nesting depth) and its compiled
    HLO. Pass either or both artifacts."""
    findings: List[Finding] = []
    hint = (
        "the deferred-sync contract moves ALL cross-chip traffic to the boundary "
        "merge (parallel/embedded.py::sharded_state_merge); a collective here "
        "reintroduces the per-step sync PR 5 removed — check that the update path "
        "uses sharded_local_step and no metric code calls sync_states in-step"
    )
    if jaxpr is not None:
        for path, name in collective_eqn_paths(jaxpr):
            findings.append(Finding(
                rule="no-collectives-in-deferred-step", severity="error",
                where=where, path=path,
                message=f"collective primitive {name!r} traced inside a deferred steady step",
                hint=hint,
            ))
    if hlo_text is not None:
        for op, n in sorted(hlo_collective_counts(hlo_text).items()):
            findings.append(Finding(
                rule="no-collectives-in-deferred-step", severity="error",
                where=where, path=f"hlo:{op}",
                message=f"compiled HLO contains {n}x {op} in a deferred steady step",
                hint=hint,
            ))
    return findings


def check_host_collectives_pinned(host: Any, where: str = "") -> List[Finding]:
    """Rule ``host-collectives-pinned``: a :class:`~metrics_tpu.engine.model_host.ModelHost`
    program may carry ONLY the collectives its sharding mode declares
    (``allowed_collectives`` — ``all_gather`` for the hybrid stem-tensor
    Inception layout, ``ppermute`` for the pipeline-staged encoder, nothing
    for single-device hosts). The embedded-model serving contract (ISSUE 19)
    keeps the METRIC steady step collective-free and confines cross-chip
    traffic to the host's stage programs; an undeclared collective here means
    the model layout leaked communication past its declared handoff (and a
    mesh-sharded host whose programs trace NO declared collective silently
    degraded to replicated execution — flagged as a warning).

    Re-traces every compiled host program from its recorded abstract
    signature (read-only; ``ModelHost.host_programs``).
    """
    import jax

    allowed = set(getattr(host, "allowed_collectives", ()))
    unknown = allowed - COLLECTIVE_PRIMITIVES
    findings: List[Finding] = []
    if unknown:
        findings.append(Finding(
            rule="host-collectives-pinned", severity="error", where=where,
            path="allowed_collectives",
            message=f"declared allowance {sorted(unknown)} names no known collective primitive",
            hint=f"valid names: {sorted(COLLECTIVE_PRIMITIVES)}",
        ))
    programs = host.host_programs()
    if not programs:
        findings.append(Finding(
            rule="host-collectives-pinned", severity="warning", where=where,
            path="", message="host has no compiled programs — serve traffic before auditing",
            hint="call host.infer(...) (or route a metric through it) first",
        ))
        return findings
    sharded = getattr(host.config, "mesh", None) is not None
    for key, (fn, (params_abs, args_abs)) in programs.items():
        pwhere = f"{where}/program[{key[3] if len(key) > 3 else key}]"
        jaxpr = jax.make_jaxpr(fn)(params_abs, *args_abs)
        seen = set()
        for path, name in collective_eqn_paths(jaxpr):
            seen.add(name)
            if name not in allowed:
                findings.append(Finding(
                    rule="host-collectives-pinned", severity="error",
                    where=pwhere, path=path,
                    message=(
                        f"collective {name!r} traced in a host program whose sharding "
                        f"mode allows only {sorted(allowed) or 'none'}"
                    ),
                    hint=(
                        "the model layout leaked communication past its declared "
                        "stage handoff — hybrid Inception may only all_gather the "
                        "stem lanes, pipeline encoders may only ppermute activations "
                        "(parallel/embedded.py); single-device hosts communicate NOT AT ALL"
                    ),
                ))
        if sharded and allowed and not (seen & allowed):
            findings.append(Finding(
                rule="host-collectives-pinned", severity="warning",
                where=pwhere, path="",
                message=(
                    f"mesh-sharded host program traces none of its declared "
                    f"handoffs {sorted(allowed)} — the layout may have silently "
                    "degraded to replicated execution"
                ),
                hint="check the builder actually routed through the sharded forward",
            ))
    return findings


def expected_step_sync_collectives(metric: Any) -> Dict[str, int]:
    """The EXACT collective multiset a step-sync mesh step must trace, derived
    from the metric's declared state reductions the same way
    ``parallel/collectives.py::fused_axis_sync`` buckets them:

    * all sum-rider-eligible 'sum' leaves share ONE ``psum``; the step's
      valid-row token adds a second;
    * 'mean'/'min'/'max' leaves cost one ``pmean``/``pmin``/``pmax`` per
      (reduction, dtype) bucket;
    * any 'cat'/None/custom (or rider-ineligible 'sum') leaf joins the single
      u32-carrier ``all_gather``;
    * QUANTIZED float 'sum' leaves (``sync_precision="q8_block"``) leave the
      psum bundle and join that same gather as block-scaled int8 — an
      all-quantized policy with no counters would drop the bundle psum
      entirely (the token psum always remains).

    Raises ``ValueError`` for metrics with nested child metrics — their
    states sync recursively with their own bundles, so the flat multiset
    below would be wrong (audit those engines with the zero/nonzero rules
    instead).
    """
    import jax.numpy as jnp

    from metrics_tpu.parallel.collectives import _REDUCE_COLLECTIVES, _sum_rider

    leaves = _state_reduction_leaves(metric)
    counts: Dict[str, int] = {}
    have_sum_bundle = False
    reduce_buckets = set()
    have_gather = False
    for fx, dtype, prec in leaves:
        is_float_sum = (
            fx == "sum" and dtype is not None and _sum_rider(jnp.dtype(dtype)) == "float"
        )
        if prec == "q8_block" and is_float_sum:
            have_gather = True  # codes + scales ride the shared u32 carrier
        elif fx == "sum" and dtype is not None and _sum_rider(jnp.dtype(dtype)) is not None:
            have_sum_bundle = True
        elif fx in _REDUCE_COLLECTIVES and fx != "sum":
            reduce_buckets.add((fx, str(dtype)))
        else:
            have_gather = True
    counts["psum"] = (1 if have_sum_bundle else 0) + 1  # fused bundle + token
    for fx, _ in reduce_buckets:
        name = {"mean": "pmean", "min": "pmin", "max": "pmax"}[fx]
        counts[name] = counts.get(name, 0) + 1
    if have_gather:
        counts["all_gather"] = 1
    return {k: v for k, v in counts.items() if v}


def _state_reduction_leaves(metric: Any) -> List[Tuple[Any, Any, str]]:
    """Flat ``(dist_reduce_fx, dtype, sync_precision)`` per top-level state
    leaf, mirroring the leaves ``MetricCollection.sync_states``/
    ``Metric.sync_states`` fuse."""
    out: List[Tuple[Any, Any, str]] = []

    def one(m: Any) -> None:
        if m._child_metrics():
            raise ValueError(
                f"{type(m).__name__} has nested child metrics; the flat step-sync "
                "multiset does not model their recursive sync bundles"
            )
        abs_state = m.abstract_state()
        for k in m._defaults:
            fx = m._reductions[k]
            v = abs_state[k]
            prec = m._sync_precision.get(k, "exact")
            if isinstance(m._defaults[k], list):
                out.append(("cat" if fx is None else fx, None, "exact"))
            else:
                out.append((fx, getattr(v, "dtype", None), prec))

    if hasattr(metric, "items") and not hasattr(metric, "_defaults"):
        for _, m in metric.items(keep_base=True):
            one(m)
    else:
        one(metric)
    return out


def check_collective_multiset(
    jaxpr: Any, expected: Dict[str, int], where: str = ""
) -> List[Finding]:
    """Rule ``exact-collective-multiset-in-step-sync``: the step-sync steady
    step's collective multiset must equal ``expected`` EXACTLY — a refactor
    must neither fall back to per-state collectives (counts grow) nor drop a
    reduction's merge (counts shrink: silent divergence across shards)."""
    actual = collective_counts(jaxpr)
    if actual == {k: v for k, v in expected.items() if v}:
        return []
    return [Finding(
        rule="exact-collective-multiset-in-step-sync", severity="error",
        where=where, path="",
        message=(
            f"step-sync step collective multiset is {actual or '{}'}, "
            f"expected exactly {expected or '{}'}"
        ),
        hint=(
            "more collectives than expected = the fused bundle degraded to "
            "per-state sync (dispatch cost returns); fewer = a reduction's "
            "cross-shard merge was dropped (shards silently diverge) — see "
            "parallel/collectives.py::fused_axis_sync for the bundling contract"
        ),
    )]
