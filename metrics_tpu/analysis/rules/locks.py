"""Lock declarations + the shared lockset walker (the concurrency plane's core).

PR 7's ``lock-discipline`` lint guarded ONE lock in TWO files. Since then the
threaded surface has grown a lock per subsystem — the flight recorder's ring
and histogram locks (PR 8), the admission/ladder locks (PR 11), the drift
detector's series lock (PR 13) — and every review pass has hand-found the
same bug classes: a bare ``+=`` losing increments across producer threads, a
histogram lock held across a jax fold stalling submits, TOCTOU in ``stop()``.
This module turns the ad-hoc comments that documented those disciplines into
**checkable declarations**:

* :class:`LockDecl` — one lock a class OWNS: its attribute name, a stable
  cross-file identity (``"StreamingEngine._state_lock"``), whether it is
  reentrant, whether jax dispatch may run under a hold (the engine's coarse
  state lock deliberately serializes device work; the recorder/histogram
  hot-path locks must never hold across a dispatch), and the methods the
  call graph only ever enters with the lock already held (plus a
  ``*_locked`` naming convention).
* :class:`GuardDecl` — which attributes a lock guards. The lock may belong
  to ANOTHER class (``EngineStats.ladder_transitions`` is guarded by the
  engine's ladder lock, not by any lock of its own).
* :class:`ClassDecl` — one class's whole discipline: owned locks, guards,
  collaborator attribute types (``self._stats`` is an ``EngineStats`` — how
  the cross-class call graph resolves), or an ``external_lock`` a caller
  must hold around every method (``StreamPager`` is bookkeeping under the
  engine's state lock; ``TokenBucket`` under the admission policy's).

:data:`CONCURRENCY_SPECS` declares the discipline of every threaded engine
module. :func:`build_class_models` compiles source + declarations into
per-method summaries (mutations, acquisitions, calls, dispatch calls — each
with the statically-held lock set), and :func:`lockset_findings` runs the
lockset rule over them: every mutation of a declared-guarded attribute must
happen with its lock statically held, via an intraprocedural ``with``-stack
walk plus a call-graph closure over lock-held methods. The other three
concurrency rules (:mod:`metrics_tpu.analysis.concurrency`) consume the same
summaries.

Static model (documented limits, shared by all four rules):

* ``with self.<lock>`` scopes a hold exactly; bare ``<lock>.acquire()`` /
  ``.release()`` calls toggle the hold linearly through the remaining
  statements of the function (the conditional-acquisition idiom in
  ``FixedBucketHistogram._flush`` resolves correctly; token-passing a lock
  between threads does not, and should not pass review either).
* Nested ``def``/``lambda`` bodies are analyzed AT their lexical position —
  right for the engine's synchronous retry-closure idiom
  (``self._retry_transient(lambda: ...)`` runs under the caller's hold),
  wrong for a closure stashed and run later on another thread (none exist;
  a new one belongs in ``locked_methods`` or gets a suppression).
* Lock aliasing is recognized one level deep: ``self._lock = other._lock``
  (or any assignment whose right side ends in a declared lock attribute)
  makes the left side an alias of that lock.
"""
import ast
from dataclasses import dataclass, field
from typing import (
    Any, Dict, FrozenSet, Iterable, List, Mapping, Optional, Sequence, Set,
    Tuple,
)

from metrics_tpu.analysis.core import Finding

__all__ = [
    "CONCURRENCY_SPECS",
    "ClassDecl",
    "ClassModel",
    "GuardDecl",
    "LockDecl",
    "MethodSummary",
    "build_class_models",
    "decls_for_file",
    "dotted_name",
    "lockset_findings",
]


# --------------------------------------------------------------- declarations


@dataclass(frozen=True)
class LockDecl:
    """One lock a class owns."""

    attr: str                 # the attribute holding the lock object
    lock_id: str              # stable cross-file identity ("Class._lock")
    #: jax dispatch (jnp ops, compiled-executable calls, device_get/put,
    #: host folds) is legal under a hold. True for coarse serialization
    #: locks (the engine's state lock SERIALIZES device work by design);
    #: False for hot-path locks a producer may block on.
    dispatch_ok: bool = False
    reentrant: bool = False   # threading.RLock: self-nesting is legal
    #: methods entered with this lock already held by contract (the caller
    #: acquires; the lexical analysis cannot see it)
    locked_methods: FrozenSet[str] = frozenset()
    #: method-name suffix implying membership in locked_methods ("" = none)
    locked_suffix: str = ""


@dataclass(frozen=True)
class GuardDecl:
    """Attributes guarded by a lock (the lock may belong to another class)."""

    lock_id: str
    guarded: FrozenSet[str]
    #: emit lockset findings for these attrs under this rule id (the PR 7
    #: ``lock-discipline`` alias: old suppressions/baselines keep working)
    rule_id: str = "concurrency-lockset"


@dataclass(frozen=True)
class ClassDecl:
    """One class's declared concurrency discipline."""

    name: str                                   # class name; "*" = any class
    locks: Tuple[LockDecl, ...] = ()
    guards: Tuple[GuardDecl, ...] = ()
    #: lock_id a CALLER must hold around every method (bookkeeping-only
    #: classes: StreamPager under the engine's state lock). Every method is
    #: treated as entered with this lock held, and call sites elsewhere are
    #: checked for the hold.
    external_lock: Optional[str] = None
    exempt_methods: FrozenSet[str] = frozenset({"__init__"})
    #: the lock attributes are assigned by a BASE class's __init__, not this
    #: class's own body (MultiStreamEngine inherits the engine locks) — skips
    #: the lock-attribute existence check
    inherits_locks: bool = False
    #: self.<attr> -> class name, for cross-class call/lock resolution
    collaborators: Mapping[str, str] = field(default_factory=dict)
    #: "method" -> class name of (the elements of) its return value, for
    #: locals assigned from collaborator calls (tr.histograms() -> [hist])
    method_returns: Mapping[str, str] = field(default_factory=dict)


_ENGINE_STATE_LOCK = LockDecl(
    attr="_state_lock",
    lock_id="StreamingEngine._state_lock",
    # the state lock SERIALIZES device work by design: steps, boundary
    # merges, result computes and snapshot encodes all dispatch under it
    dispatch_ok=True,
    reentrant=True,  # RLock: _process_group re-enters _save_snapshot
    locked_methods=frozenset({
        # lock taken by the caller: _process_group holds it across the whole
        # group, result()/state()/stream_state() across merges and reads
        "_do_step", "_recover_step", "_bound_inflight", "_execute_chunk",
        "_run_padded_step", "_execute_payload", "_execute_routed", "_page_round",
        "_merged_state", "_latch_host_attrs",
        "_record_quarantine", "_screen_group",
        # ISSUE 11: ladder rung application runs under the tick's lock hold;
        # the topology swap/memo invalidation only run inside _reshard_locked
        # (itself *_locked by convention) or the rung application
        "_engage_rung", "_release_rung", "_engage_quantize", "_release_quantize",
        "_refresh_policy_identity", "_apply_topology", "_apply_topology_state",
        "_invalidate_topology_memos",
        # ISSUE 13: pane rotation runs inside _process_group_locked's lock
        # hold; windowed readers run under result()/results()' lock hold
        "_plan_rotation", "_commit_rotation", "_record_drift",
        "_windowed_row_result", "_sharded_results_values",
        # stream-sharded helpers reached only from locked dispatch/read paths
        "_refresh_gauges", "_snapshot_state", "_snapshot_doc", "_global_rows_host",
        "_fetch_row", "_topology_state",
    }),
    locked_suffix="_locked",
)

_ENGINE_LADDER_LOCK = LockDecl(
    attr="_ladder_lock",
    lock_id="StreamingEngine._ladder_lock",
    # the throttled p99 refresh may force a histogram fold under the tick's
    # hold — deliberate (ticks are per-group, the fold is throttled); the
    # cost of a producer shed-rejection briefly blocking on it is accepted
    dispatch_ok=True,
    locked_methods=frozenset({"_ladder_signals"}),
)

#: the PR 3/PR 7 guarded set — rule id kept as the `lock-discipline` alias so
#: existing suppressions, baselines and tests keep working
_ENGINE_LEGACY_GUARD = GuardDecl(
    lock_id="StreamingEngine._state_lock",
    guarded=frozenset({
        "_state", "_state_version", "_merged_memo", "_inflight",
        "_step", "_batches_done", "_quarantine",
    }),
    rule_id="lock-discipline",
)

#: fields that predate the declaration convention (ISSUE 11/13 era), now
#: declared: the pane-ring cursors, the defer-rung read cache, the quantize
#: rung's saved policy state
_ENGINE_NEW_GUARD = GuardDecl(
    lock_id="StreamingEngine._state_lock",
    guarded=frozenset({
        "_result_cache", "_defer_cold_reads",
        "_ladder_saved_window", "_ladder_quantized",
        "_pane_cursor", "_rotations", "_pane_open_cursor",
        "_last_rotate_batches", "_last_rotate_time",
        "_program_memo", "_merged_abs_memo",
    }),
)

_ENGINE_LADDER_GUARD = GuardDecl(
    lock_id="StreamingEngine._ladder_lock",
    guarded=frozenset({"_ladder_marks", "_ladder_ticks", "_ladder_p99"}),
)

_ENGINE_COLLABORATORS = {
    "_stats": "EngineStats",
    "_trace": "TraceRecorder",
    "_admission": "AdmissionPolicy",
    "_ladder": "DegradationLadder",
    "_drift": "DriftDetector",
    "_pager": "StreamPager",
    "_aot": "AotCache",
}

_ENGINE_RETURNS = {
    "histograms": "FixedBucketHistogram",  # TraceRecorder.histograms()
}


def _engine_decl(name: str, inherits_locks: bool = False) -> ClassDecl:
    return ClassDecl(
        name=name,
        locks=(_ENGINE_STATE_LOCK, _ENGINE_LADDER_LOCK),
        guards=(_ENGINE_LEGACY_GUARD, _ENGINE_NEW_GUARD, _ENGINE_LADDER_GUARD),
        inherits_locks=inherits_locks,
        collaborators=_ENGINE_COLLABORATORS,
        method_returns=_ENGINE_RETURNS,
    )


#: path-suffix -> declared disciplines of the classes in that file. This IS
#: the audited engine module set: `tools/engine_report.py` reports it clean
#: when `make analyze` found nothing, and deleting a lock (or renaming a
#: guarded attribute) fails the declaration resolution loudly in
#: `make analyze` before any smoke can flake.
CONCURRENCY_SPECS: Dict[str, Tuple[ClassDecl, ...]] = {
    "engine/pipeline.py": (_engine_decl("StreamingEngine"),),
    "engine/multistream.py": (_engine_decl("MultiStreamEngine", inherits_locks=True),),
    "engine/trace.py": (
        ClassDecl(
            name="TraceRecorder",
            locks=(
                LockDecl(
                    attr="_lock", lock_id="TraceRecorder._lock",
                    # producers block on this in submit(): never hold it
                    # across a dispatch, and never nest the histogram lock
                    # under it (PR 8's stall fix, pinned by the lock-order
                    # rule's forbidden pair)
                    dispatch_ok=False,
                ),
            ),
            guards=(
                GuardDecl(
                    lock_id="TraceRecorder._lock",
                    guarded=frozenset({"_ring", "_dropped", "_n_traces", "_hists"}),
                ),
            ),
            collaborators={"_hists": "FixedBucketHistogram"},
        ),
        ClassDecl(
            name="FixedBucketHistogram",
            locks=(
                LockDecl(
                    attr="_lock", lock_id="FixedBucketHistogram._lock",
                    # the PR 8 incident this plane exists for: this lock held
                    # across the jax fold stalled every producer's observe
                    dispatch_ok=False,
                ),
                LockDecl(
                    attr="_fold_lock", lock_id="FixedBucketHistogram._fold_lock",
                    # serializes folds; the fold itself runs under it
                    dispatch_ok=True,
                    locked_methods=frozenset({"_flush_under_fold_lock"}),
                ),
            ),
            guards=(
                GuardDecl(
                    lock_id="FixedBucketHistogram._lock",
                    guarded=frozenset({"_pending", "_counts", "_sum", "_n"}),
                ),
            ),
        ),
    ),
    "engine/admission.py": (
        ClassDecl(
            name="AdmissionPolicy",
            locks=(
                LockDecl(
                    attr="_lock", lock_id="AdmissionPolicy._lock",
                    # every producer's submit crosses this lock: host
                    # arithmetic only, never a dispatch
                    dispatch_ok=False,
                ),
            ),
            guards=(
                GuardDecl(
                    lock_id="AdmissionPolicy._lock",
                    guarded=frozenset({
                        "_buckets", "_shed_floor", "_admitted", "_rejected", "_shed",
                    }),
                ),
            ),
        ),
        ClassDecl(
            # "NOT thread-safe on its own — the owning AdmissionPolicy
            # serializes access under one lock" (its docstring), declared
            name="TokenBucket",
            external_lock="AdmissionPolicy._lock",
        ),
        ClassDecl(
            # ticks come from the dispatcher AND producer shed rejections;
            # the engine serializes every tick under its ladder lock
            name="DegradationLadder",
            external_lock="StreamingEngine._ladder_lock",
        ),
        ClassDecl(
            name="OverloadDetector",
            external_lock="StreamingEngine._ladder_lock",
        ),
    ),
    "engine/stats.py": (
        ClassDecl(
            name="EngineStats",
            locks=(
                LockDecl(
                    attr="_counter_lock", lock_id="EngineStats._counter_lock",
                    dispatch_ok=False,
                ),
            ),
            guards=(
                GuardDecl(
                    # counters bumped from PRODUCER threads concurrently with
                    # the dispatcher: a bare `+=`/`dict[k] += 1` loses
                    # increments (the PR 11 incident, now package-checked)
                    lock_id="EngineStats._counter_lock",
                    guarded=frozenset({
                        "admission_admitted", "admission_rejected", "admission_shed",
                        "retries", "deferred_reads", "batches_submitted",
                        "faults_injected",
                        # fleet boundary counters (ISSUE 15): moved by the
                        # fleet caller thread today, but the record_* methods
                        # lock anyway — declaring them keeps any future
                        # multi-threaded fleet driver honest by construction
                        "fleet_ingested", "fleet_skipped", "fleet_merges",
                        "fleet_merge_us_total", "fleet_barriers", "fleet_cuts",
                        "fleet_payload_exact_bytes", "fleet_payload_quant_bytes",
                    }),
                ),
                GuardDecl(
                    # dispatcher ticks and producer shed-rejection ticks both
                    # move these — serialized by the ENGINE's ladder lock
                    lock_id="StreamingEngine._ladder_lock",
                    guarded=frozenset({"ladder_transitions", "ladder_level"}),
                ),
            ),
        ),
    ),
    "engine/paging.py": (
        ClassDecl(
            # "BOOKKEEPING ONLY" (its docstring): slot tables, LRU order and
            # the spill store mutate exclusively under the engine's state
            # lock — the pager plans, the engine moves bytes and commits
            name="StreamPager",
            external_lock="StreamingEngine._state_lock",
        ),
    ),
    "engine/tracker.py": (
        ClassDecl(
            name="DriftDetector",
            locks=(
                LockDecl(
                    attr="_lock", lock_id="DriftDetector._lock",
                    # record() runs on the dispatcher's rotation path while
                    # readers poll alarms(): short host-only sections
                    dispatch_ok=False,
                ),
            ),
            guards=(
                GuardDecl(
                    lock_id="DriftDetector._lock",
                    guarded=frozenset({"_series", "_alarms", "evals"}),
                ),
            ),
        ),
    ),
    "engine/windows.py": (
        # WindowPolicy is immutable-after-__post_init__ configuration; no
        # locks, nothing guarded — declared so the module is in the audited
        # set (a future mutable field added here must pick a lock or move)
        ClassDecl(name="WindowPolicy", exempt_methods=frozenset({"__init__", "__post_init__"})),
    ),
    "engine/aot.py": (
        ClassDecl(
            name="AotCache",
            locks=(
                LockDecl(
                    attr="_lock", lock_id="AotCache._lock",
                    # the lock deliberately spans build(): two engines racing
                    # one key pay ONE compile (its docstring contract)
                    dispatch_ok=True,
                ),
            ),
            guards=(
                GuardDecl(
                    lock_id="AotCache._lock",
                    guarded=frozenset({
                        "_programs", "hits", "misses", "compile_seconds", "cache_dir",
                    }),
                ),
            ),
        ),
    ),
}


# ------------------------------------------------------------- AST utilities


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute chains rooted at a bare Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "clear", "pop", "popleft", "remove",
    "add", "update", "insert", "discard", "setdefault",
}

#: jax dispatch heads/prefixes the no-dispatch-under-lock rule recognizes
_DISPATCH_PREFIXES = ("jnp.", "jax.numpy.")
_DISPATCH_CALLS = {
    "jax.device_get", "jax.device_put", "jax.block_until_ready",
    "device_get", "device_put", "block_until_ready",
    # the library's own host fold (the PR 8 histogram incident)
    "histogram_accumulate",
}
#: calling the RESULT of one of these suffixes is invoking a compiled
#: executable: self._compute_program()(state) is a device dispatch
_PROGRAM_SUFFIXES = ("_program", "_callable", "_executable")


def _is_dispatch_call(node: ast.Call) -> Optional[str]:
    """A human-readable label when ``node`` is a jax dispatch, else None."""
    d = dotted_name(node.func)
    if d is not None:
        if d in _DISPATCH_CALLS or any(d.startswith(p) for p in _DISPATCH_PREFIXES):
            return d
    if isinstance(node.func, ast.Call):
        inner = dotted_name(node.func.func)
        if inner is not None and inner.rsplit(".", 1)[-1].endswith(_PROGRAM_SUFFIXES):
            return f"{inner}()(...)"
    return None


# ------------------------------------------------------------ method summary


@dataclass
class Mutation:
    attr: str            # the guarded attribute (on `cls_name`)
    cls_name: str        # class the attribute belongs to
    kind: str            # "assigned" | "item-assigned" | "mutated via .x()"
    lineno: int
    held: FrozenSet[str]


@dataclass
class Acquisition:
    lock_id: str
    held_before: FrozenSet[str]
    lineno: int


@dataclass
class CallSite:
    cls_name: str        # resolved class of the receiver
    method: str
    lineno: int
    held: FrozenSet[str]


@dataclass
class DispatchCall:
    label: str
    lineno: int
    held: FrozenSet[str]


@dataclass
class WithRegion:
    """One explicit ``with self.<lock>`` region (check-then-act's unit)."""

    lock_id: str
    lineno: int
    order: int                     # lexical order within the method
    reads: Set[str] = field(default_factory=set)    # guarded attrs read
    writes: Set[str] = field(default_factory=set)   # guarded attrs written
    binds: Set[str] = field(default_factory=set)    # names assigned inside


@dataclass
class MethodSummary:
    name: str
    cls_name: str
    lineno: int
    entry_held: FrozenSet[str]
    mutations: List[Mutation] = field(default_factory=list)
    acquisitions: List[Acquisition] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    dispatch: List[DispatchCall] = field(default_factory=list)
    regions: List[WithRegion] = field(default_factory=list)
    #: (lineno, names read in the test) of if/while tests OUTSIDE any lock
    #: region — check-then-act's "decision on a stale value" evidence
    branch_uses: List[Tuple[int, FrozenSet[str]]] = field(default_factory=list)
    #: call sites whose receiver could not be resolved (kept for honesty)
    unresolved_calls: int = 0


@dataclass
class ClassModel:
    decl: ClassDecl
    filename: str
    methods: Dict[str, MethodSummary] = field(default_factory=dict)
    #: attr (incl. aliases) -> lock_id for locks this class can acquire
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    #: guarded attr -> (lock_id, rule_id)
    guard_map: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: methods assumed lock-held per lock_id (declared + suffix + closure)
    locked_methods: Dict[str, Set[str]] = field(default_factory=dict)

    def entry_locks(self, method: str) -> FrozenSet[str]:
        held = {
            lock_id
            for lock_id, names in self.locked_methods.items()
            if method in names
        }
        if self.decl.external_lock is not None:
            held.add(self.decl.external_lock)
        return frozenset(held)


class _MethodWalker:
    """One method's linear walk: tracks the held-lock set through ``with``
    scoping and bare acquire()/release() toggles, records mutations /
    acquisitions / calls / dispatch calls / with-regions. Nested def and
    lambda bodies are walked at their lexical position (the synchronous
    retry-closure idiom)."""

    def __init__(self, model: "_ModelBuilder", cls: ClassModel, summary: MethodSummary):
        self.model = model
        self.cls = cls
        self.s = summary
        self.locals: Dict[str, str] = {}   # local name -> collaborator class
        self.region_stack: List[WithRegion] = []
        self.n_regions = 0

    # -- lock resolution -----------------------------------------------------

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None:
            return self.cls.lock_attrs.get(attr)
        return None

    # -- the walk ------------------------------------------------------------

    def walk_body(self, body: Sequence[ast.stmt], held: Set[str]) -> None:
        held = set(held)  # acquire()/release() toggles stay block-local-ish
        for stmt in body:
            self._walk_stmt(stmt, held)

    def _walk_stmt(self, stmt: ast.stmt, held: Set[str]) -> None:
        if isinstance(stmt, ast.With):
            region_locks = []
            for item in stmt.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    # earlier items of the SAME with statement are already
                    # held when a later one acquires (`with self._a, self._b`)
                    self.s.acquisitions.append(
                        Acquisition(lock, frozenset(held) | frozenset(region_locks), stmt.lineno)
                    )
                    region_locks.append(lock)
                else:
                    self._visit_expr(item.context_expr, held)
            region = None
            if len(region_locks) == 1 and not self.region_stack:
                region = WithRegion(region_locks[0], stmt.lineno, self.n_regions)
                self.n_regions += 1
                self.region_stack.append(region)
            inner = set(held) | set(region_locks)
            for sub in stmt.body:
                self._walk_stmt(sub, inner)
            if region is not None:
                self.region_stack.pop()
                self.s.regions.append(region)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.walk_body(stmt.body, held)  # lexical-position execution model
            return
        if isinstance(stmt, ast.If):
            if not self.region_stack:
                names = frozenset(
                    n.id for n in ast.walk(stmt.test) if isinstance(n, ast.Name)
                )
                if names:
                    self.s.branch_uses.append((stmt.lineno, names))
            # branches are EXCLUSIVE: each arm walks its own copy of the
            # held set, so the if-arm's bare acquire() is never mistaken for
            # a re-acquisition by the elif-arm's (the _flush conditional-
            # acquisition idiom), while a genuine acquire() under an
            # enclosing hold keeps its self-edge. Test toggles apply to both
            # arms (the test runs on every path); after the statement only
            # locks held on EVERY arm survive — conservative in the safe
            # direction for the lockset rule.
            self._visit_expr(stmt.test, held)
            body_held = set(held)
            orelse_held = set(held)
            for sub in stmt.body:
                self._walk_stmt(sub, body_held)
            for sub in stmt.orelse:
                self._walk_stmt(sub, orelse_held)
            merged = body_held & orelse_held
            held.clear()
            held.update(merged)
            return
        if isinstance(stmt, ast.While):
            if not self.region_stack:
                names = frozenset(
                    n.id for n in ast.walk(stmt.test) if isinstance(n, ast.Name)
                )
                if names:
                    self.s.branch_uses.append((stmt.lineno, names))
            self._visit_expr(stmt.test, held)
            for sub in stmt.body + stmt.orelse:
                self._walk_stmt(sub, held)
            return
        if isinstance(stmt, ast.For):
            self._visit_expr(stmt.iter, held)
            self._bind_target(stmt.target, stmt.iter)
            for sub in stmt.body + stmt.orelse:
                self._walk_stmt(sub, held)
            return
        if isinstance(stmt, ast.Try):
            for sub in stmt.body:
                self._walk_stmt(sub, held)
            for handler in stmt.handlers:
                for sub in handler.body:
                    self._walk_stmt(sub, held)
            for sub in stmt.orelse + stmt.finalbody:
                self._walk_stmt(sub, held)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._record_mutation(stmt, held)
            value = getattr(stmt, "value", None)
            if value is not None:
                self._visit_expr(value, held)
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for t in targets:
                self._bind_target(t, value)
            return
        if isinstance(stmt, ast.Expr):
            # bare acquire()/release() toggles (conditional acquisition is
            # handled in _visit_expr, where the call is seen inside tests)
            self._visit_expr(stmt.value, held, toggle=held)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._visit_expr(child, held)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, held)

    # -- expression visit ----------------------------------------------------

    def _visit_expr(
        self, node: ast.AST, held: Set[str], toggle: Optional[Set[str]] = None
    ) -> None:
        if isinstance(node, ast.Call):
            self._visit_call(node, held, toggle)
            return
        attr = _self_attr(node)
        if attr is not None and isinstance(getattr(node, "ctx", None), ast.Load):
            self._record_read(attr, self.cls)
        # self.<coll>.<attr> reads
        if isinstance(node, ast.Attribute):
            recv = _self_attr(node.value)
            if recv is not None:
                coll = self.cls.decl.collaborators.get(recv)
                target = self.model.classes_by_name.get(coll) if coll else None
                if target is not None:
                    self._record_read(node.attr, target)
        # Lambda is itself an expr, so lambda bodies recurse through this
        # same loop (statement nodes can never be expression children)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._visit_expr(child, held, toggle)

    def _visit_call(
        self, node: ast.Call, held: Set[str], toggle: Optional[Set[str]]
    ) -> None:
        # acquire()/release() on a declared lock: linear hold toggling.
        # `self._lock.acquire()` used as an expression (if-test) counts too:
        # on the paths that continue, the lock is held.
        if isinstance(node.func, ast.Attribute) and node.func.attr in ("acquire", "release"):
            lock = self._lock_of(node.func.value)
            if lock is not None:
                mutate = toggle if toggle is not None else held
                if node.func.attr == "acquire":
                    # held_before keeps the lock itself when already held: a
                    # bare acquire() under an enclosing hold is the same
                    # self-deadlock as a nested `with` and must carry its
                    # self-edge into the reentrancy check (exclusive if/elif
                    # arms walk separate copies, so the conditional-
                    # acquisition idiom never fakes one)
                    self.s.acquisitions.append(
                        Acquisition(lock, frozenset(held), node.lineno)
                    )
                    mutate.add(lock)
                    held.add(lock)
                else:
                    mutate.discard(lock)
                    held.discard(lock)
                for a in node.args:
                    self._visit_expr(a, held)
                return
        label = _is_dispatch_call(node)
        if label is not None:
            self.s.dispatch.append(DispatchCall(label, node.lineno, frozenset(held)))
        # method-call resolution: self.m(...), self.<coll>.m(...), local.m(...)
        if isinstance(node.func, ast.Attribute):
            recv, meth = node.func.value, node.func.attr
            target_cls: Optional[str] = None
            if isinstance(recv, ast.Name) and recv.id == "self":
                target_cls = self.cls.decl.name
            else:
                recv_attr = _self_attr(recv)
                if recv_attr is not None:
                    # a method call ON a guarded container is a read of it
                    # (check-then-act: `self._result_cache.get(sid)` reads)
                    self._record_read(recv_attr, self.cls)
                    target_cls = self.cls.decl.collaborators.get(recv_attr)
                elif isinstance(recv, ast.Name):
                    target_cls = self.locals.get(recv.id)
                elif isinstance(recv, ast.Call):
                    # h = <...>.histograms() style receivers are handled via
                    # _bind_target; a direct chained call resolves here
                    inner = dotted_name(recv.func)
                    if inner is not None:
                        target_cls = self.cls.decl.method_returns.get(
                            inner.rsplit(".", 1)[-1]
                        )
                elif isinstance(recv, ast.Subscript):
                    sub_attr = _self_attr(recv.value)
                    if sub_attr is not None:
                        target_cls = self.cls.decl.collaborators.get(sub_attr)
            if target_cls is not None:
                self.s.calls.append(
                    CallSite(target_cls, meth, node.lineno, frozenset(held))
                )
                # mutator-method calls on guarded containers
                self._record_container_mutation(node, held)
            elif meth in _MUTATOR_METHODS:
                self._record_container_mutation(node, held)
            else:
                self.s.unresolved_calls += 1
        for child in list(node.args) + [kw.value for kw in node.keywords]:
            self._visit_expr(child, held)
        if not isinstance(node.func, ast.Attribute):
            self._visit_expr(node.func, held)

    # -- recording -----------------------------------------------------------

    def _guard_of(self, attr: str, cls: ClassModel) -> Optional[Tuple[str, str]]:
        return cls.guard_map.get(attr)

    def _record_read(self, attr: str, cls: ClassModel) -> None:
        g = self._guard_of(attr, cls)
        if g is not None and self.region_stack and self.region_stack[-1].lock_id == g[0]:
            self.region_stack[-1].reads.add(attr)

    def _record_write_region(self, attr: str, cls: ClassModel) -> None:
        g = self._guard_of(attr, cls)
        if g is not None and self.region_stack and self.region_stack[-1].lock_id == g[0]:
            self.region_stack[-1].writes.add(attr)

    def _bind_target(self, target: ast.AST, value: Optional[ast.AST]) -> None:
        names = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, ast.Tuple):
            names = [e.id for e in target.elts if isinstance(e, ast.Name)]
        for n in names:
            if self.region_stack:
                self.region_stack[-1].binds.add(n)
        # collaborator typing of locals: x = self._stats / h = tr.histograms()
        if isinstance(target, ast.Name) and value is not None:
            attr = _self_attr(value)
            if attr is not None:
                coll = self.cls.decl.collaborators.get(attr)
                if coll is not None:
                    self.locals[target.id] = coll
                    return
            if isinstance(value, ast.Call):
                d = dotted_name(value.func)
                if d is not None:
                    ret = self.cls.decl.method_returns.get(d.rsplit(".", 1)[-1])
                    if ret is not None:
                        self.locals[target.id] = ret
            if isinstance(value, ast.Subscript):
                sub_attr = _self_attr(value.value)
                if sub_attr is not None:
                    coll = self.cls.decl.collaborators.get(sub_attr)
                    if coll is not None:
                        self.locals[target.id] = coll

    def _guarded_here(self, attr: str) -> bool:
        # an external_lock class is ALL-guarded: every attribute mutation is
        # the caller-held lock's business (the class is pure bookkeeping)
        return attr in self.cls.guard_map or self.cls.decl.external_lock is not None

    def _mutation_target(self, e: ast.AST) -> Optional[Tuple[str, ClassModel, str]]:
        """(attr, owning class model, kind) for a guarded mutation target."""
        attr = _self_attr(e)
        if attr is not None:
            if self._guarded_here(attr):
                return attr, self.cls, "assigned"
            return None
        if isinstance(e, ast.Subscript):
            base = e.value
            attr = _self_attr(base)
            if attr is not None and self._guarded_here(attr):
                return attr, self.cls, "item-assigned"
            # self.<coll>.<attr>[...] =
            if isinstance(base, ast.Attribute):
                recv = _self_attr(base.value)
                if recv is not None:
                    coll = self.cls.decl.collaborators.get(recv)
                    target = self.model.classes_by_name.get(coll) if coll else None
                    if target is not None and base.attr in target.guard_map:
                        return base.attr, target, "item-assigned"
            return None
        # self.<coll>.<attr> =  (cross-object write: the _submit_item bug shape)
        if isinstance(e, ast.Attribute):
            recv = _self_attr(e.value)
            if recv is not None:
                coll = self.cls.decl.collaborators.get(recv)
                target = self.model.classes_by_name.get(coll) if coll else None
                if target is not None and e.attr in target.guard_map:
                    return e.attr, target, "assigned"
        return None

    def _record_mutation(self, stmt: ast.stmt, held: Set[str]) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        for t in targets:
            elts = t.elts if isinstance(t, ast.Tuple) else [t]
            for e in elts:
                hit = self._mutation_target(e)
                if hit is None:
                    continue
                attr, cls, kind = hit
                self.s.mutations.append(
                    Mutation(attr, cls.decl.name, kind, stmt.lineno, frozenset(held))
                )
                self._record_write_region(attr, cls)

    def _record_container_mutation(self, node: ast.Call, held: Set[str]) -> None:
        if not (isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATOR_METHODS):
            return
        hit = self._mutation_target(node.func.value)
        if hit is None:
            attr = _self_attr(node.func.value)
            if attr is not None and attr in self.cls.guard_map:
                hit = (attr, self.cls, "mutated")
        if hit is not None:
            attr, cls, _ = hit
            self.s.mutations.append(
                Mutation(
                    attr, cls.decl.name, f"mutated via .{node.func.attr}()",
                    node.lineno, frozenset(held),
                )
            )
            self._record_write_region(attr, cls)


class _ModelBuilder:
    def __init__(self) -> None:
        self.classes_by_name: Dict[str, ClassModel] = {}

    def add_file(
        self, tree: ast.Module, filename: str, decls: Sequence[ClassDecl]
    ) -> List[Tuple[ClassModel, ast.ClassDef]]:
        out: List[Tuple[ClassModel, ast.ClassDef]] = []
        class_nodes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
        for decl in decls:
            nodes = (
                class_nodes
                if decl.name == "*"
                else [n for n in class_nodes if n.name == decl.name]
            )
            for node in nodes:
                cls = ClassModel(decl=decl, filename=filename)
                cls.lock_attrs = {l.attr: l.lock_id for l in decl.locks}
                for g in decl.guards:
                    for a in g.guarded:
                        cls.guard_map[a] = (g.lock_id, g.rule_id)
                cls.locked_methods = {
                    l.lock_id: set(l.locked_methods) for l in decl.locks
                }
                if decl.external_lock is not None:
                    cls.locked_methods.setdefault(decl.external_lock, set())
                self._collect_aliases(node, cls)
                self.classes_by_name[node.name if decl.name == "*" else decl.name] = cls
                out.append((cls, node))
        # summaries in a second pass: collaborator resolution needs the full
        # class table (cross-file models are added before summarize())
        return out

    @staticmethod
    def _collect_aliases(node: ast.ClassDef, cls: ClassModel) -> None:
        """``self.X = <anything>._Y`` where _Y is a declared lock attr makes
        X an alias of that lock (one level: the `self._lock = other._lock`
        sharing idiom)."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign) or len(sub.targets) != 1:
                continue
            target_attr = _self_attr(sub.targets[0])
            if target_attr is None or target_attr in cls.lock_attrs:
                continue
            value = sub.value
            tail = None
            if isinstance(value, ast.Attribute):
                tail = value.attr
            elif isinstance(value, ast.Name):
                tail = value.id
            if tail in cls.lock_attrs:
                cls.lock_attrs[target_attr] = cls.lock_attrs[tail]

    def summarize(self, pairs: Iterable[Tuple[ClassModel, ast.ClassDef]]) -> None:
        for cls, node in pairs:
            methods = [
                n for n in node.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            # lock-held closure: declared + suffix first, then methods whose
            # every intra-class call site already holds the lock (private
            # helpers reached through one or more locked levels)
            for lock in cls.decl.locks:
                if lock.locked_suffix:
                    for m in methods:
                        if m.name.endswith(lock.locked_suffix):
                            cls.locked_methods[lock.lock_id].add(m.name)
            for m in methods:
                summary = MethodSummary(
                    name=m.name, cls_name=cls.decl.name, lineno=m.lineno,
                    entry_held=cls.entry_locks(m.name),
                )
                walker = _MethodWalker(self, cls, summary)
                walker.walk_body(m.body, set(summary.entry_held))
                cls.methods[m.name] = summary
            # closure fixpoint: each round may prove more methods lock-held
            # (an N-deep locked call chain needs N rounds; the cap is a
            # runaway guard far above any real nesting depth)
            for _ in range(16):
                closed = self._close_locked_methods(cls)
                rewalked = False
                for m in methods:
                    entry = cls.entry_locks(m.name)
                    if entry != cls.methods[m.name].entry_held:
                        summary = MethodSummary(
                            name=m.name, cls_name=cls.decl.name, lineno=m.lineno,
                            entry_held=entry,
                        )
                        walker = _MethodWalker(self, cls, summary)
                        walker.walk_body(m.body, set(entry))
                        cls.methods[m.name] = summary
                        rewalked = True
                if not closed and not rewalked:
                    break

    @staticmethod
    def _close_locked_methods(cls: ClassModel) -> bool:
        """One closure round: a private method whose every intra-class call
        site holds lock L joins L's locked set. Returns True on any change."""
        sites: Dict[str, List[FrozenSet[str]]] = {}
        for s in cls.methods.values():
            for call in s.calls:
                if call.cls_name == cls.decl.name:
                    sites.setdefault(call.method, []).append(call.held)
        changed = False
        for lock_id, locked in cls.locked_methods.items():
            for name, helds in sites.items():
                if (
                    name.startswith("_")
                    and name not in locked
                    and name in cls.methods
                    and helds
                    and all(lock_id in h for h in helds)
                ):
                    locked.add(name)
                    changed = True
        return changed


def build_class_models(
    sources: Mapping[str, Any],
    specs: Optional[Mapping[str, Sequence[ClassDecl]]] = None,
) -> Tuple[Dict[str, ClassModel], List[Finding]]:
    """Compile ``{filename: source-or-parsed-Module}`` + declarations into
    class models.

    Returns ``(classes_by_name, resolution_findings)`` — a declaration that
    no longer matches the source (class or lock attribute deleted/renamed)
    is a loud ``concurrency-decl-unresolved`` error, not a silent skip: a
    refactor that deletes a lock must fail ``make analyze``, not quietly
    shrink the audited surface.
    """
    specs = CONCURRENCY_SPECS if specs is None else specs
    builder = _ModelBuilder()
    findings: List[Finding] = []
    pairs: List[Tuple[ClassModel, ast.ClassDef]] = []
    for filename, source in sources.items():
        decls = _decls_for(filename, specs)
        if not decls:
            continue
        tree = source if isinstance(source, ast.Module) else ast.parse(source, filename=filename)
        declared = {d.name for d in decls if d.name != "*"}
        present = {n.name for n in ast.walk(tree) if isinstance(n, ast.ClassDef)}
        for missing in sorted(declared - present):
            findings.append(Finding(
                rule="concurrency-decl-unresolved", severity="error",
                where=f"{filename}:1",
                message=(
                    f"declared class {missing!r} not found in {filename} — the "
                    "concurrency declarations no longer match the source"
                ),
                hint=(
                    "update CONCURRENCY_SPECS in analysis/rules/locks.py "
                    "alongside the refactor (the declarations are the checked "
                    "record of the lock discipline)"
                ),
            ))
        pairs.extend(builder.add_file(tree, filename, decls))
    # lock attributes must exist where declared (a deleted lock fails here);
    # classes without an __init__ skip the check — lock creation lives in
    # construction, and a class with no constructor has nowhere to assign
    for cls, node in pairs:
        if cls.decl.inherits_locks:
            continue
        if not any(
            isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)) and m.name == "__init__"
            for m in node.body
        ):
            continue
        declared_attrs = {l.attr for l in cls.decl.locks}
        assigned = {
            _self_attr(t)
            for sub in ast.walk(node)
            if isinstance(sub, ast.Assign)
            for t in sub.targets
        }
        for attr in sorted(declared_attrs - assigned):
            findings.append(Finding(
                rule="concurrency-decl-unresolved", severity="error",
                where=f"{cls.filename}:{node.lineno}",
                message=(
                    f"{cls.decl.name} declares lock attribute {attr!r} but the "
                    "class never assigns it — lock deleted or renamed?"
                ),
                hint="fix the declaration in analysis/rules/locks.py or restore the lock",
            ))
    builder.summarize(pairs)
    return builder.classes_by_name, findings


def decls_for_file(
    filename: str, specs: Optional[Mapping[str, Sequence[ClassDecl]]] = None
) -> Tuple[ClassDecl, ...]:
    """The declarations whose path suffix matches ``filename`` (empty tuple
    for undeclared modules — they simply are not in the audited set)."""
    specs = CONCURRENCY_SPECS if specs is None else specs
    norm = filename.replace("\\", "/")
    for suffix, decls in specs.items():
        if norm.endswith(suffix):
            return tuple(decls)
    return ()


_decls_for = decls_for_file


# --------------------------------------------------------------- the lockset


def lockset_findings(
    classes: Mapping[str, ClassModel],
    only_rule: Optional[str] = None,
) -> List[Finding]:
    """The lockset rule: every mutation of a declared-guarded attribute with
    its lock statically held. ``only_rule`` restricts output to one emitted
    rule id (the ``lock-discipline`` legacy delegation)."""
    findings: List[Finding] = []
    for cls in classes.values():
        for summary in cls.methods.values():
            if summary.name in cls.decl.exempt_methods:
                continue
            owner_lookup = {cls.decl.name: cls}
            for mut in summary.mutations:
                owner = classes.get(mut.cls_name, owner_lookup.get(mut.cls_name))
                if owner is None:
                    continue
                lock_id, rule_id = owner.guard_map.get(
                    mut.attr, (owner.decl.external_lock, "concurrency-lockset")
                )
                if lock_id is None:
                    continue
                if only_rule is not None and rule_id != only_rule:
                    continue
                if lock_id in mut.held:
                    continue
                target = (
                    f"self.{mut.attr}"
                    if mut.cls_name == cls.decl.name
                    else f"{mut.cls_name}.{mut.attr}"
                )
                findings.append(Finding(
                    rule=rule_id, severity="error",
                    where=f"{cls.filename}:{mut.lineno}",
                    message=(
                        f"lock-guarded attribute {target} {mut.kind} without "
                        f"{lock_id} held (in {cls.decl.name}.{summary.name})"
                    ),
                    hint=(
                        "an unlocked read-modify-write can interleave with the "
                        "thread the lock exists for and lose the update — take "
                        "the lock, route the write through a locked method of "
                        "the owning class, or declare the method lock-held in "
                        "analysis/rules/locks.py with a comment saying why"
                    ),
                ))
    findings.sort(key=lambda f: (f.where, f.rule))
    return findings
