"""The rule catalog: every named invariant both analysis planes can evaluate.

Program-plane rules check traced jaxprs / compiled HLO of engine programs
(``analysis/program.py`` wires them to a built engine); source-plane rules
are AST lints over ``metrics_tpu/`` (``analysis/source.py``). Each entry
names the invariant, what violating it costs, and — where one exists — the
historical incident the rule encodes, so the catalog doubles as the repo's
institutional memory (docs/analysis.md renders it).
"""
from dataclasses import dataclass
from typing import Dict

from metrics_tpu.analysis.rules.arena import check_arena_pack_fused
from metrics_tpu.analysis.rules.collectives import (
    COLLECTIVE_PRIMITIVES,
    check_collective_multiset,
    check_host_collectives_pinned,
    check_no_collectives,
    collective_counts,
    collective_eqn_paths,
    expected_step_sync_collectives,
    hlo_collective_counts,
)
from metrics_tpu.analysis.rules.callbacks import check_no_host_callbacks
from metrics_tpu.analysis.rules.compile_cap import check_compile_cap
from metrics_tpu.analysis.rules.constants import (
    check_no_baked_host_constants,
    default_attr_alternates,
)
from metrics_tpu.analysis.rules.donation import (
    check_donation_honored,
    parse_hlo_aliased_params,
)
from metrics_tpu.analysis.rules.locks import (
    CONCURRENCY_SPECS,
    ClassDecl,
    GuardDecl,
    LockDecl,
    build_class_models,
    decls_for_file,
    lockset_findings,
)
from metrics_tpu.analysis.rules.pallas import (
    check_megastep_launch_count,
    check_no_scatter_under_pallas,
    check_pallas_call_count,
)
from metrics_tpu.analysis.rules.quantized import (
    check_quantized_policy_honored,
    expected_sync_payload,
)

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "CONCURRENCY_SPECS",
    "ClassDecl",
    "GuardDecl",
    "LockDecl",
    "RULES",
    "RuleInfo",
    "build_class_models",
    "check_arena_pack_fused",
    "check_collective_multiset",
    "check_compile_cap",
    "check_host_collectives_pinned",
    "check_donation_honored",
    "decls_for_file",
    "lockset_findings",
    "check_no_baked_host_constants",
    "check_no_collectives",
    "check_no_host_callbacks",
    "check_megastep_launch_count",
    "check_no_scatter_under_pallas",
    "check_pallas_call_count",
    "check_quantized_policy_honored",
    "collective_counts",
    "collective_eqn_paths",
    "default_attr_alternates",
    "expected_step_sync_collectives",
    "expected_sync_payload",
    "hlo_collective_counts",
    "parse_hlo_aliased_params",
]


@dataclass(frozen=True)
class RuleInfo:
    id: str
    plane: str       # "program" | "source" | "concurrency"
    severity: str
    summary: str
    incident: str = ""  # the historical bug this rule encodes, if any


RULES: Dict[str, RuleInfo] = {
    r.id: r
    for r in [
        RuleInfo(
            "no-collectives-in-deferred-step", "program", "error",
            "Deferred-sync steady steps carry zero cross-chip collectives "
            "(jaxpr at any depth, and compiled HLO).",
            incident="PR 5 pinned this with one-off jaxpr walks + HLO regexes per test",
        ),
        RuleInfo(
            "exact-collective-multiset-in-step-sync", "program", "error",
            "Step-sync mesh steps trace EXACTLY the fused bundle: one psum for "
            "all sum states + the token psum + one collective per extra "
            "(reduction, dtype).",
            incident="PR 5's per-test multiset pins",
        ),
        RuleInfo(
            "quantized-sync-policy-honored", "program", "error",
            "States ride the payload their sync_precision declares: the fused "
            "bundle's f32 psum element count and u32 gather word count (incl. "
            "the int8 codes+scales section) equal the policy's analytic plan — "
            "an 'exact' state on the quantized rider loses bit-exactness, a "
            "quantized state on the f32 psum pays exact bandwidth silently.",
            incident="ISSUE 10: the policy is a trace constant, so a stale "
            "program serves the WRONG precision without erroring",
        ),
        RuleInfo(
            "host-collectives-pinned", "program", "error",
            "Embedded-model host programs carry ONLY their sharding mode's "
            "declared collectives (hybrid Inception: all_gather of stem lanes; "
            "pipeline encoder: ppermute stage handoff; single-device: none) — "
            "metric steady steps stay collective-free, cross-chip traffic "
            "lives exclusively in the host's stage programs.",
            incident="ISSUE 19: the model-serving split is structural, so a "
            "layout leaking communication past its handoff re-couples metric "
            "dispatch to model sharding",
        ),
        RuleInfo(
            "no-host-callback-in-aggregate", "program", "error",
            "Device-aggregate programs (the ragged batched fold / corpus "
            "bundle) contain no host-callback primitives at any depth — a "
            "pure_callback inside the trace is a synchronous host round-trip "
            "per dispatch, the per-group host loop the path exists to delete, "
            "invisible to the dispatch counters.",
            incident="ISSUE 18: the aggregate's one-dispatch contract is "
            "pinned structurally, not just by the bench's latency series",
        ),
        RuleInfo(
            "no-scatter-under-pallas", "program", "error",
            "Programs traced under a Pallas kernel backend contain no scatter "
            "primitives — the kernels replace .at[ids].op with compare-reduce.",
            incident="PR 4's per-test zero-scatter pins",
        ),
        RuleInfo(
            "pallas-call-per-leaf", "program", "error",
            "Kernel-backend programs trace the expected pallas_call count "
            "(one per state leaf for delta metrics; >=1 in the engine audit). "
            "Megastep form (ISSUE 16): exactly one fused grid per eligible "
            "arena dtype and total launches <= dtypes + per-primitive budget "
            "— O(dtypes), never O(leaves).",
            incident="PR 4's closure-identity trace-cache footgun hid a zero count",
        ),
        RuleInfo(
            "donation-honored", "program", "error",
            "Every declared donated buffer is actually aliased in the compiled "
            "HLO's input_output_alias table — XLA dropping a donation silently "
            "double-buffers the state.",
        ),
        RuleInfo(
            "no-baked-host-constants", "program", "error",
            "A host-derived attr that changes the traced program must change "
            "the metric fingerprint — else shared AotCaches hand out programs "
            "with the wrong constant baked in.",
            incident="PR 3's Accuracy.mode shared-cache collision (found by accident)",
        ),
        RuleInfo(
            "arena-pack-fused", "program", "error",
            "No per-leaf materialized copies or per-leaf arena-buffer writes "
            "between unpack and pack — the arena step stays one concat per dtype. "
            "Megastep form (ISSUE 16): a fused dtype's buffer comes straight "
            "out of the grid; an XLA concatenate pack for it means the fusion "
            "silently degraded.",
        ),
        RuleInfo(
            "compile-cap", "program", "error",
            "Programs-per-engine accounting: at most len(buckets) update "
            "programs per payload structure + compute (+ merge when deferred).",
        ),
        RuleInfo(
            "traced-python-branch", "source", "error",
            "No Python if/while on a value reachable from a jit/vmap-traced "
            "parameter — it raises a TracerBoolConversionError at best, bakes "
            "one branch at worst.",
        ),
        RuleInfo(
            "closure-identity-trace-cache", "source", "warning",
            "Do not re-trace one closure under multiple lowering-changing "
            "contexts (use_backend, ...): JAX caches traces by function "
            "identity + avals, so the second context reuses the first jaxpr.",
            incident="PR 4: re-tracing one closure under two kernel backends reused the first lowering",
        ),
        RuleInfo(
            "lock-discipline", "source", "error",
            "Declared lock-guarded engine attributes mutate only inside "
            "`with self._state_lock` (or in methods declared lock-held) — the "
            "dispatcher donates live buffers, so unlocked RMW races tear state. "
            "Since ISSUE 14 an alias over the concurrency plane's lockset rule "
            "(one implementation) for the original state-lock guarded set.",
            incident="PR 3: reset_stream vs donating dispatcher RMW race",
        ),
        RuleInfo(
            "concurrency-lockset", "concurrency", "error",
            "Every mutation of a declared-guarded attribute happens with its "
            "lock statically held (with-stack walk + call-graph closure over "
            "*_locked/declared lock-held methods, cross-object writes "
            "included); mutating methods of caller-locked bookkeeping classes "
            "(StreamPager, TokenBucket) are only called under the declared lock.",
            incident=(
                "ISSUE 14: batches_submitted `+=` on producer threads and "
                "record_fault's dict bump from the admission site both lost "
                "increments — the PR 11 admission-counter class, re-found by "
                "this rule and fixed in the same PR"
            ),
        ),
        RuleInfo(
            "concurrency-lock-order", "concurrency", "error",
            "The may-acquire-under graph over all declared locks is acyclic "
            "(self-acquisition only for declared RLocks), and declared "
            "forbidden pairs never nest in either direction.",
            incident=(
                "PR 8: recorder and histogram locks must never nest — a fold "
                "under both stalls every producer's submit; now a checked "
                "property of the whole tree (FORBIDDEN_NESTINGS)"
            ),
        ),
        RuleInfo(
            "concurrency-dispatch-under-lock", "concurrency", "error",
            "No jax dispatch (jnp.*, compiled-executable calls, device_get/"
            "put, block_until_ready, histogram_accumulate folds) reachable "
            "while a dispatch_ok=False lock is held.",
            incident=(
                "PR 8 review: the histogram lock was held across the jax "
                "fold, blocking observe() — fixed by swapping the pending "
                "buffer out under the lock and folding after release"
            ),
        ),
        RuleInfo(
            "concurrency-check-then-act", "concurrency", "warning",
            "A guarded read whose result steers a branch after the lock is "
            "released, followed by a re-acquired write of the same attribute "
            "— between release and re-acquire the world may have changed.",
            incident=(
                "PR 11 review: stop() checked dispatcher liveness, released "
                "the world, then blocked on a put the dead dispatcher would "
                "never drain (TOCTOU) — fixed by re-checking in the put loop"
            ),
        ),
        RuleInfo(
            "concurrency-decl-unresolved", "concurrency", "error",
            "Every declared class, module and lock attribute still exists in "
            "the source — a refactor that deletes a lock or renames a guarded "
            "attribute must update the declarations in the same diff, not "
            "silently shrink the audited surface.",
        ),
        RuleInfo(
            "raise-tuple", "source", "error",
            "Exceptions are raised with ONE formatted message string — "
            "multi-arg (or tuple-literal) raises render as mangled tuples.",
            incident="PR 1: reference checks.py raise ValueError('...', '...') tuple-message bug",
        ),
        RuleInfo(
            "wallclock-in-jit", "source", "error",
            "No wall-clock or host-RNG calls inside jitted step builders — "
            "they bake one trace-time value into every later execution.",
        ),
        RuleInfo(
            "suppression-missing-reason", "source", "error",
            "Every `# analysis: disable=` directive carries a `-- reason`; "
            "silenced rules must say why.",
        ),
    ]
}
