"""Rule ``quantized-sync-policy-honored``: states ride the payload their
``sync_precision`` declares (ISSUE 10).

The quantized-sync contract is structural, like collective placement: under a
metric's policy, each state leaf belongs to exactly one rider — the f32 psum
bundle (exact floats + integer digit riders), a per-(reduction, dtype)
collective, the verbatim u32 gather carrier, or the block-scaled int8 section
of that carrier. A state crossing riders is silent corruption in one
direction (an "exact" count riding quantized loses bit-exactness) and a
silent bandwidth regression in the other (a quantized Gram accumulator
falling back to f32 psum).

The audit is size-based and program-plane: from the metric's declared
``(fx, leaf, precision)`` triples, ``parallel/collectives.py::fused_sync_plan``
derives the EXACT flat element count of the f32 psum bundle and the EXACT u32
word count of the shared gather — then the traced merge/step jaxpr must
contain a psum over exactly that many f32 elements (none, when everything
quantizes away) and an all_gather over exactly that many u32 words. Any
policy violation moves elements between the buckets and changes both counts,
so a mismatch IS the finding. The clean-twin fixture in
``tests/analysis/test_program_rules.py`` pins the analytic plan against an
actual ``fused_axis_sync`` trace, so the two can never drift apart silently.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

from metrics_tpu.analysis.core import Finding

__all__ = ["check_quantized_policy_honored", "expected_sync_payload"]


def expected_sync_payload(
    leaf_info: Sequence[Tuple[Any, Any, Optional[str]]], world: int
) -> Dict[str, int]:
    """``{"sum_elems", "gather_words"}`` the fused sync must trace for the
    declared ``(fx, abstract_leaf, precision)`` triples on a ``world``-shard
    axis — straight from the shared accounting in ``parallel/collectives.py``
    (quantized leaves' codes+scales words count into the gather)."""
    from metrics_tpu.parallel.collectives import fused_sync_plan

    plan = fused_sync_plan(leaf_info, world)
    return {
        "sum_elems": int(plan["sum_elems"]),
        "gather_words": int(plan["gather_words"] + plan["q8_words"]),
    }


def _bundle_sizes(jaxpr: Any) -> Tuple[List[int], List[int]]:
    """(f32 psum operand sizes, u32 all_gather operand sizes) anywhere in
    the jaxpr — the observable the policy audit compares against. The
    valid-row token psum is i32 and per-(reduction, dtype) collectives carry
    their own dtypes, so filtering by dtype isolates the fused bundle."""
    import numpy as np

    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    psums: List[int] = []
    gathers: List[int] = []
    for _, eqn in iter_eqns(unwrap_jaxpr(jaxpr)):
        name = eqn.primitive.name
        if name not in ("psum", "psum2", "all_gather", "all_gather_invariant"):
            continue
        for var in eqn.invars:
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None:
                continue
            size = 1
            for d in getattr(aval, "shape", ()):
                size *= int(d)
            if name.startswith("psum") and np.dtype(dtype) == np.float32:
                psums.append(size)
            elif name.startswith("all_gather") and np.dtype(dtype) == np.uint32:
                gathers.append(size)
    return psums, gathers


def check_quantized_policy_honored(
    jaxpr: Any,
    leaf_info: Sequence[Tuple[Any, Any, Optional[str]]],
    world: int,
    where: str = "",
) -> List[Finding]:
    """Audit one merge/step-sync program against the declared policy: the
    traced f32 psum bundle and u32 gather carrier must carry EXACTLY the
    element/word counts the policy implies. ``leaf_info`` is the metric's
    ``sync_leaf_info()``; ``world`` the mesh axis size the program lowered
    for (the integer digit split depends on it)."""
    want = expected_sync_payload(leaf_info, world)
    psums, gathers = _bundle_sizes(jaxpr)
    findings: List[Finding] = []
    hint = (
        "a state is riding the wrong payload for its declared sync_precision — "
        "an 'exact' state on the quantized rider loses bit-exactness, a "
        "quantized state on the f32 psum silently pays exact bandwidth; check "
        "that sync_states passes the per-leaf precisions through "
        "parallel/collectives.py::fused_axis_sync and that the policy was set "
        "BEFORE the engine compiled its programs"
    )
    if want["sum_elems"] > 0 and want["sum_elems"] not in psums:
        findings.append(Finding(
            rule="quantized-sync-policy-honored", severity="error",
            where=where, path="psum",
            message=(
                f"no f32 psum of {want['sum_elems']} elements in the program "
                f"(observed f32 psum sizes: {sorted(psums) or 'none'}) — the exact "
                "sum bundle does not match the declared policy"
            ),
            hint=hint,
        ))
    if want["sum_elems"] == 0 and psums:
        findings.append(Finding(
            rule="quantized-sync-policy-honored", severity="error",
            where=where, path="psum",
            message=(
                f"policy quantizes every sum leaf, but the program still traces "
                f"f32 psums of sizes {sorted(psums)} — an exact bundle survived"
            ),
            hint=hint,
        ))
    if want["gather_words"] > 0 and want["gather_words"] not in gathers:
        findings.append(Finding(
            rule="quantized-sync-policy-honored", severity="error",
            where=where, path="all_gather",
            message=(
                f"no u32 all_gather of {want['gather_words']} words in the program "
                f"(observed: {sorted(gathers) or 'none'}) — the carrier (incl. the "
                "quantized codes+scales section) does not match the declared policy"
            ),
            hint=hint,
        ))
    if want["gather_words"] == 0 and gathers:
        findings.append(Finding(
            rule="quantized-sync-policy-honored", severity="error",
            where=where, path="all_gather",
            message=(
                f"policy implies no gather carrier, but the program traces u32 "
                f"all_gathers of sizes {sorted(gathers)}"
            ),
            hint=hint,
        ))
    return findings
