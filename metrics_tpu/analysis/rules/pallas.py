"""Pallas-lowering rules: the fused-kernel invariants from PR 4.

Under a Pallas backend the engine's update step must carry its fold/segment
work INSIDE ``pallas_call`` kernels — one per state leaf for delta-strategy
metrics — and the segmented multi-stream path must be scatter-free (the
scatter-vs-compare-reduce tradeoff is the whole point of
``ops/kernels/pallas_segment.py``). Formerly pinned ad hoc by
``tests/ops/test_kernel_dispatch.py`` / ``test_kernel_attribution.py``.
"""
from typing import Any, List, Optional

from metrics_tpu.analysis.core import Finding

__all__ = [
    "check_megastep_launch_count",
    "check_no_scatter_under_pallas",
    "check_pallas_call_count",
]

#: substring of ``name_and_src_info`` that identifies a megastep grid — the
#: fused kernels are all named ``_mega_*`` (ops/kernels/pallas_megastep.py),
#: which distinguishes them from per-primitive launches (e.g. the histogram
#: MXU kernel a delta body calls itself) in a traced step
_MEGASTEP_KERNEL_MARK = "_mega_"


def _scatter_paths(jaxpr: Any) -> List[str]:
    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    return [
        f"{path}:{eqn.primitive.name}"
        for path, eqn in iter_eqns(unwrap_jaxpr(jaxpr))
        if eqn.primitive.name.startswith("scatter")
    ]


def check_no_scatter_under_pallas(jaxpr: Any, where: str = "") -> List[Finding]:
    """Rule ``no-scatter-under-pallas``: a program traced under a Pallas
    kernel backend must contain NO ``scatter*`` primitives at any depth —
    the kernels replace the ``.at[ids].op`` scatters with VMEM-resident
    compare-select reductions, and a surviving scatter means some update
    path silently fell back or bypassed the dispatcher."""
    return [
        Finding(
            rule="no-scatter-under-pallas", severity="error",
            where=where, path=path,
            message="scatter primitive traced in a Pallas-backend program",
            hint=(
                "route the update through ops/kernels (fold_rows_masked / "
                "segment_reduce_masked / histogram_accumulate); if the input is "
                "genuinely kernel-ineligible (dtype/shape), the engine should be "
                "audited with its RESOLVED backend = xla instead"
            ),
        )
        for path in _scatter_paths(jaxpr)
    ]


def check_pallas_call_count(
    jaxpr: Any,
    expected: Optional[int] = None,
    min_count: Optional[int] = None,
    max_count: Optional[int] = None,
    where: str = "",
) -> List[Finding]:
    """Rule ``pallas-call-per-leaf``: the number of ``pallas_call`` eqns in a
    kernel-backend program. ``expected`` pins an exact count (delta-strategy
    metrics fold one kernel per state leaf); ``min_count`` asserts the kernel
    path engaged at all (the engine audit's weaker form — eligibility rules
    may legitimately route SOME leaves to XLA); ``max_count`` bounds the
    launch count from above (the batched-read form, ISSUE 18: a ragged
    device aggregate folds its scalar-bundle columns in a handful of masked
    kernels — a count scaling with the group universe means the batched
    program degraded to per-group launches)."""
    from metrics_tpu.analysis.program import primitive_counts

    n = primitive_counts(jaxpr).get("pallas_call", 0)
    hint = (
        "a lower count means the kernel dispatch silently fell back (shape/dtype "
        "eligibility, or the trace-cache closure-identity footgun reusing an XLA "
        "trace); a higher count means per-leaf work split into extra kernels — "
        "see ops/kernels/dispatch.py for the eligibility rules"
    )
    if expected is not None and n != expected:
        return [Finding(
            rule="pallas-call-per-leaf", severity="error", where=where, path="",
            message=f"program traces {n} pallas_call eqns, expected exactly {expected}",
            hint=hint,
        )]
    findings: List[Finding] = []
    if min_count is not None and n < min_count:
        findings.append(Finding(
            rule="pallas-call-per-leaf", severity="error", where=where, path="",
            message=f"program traces {n} pallas_call eqns, expected at least {min_count}",
            hint=hint,
        ))
    if max_count is not None and n > max_count:
        findings.append(Finding(
            rule="pallas-call-per-leaf", severity="error", where=where, path="",
            message=(
                f"program traces {n} pallas_call eqns, expected at most "
                f"{max_count} — launch count must not scale with the group "
                "universe (batched-read contract)"
            ),
            hint=hint,
        ))
    return findings


def check_megastep_launch_count(
    jaxpr: Any,
    n_dtypes: int,
    extra: int = 0,
    where: str = "",
) -> List[Finding]:
    """Rule ``pallas-call-per-leaf`` (megastep form, ISSUE 16): under a
    megastep backend the steady step launches exactly ONE fused grid per
    eligible arena dtype — launch count scales with dtypes, never leaves.

    Megastep grids are identified by their kernel names (``_mega_*`` in the
    ``pallas_call`` eqn's ``name_and_src_info``); ``n_dtypes`` is the
    eligible-after-degradation dtype count. ``extra`` bounds the OTHER
    launches a step may legitimately carry — per-primitive kernels a delta
    body calls itself (ConfusionMatrix's bincount rides the histogram MXU
    kernel) — typically the metric count, still O(dtypes)-class, so a
    per-leaf regression (one kernel per state leaf) blows the bound."""
    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    names = [
        str(eqn.params.get("name_and_src_info", ""))
        for _, eqn in iter_eqns(unwrap_jaxpr(jaxpr))
        if eqn.primitive.name == "pallas_call"
    ]
    mega = [nm for nm in names if _MEGASTEP_KERNEL_MARK in nm]
    findings: List[Finding] = []
    if len(mega) != n_dtypes:
        findings.append(Finding(
            rule="pallas-call-per-leaf", severity="error", where=where, path="",
            message=(
                f"megastep program traces {len(mega)} fused-grid pallas_call "
                f"eqns, expected exactly {n_dtypes} (one per eligible arena "
                "dtype)"
            ),
            hint=(
                "fewer grids means a dtype silently fell off the whole-step "
                "path (check stats.kernel_fallbacks for the reason); more "
                "means the fold/segment/pack split back into multiple "
                "launches — see ops/kernels/pallas_megastep.py"
            ),
        ))
    budget = n_dtypes + max(0, extra)
    if len(names) > budget:
        findings.append(Finding(
            rule="pallas-call-per-leaf", severity="error", where=where, path="",
            message=(
                f"megastep program traces {len(names)} total pallas_call eqns "
                f"(> {budget} = dtypes + per-primitive budget) — launch count "
                "is scaling with leaves, not dtypes"
            ),
            hint=(
                "the megastep contract is O(dtypes) launches per steady step; "
                "per-leaf fold kernels alongside the fused grids mean the "
                "dispatcher ran BOTH paths for some leaves"
            ),
        ))
    return findings
