"""Arena-fusion rule: unpack -> update -> pack must not materialize per leaf.

The arena's whole value (PR 3) is that the carried state crosses the dispatch
boundary as one buffer per dtype while the jitted step's unpack (static
slices) and pack (one concatenate per dtype) fuse away. Two regressions
reintroduce per-leaf cost inside the program where nobody would see it:

* explicit device copies of CARRIED-STATE leaves (``jnp.array(x, copy=True)``
  / defensive clones inside the step) — one ``copy`` eqn per leaf. Copies of
  trace-time constants are benign (``init_state``'s per-leaf defensive copy
  of the zero defaults lowers to ``copy`` of a constant, which XLA folds), so
  the rule runs a forward TAINT walk from the state inputs and flags only
  copies reachable from them;
* packing by writing each leaf into the arena buffer individually
  (``buf.at[off:off+n].set(leaf)``) — one scatter per leaf into an
  arena-buffer-shaped output, serializing what the concat form fuses.
"""
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from metrics_tpu.analysis.core import Finding

__all__ = ["check_arena_pack_fused"]


def _arena_avals(layout: Any, worlds: Iterable[int]) -> Set[Tuple[Tuple[int, ...], str]]:
    """Full-buffer (shape, dtype) signatures in every carried form: per-shard
    ``(n,)`` and, for each mesh world size given, shard-stacked ``(world, n)``."""
    out: Set[Tuple[Tuple[int, ...], str]] = set()
    for k, n in layout.buffer_sizes().items():
        out.add(((n,), k))
        for w in worlds:
            out.add(((int(w), n), k))
    return out


def _tainted_copy_paths(jaxpr: Any, tainted_invars: Optional[int]) -> List[str]:
    """Eqn paths of every ``copy`` whose input derives from a tainted program
    input, walking sub-jaxprs with positional invar mapping where the
    container aligns (pjit/shard_map/scan: body invars mirror eqn invars;
    cond: branches take ``eqn.invars[1:]``) and a conservative all-tainted
    spill where it does not. ``tainted_invars`` = how many leading invars are
    tainted (None = all: taint every runtime input)."""
    from metrics_tpu.ops.profiling import eqn_subjaxprs

    out: List[str] = []

    def walk(jx: Any, tainted: Set[Any], path: str) -> None:
        live = set(tainted)
        for i, eqn in enumerate(jx.eqns):
            here = f"{path}/{eqn.primitive.name}@{i}" if path else f"{eqn.primitive.name}@{i}"
            in_vars = [v for v in eqn.invars if not type(v).__name__ == "Literal"]
            hit = any(v in live for v in in_vars)
            if eqn.primitive.name == "copy" and hit:
                out.append(here)
            for tag, sub in eqn_subjaxprs(eqn):
                sub_inv = list(sub.invars)
                if len(sub_inv) == len(eqn.invars):
                    sub_tainted = {
                        sv for sv, ov in zip(sub_inv, eqn.invars)
                        if type(ov).__name__ != "Literal" and ov in live
                    }
                elif len(sub_inv) == len(eqn.invars) - 1:  # cond branches
                    sub_tainted = {
                        sv for sv, ov in zip(sub_inv, eqn.invars[1:])
                        if type(ov).__name__ != "Literal" and ov in live
                    }
                else:  # unknown container: spill conservatively
                    sub_tainted = set(sub_inv) if hit else set()
                walk(sub, sub_tainted, f"{here}.{tag}")
            if hit:
                live.update(eqn.outvars)

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    invars = list(inner.invars)
    n = len(invars) if tainted_invars is None else min(tainted_invars, len(invars))
    walk(inner, set(invars[:n]), "")
    return out


#: containers the pack can legitimately sit inside — the write-scan descends
#: through these but NOT into loop/branch bodies (scan/while/cond), where an
#: arena-buffer-shaped write is metric-update semantics (e.g. a cat-strategy
#: capacity buffer that happens to share the arena buffer's shape), never
#: the step's pack
_TRANSPARENT_CONTAINERS = {
    "pjit", "closed_call", "core_call", "xla_call", "shard_map",
    "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint",
}


def _pack_level_eqns(jaxpr: Any, path: str = ""):
    from metrics_tpu.ops.profiling import eqn_subjaxprs

    for i, eqn in enumerate(jaxpr.eqns):
        here = f"{path}/{eqn.primitive.name}@{i}" if path else f"{eqn.primitive.name}@{i}"
        yield here, eqn
        if eqn.primitive.name in _TRANSPARENT_CONTAINERS:
            for tag, sub in eqn_subjaxprs(eqn):
                yield from _pack_level_eqns(sub, f"{here}.{tag}")


def check_arena_pack_fused(
    jaxpr: Any,
    layout: Any,
    where: str = "",
    worlds: Iterable[int] = (),
    state_leaves: Optional[int] = None,
    buffer_shapes: Optional[Iterable[Tuple[Tuple[int, ...], str]]] = None,
    fused_dtypes: Iterable[str] = (),
) -> List[Finding]:
    """Rule ``arena-pack-fused``: in an arena-carrying step program, flag

    * every ``copy`` eqn reachable from the carried state (``state_leaves``
      leading program inputs; None taints every input) — a materialized
      per-leaf clone between unpack and pack; copies of constants
      (``init_state`` defaults) are benign and ignored, and
    * every scatter/dynamic-update-slice whose OUTPUT is exactly an arena
      buffer (per-leaf writes into the packed form instead of one concat
      per dtype).

    ``buffer_shapes`` overrides the default per-shard/shard-stacked buffer
    signatures with the engine's REAL carried forms — the stream-sharded
    paged arena carries ``(resident, n)``/``(world, resident, n)`` buffers
    whose flat ``(n,)`` form never exists in its step, and matching the flat
    form there would misfire on the segmented update's legitimate per-slot
    scatters whenever a stacked state leaf happens to share it.

    ``fused_dtypes`` is the megastep form (ISSUE 16): dtypes whose arena
    buffer must come straight out of the fused grid. Under the per-leaf
    backends one ``concatenate`` per dtype IS the pack (the design this rule
    protects); under a megastep backend the re-pack happens inside the grid,
    so a pack-level ``concatenate`` producing an arena-buffer-shaped output
    of a fused dtype means the fusion silently degraded back to the XLA
    pack — flagged structurally, not just benched.
    """
    from metrics_tpu.analysis.program import unwrap_jaxpr

    findings: List[Finding] = []
    for path in _tainted_copy_paths(jaxpr, state_leaves):
        findings.append(Finding(
            rule="arena-pack-fused", severity="error", where=where, path=path,
            message="carried-state leaf materialized via an explicit device copy inside the step",
            hint=(
                "the arena contract keeps unpack/pack free after XLA fusion; "
                "drop the jnp.array(copy=True)/clone — transactional shadows "
                "belong OUTSIDE the compiled step (engine/pipeline.py::_step_shadow)"
            ),
        ))
    arena_sigs = (
        set(tuple(s) for s in buffer_shapes)
        if buffer_shapes is not None
        else _arena_avals(layout, worlds)
    )
    fused = set(fused_dtypes)
    for path, eqn in _pack_level_eqns(unwrap_jaxpr(jaxpr)):
        name = eqn.primitive.name
        is_write = name.startswith("scatter") or name == "dynamic_update_slice"
        is_concat = name == "concatenate"
        if not (is_write or is_concat):
            continue
        out_aval = eqn.outvars[0].aval if eqn.outvars else None
        if out_aval is None or not hasattr(out_aval, "shape"):
            continue
        sig = (tuple(int(d) for d in out_aval.shape), str(out_aval.dtype))
        if is_concat:
            if fused and sig[1] in fused and sig in arena_sigs:
                findings.append(Finding(
                    rule="arena-pack-fused", severity="error", where=where, path=path,
                    message=(
                        f"arena buffer {sig[0]}:{sig[1]} packed by an XLA "
                        "concatenate in a megastep program — the fused grid "
                        "no longer emits the packed form for this dtype"
                    ),
                    hint=(
                        "the megastep grid re-packs in VMEM (ops/kernels/"
                        "pallas_megastep.py); a concatenate pack here means "
                        "the engine split the dtype back onto the per-leaf "
                        "path without recording a fallback — check "
                        "MegastepPlan.fallback_reasons() against the traced "
                        "program"
                    ),
                ))
            continue
        if sig in arena_sigs:
            findings.append(Finding(
                rule="arena-pack-fused", severity="error", where=where, path=path,
                message=(
                    f"per-leaf {name} writes into an arena buffer "
                    f"{sig[0]}:{sig[1]} — the pack degraded from one concatenate "
                    "per dtype to one write per leaf"
                ),
                hint=(
                    "pack with ArenaLayout.pack/pack_stacked (a single per-dtype "
                    "concatenate XLA writes straight into the donated input); "
                    ".at[offset:offset+size].set loops serialize and defeat donation"
                ),
            ))
    return findings
