"""Host-callback rule: device aggregates stay on device (ISSUE 18).

The ragged device aggregate's whole contract is ONE device program plus one
scalar-bundle transfer; a ``pure_callback`` / ``io_callback`` /
``debug_callback`` smuggled anywhere into the traced aggregate reintroduces
a host round-trip INSIDE the dispatch — the per-group host loop the path
exists to delete, hidden where the stats counters (``result_device_calls``,
``agg_device_reads``) can no longer see it. The rule walks the re-traced
aggregate jaxprs at every depth, so a callback buried under a ``vmap`` or
``scan`` body fires the same as a top-level one.
"""
from typing import Any, List

from metrics_tpu.analysis.core import Finding

__all__ = ["check_no_host_callbacks"]


def _callback_paths(jaxpr: Any) -> List[str]:
    from metrics_tpu.analysis.program import iter_eqns, unwrap_jaxpr

    return [
        f"{path}:{eqn.primitive.name}"
        for path, eqn in iter_eqns(unwrap_jaxpr(jaxpr))
        if "callback" in eqn.primitive.name
    ]


def check_no_host_callbacks(jaxpr: Any, where: str = "") -> List[Finding]:
    """Rule ``no-host-callback-in-aggregate``: a device-aggregate program
    must contain NO host-callback primitives (``*callback*``) at any depth —
    each one is a synchronous host round-trip per dispatch, silently turning
    the one-program aggregate back into host-paced serving."""
    return [
        Finding(
            rule="no-host-callback-in-aggregate", severity="error",
            where=where, path=path,
            message="host callback primitive traced in a device-aggregate program",
            hint=(
                "express the score/fold on-device (grouped_batch_scores / "
                "grouped_corpus_device are traced under jit); host-only logic "
                "belongs in the plan/finish hooks, which run OUTSIDE the "
                "compiled program — or serve the metric with "
                "aggregate_oracle=True and keep the host path explicit"
            ),
        )
        for path in _callback_paths(jaxpr)
    ]
