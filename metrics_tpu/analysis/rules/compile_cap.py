"""Compile-cap rule: the closed-program-set accounting, as a named check.

The engine's serving contract (PR 2) is a CLOSED executable set: at most
``len(buckets)`` update programs per payload structure, one compute program,
plus one merge program under deferred sync. A program count above the cap
means the steady state is re-tracing — the exact dispatch regression the AOT
cache exists to prevent — usually via an unstable program key (identity
objects in the signature, a drifting fingerprint) or payload structures
nobody bucketed.
"""
from typing import List

from metrics_tpu.analysis.core import Finding

__all__ = ["check_compile_cap"]


def check_compile_cap(
    n_programs: int, cap: int, where: str = "", detail: str = ""
) -> List[Finding]:
    """Rule ``compile-cap``: ``n_programs`` compiled for one engine must not
    exceed ``cap``."""
    if n_programs <= cap:
        return []
    return [Finding(
        rule="compile-cap", severity="error", where=where, path="",
        message=(
            f"engine owns {n_programs} compiled programs, cap is {cap}"
            + (f" ({detail})" if detail else "")
        ),
        hint=(
            "an open program set re-traces in the steady state: check for "
            "unstable program-key inputs (object identity, un-latched host "
            "attrs drifting the fingerprint) or payload structures outside the "
            "bucket policy (engine/aot.py::AotCache.program_key)"
        ),
    )]
