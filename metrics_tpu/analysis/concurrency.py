"""Concurrency plane: the static contract checker over the threaded engine.

Every review pass since PR 6 has hand-found real concurrency bugs in the
serving engine — a bare ``+=`` losing admission increments across producer
threads, a histogram lock held across a jax fold stalling every submit,
TOCTOU in ``stop()``, ladder rungs stranded half-engaged. This plane pins
the bug CLASS structurally: the per-class lock declarations in
:mod:`metrics_tpu.analysis.rules.locks` (which attributes each lock guards,
which methods run lock-held, whether dispatch is legal under a hold) are
compiled into per-method summaries, and four rules run over the whole
package:

* ``concurrency-lockset`` — every mutation of a declared-guarded attribute
  happens with its lock statically held (intraprocedural ``with``-stack walk
  + call-graph closure over ``*_locked``/declared lock-held methods; the
  PR 7 ``lock-discipline`` rule id survives as an alias for the original
  state-lock guarded set). Also checks calls into externally-locked
  bookkeeping classes (``StreamPager``, ``TokenBucket``): a mutating method
  of a class whose contract says "caller holds the lock" must only be
  called with that lock held.
* ``concurrency-lock-order`` — the may-acquire-under graph across all
  declared locks must be acyclic (reentrant self-acquisition is legal only
  for declared RLocks), and declared forbidden pairs must never nest in
  EITHER direction — the "recorder and histogram locks never nest"
  invariant from PR 8 is :data:`FORBIDDEN_NESTINGS`' first entry.
* ``concurrency-dispatch-under-lock`` — no jax dispatch (``jnp.*``,
  compiled-executable calls, ``device_get``/``device_put``/
  ``block_until_ready``, ``histogram_accumulate`` host folds) reachable
  while a ``dispatch_ok=False`` lock is held — the exact stall class PR 8's
  review fixed by hand (the fold now swaps the pending buffer out under the
  lock and folds after releasing it).
* ``concurrency-check-then-act`` — a guarded read in one lock region whose
  result steers a branch that re-acquires the lock to write the same
  attribute (the ``stop()`` TOCTOU shape): between release and re-acquire
  the world may have changed.

Suppression works exactly like the source plane: ``# analysis:
disable=rule-id -- reason`` on (or directly above) the offending line, the
reason mandatory. Findings carry repo-relative ``file:line`` locations and
ride the same baseline ratchet (``tools/analyze.py``).
"""
import os
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.core import (
    Finding,
    Report,
    filter_suppressed,
    parse_suppressions,
)
from metrics_tpu.analysis.rules.locks import (
    CONCURRENCY_SPECS,
    ClassDecl,
    ClassModel,
    LockDecl,
    build_class_models,
    lockset_findings,
)

__all__ = [
    "FORBIDDEN_NESTINGS",
    "check_concurrency_sources",
    "check_concurrency_tree",
    "lock_order_edges",
]

#: lock pairs that must never nest in EITHER direction. The first entry is
#: the PR 8 invariant stated in ``engine/trace.py``: a producer's submit
#: needs the recorder lock (new_trace/_append), a scrape holds the histogram
#: lock across buffer swaps — nesting them in any order puts a fold's jax
#: dispatch (or a full ring walk) on the submit path.
FORBIDDEN_NESTINGS: Tuple[Tuple[str, str], ...] = (
    ("TraceRecorder._lock", "FixedBucketHistogram._lock"),
)


def _lock_registry(
    specs: Mapping[str, Sequence[ClassDecl]]
) -> Dict[str, LockDecl]:
    out: Dict[str, LockDecl] = {}
    for decls in specs.values():
        for decl in decls:
            for lock in decl.locks:
                out.setdefault(lock.lock_id, lock)
    return out


# ------------------------------------------------------------------ lock-order


def lock_order_edges(
    classes: Mapping[str, ClassModel],
) -> Dict[Tuple[str, str], Tuple[str, str]]:
    """The may-acquire-under graph: ``(held, acquired) -> (where, via)``.

    Direct edges come from acquisitions with a non-empty held set; transitive
    edges propagate each call site's held set onto every lock the callee
    (transitively) acquires — the cross-class closure that sees
    ``_ladder_tick``'s hold reach the histogram locks through
    ``tr.histograms()`` / ``h.quantile()``.
    """
    # transitive acquire sets per (class, method), fixpoint over the call graph
    acquires: Dict[Tuple[str, str], Set[str]] = {}
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            acquires[(cname, m)] = {a.lock_id for a in s.acquisitions}
    changed = True
    while changed:
        changed = False
        for cname, cls in classes.items():
            for m, s in cls.methods.items():
                cur = acquires[(cname, m)]
                for call in s.calls:
                    sub = acquires.get((call.cls_name, call.method))
                    if sub and not sub <= cur:
                        cur |= sub
                        changed = True
    edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            where_base = f"{cls.filename}"
            for acq in s.acquisitions:
                for held in acq.held_before:
                    edges.setdefault(
                        (held, acq.lock_id),
                        (f"{where_base}:{acq.lineno}", f"{cname}.{m}"),
                    )
            for call in s.calls:
                sub = acquires.get((call.cls_name, call.method), set())
                for held in call.held:
                    # held == acquired included: a TRANSITIVE re-acquisition
                    # of a non-reentrant lock (public helper callable both
                    # locked and unlocked) is a guaranteed self-deadlock the
                    # reentrancy check below must see
                    for acquired in sub:
                        edges.setdefault(
                            (held, acquired),
                            (
                                f"{where_base}:{call.lineno}",
                                f"{cname}.{m} -> {call.cls_name}.{call.method}",
                            ),
                        )
            # self-acquisition while already held (reentrancy check)
            for acq in s.acquisitions:
                if acq.lock_id in acq.held_before:
                    edges.setdefault(
                        (acq.lock_id, acq.lock_id),
                        (f"{where_base}:{acq.lineno}", f"{cname}.{m}"),
                    )
    return edges


def _find_cycle(edges: Iterable[Tuple[str, str]]) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        if a != b:
            graph.setdefault(a, []).append(b)
    WHITE, GREY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def visit(node: str) -> Optional[List[str]]:
        color[node] = GREY
        stack.append(node)
        for nxt in graph.get(node, ()):
            c = color.get(nxt, WHITE)
            if c == GREY:
                return stack[stack.index(nxt):] + [nxt]
            if c == WHITE:
                cyc = visit(nxt)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[node] = BLACK
        return None

    for node in sorted(graph):
        if color.get(node, WHITE) == WHITE:
            cyc = visit(node)
            if cyc is not None:
                return cyc
    return None


def _rule_lock_order(
    classes: Mapping[str, ClassModel],
    locks: Mapping[str, LockDecl],
    forbidden: Tuple[Tuple[str, str], ...],
) -> List[Finding]:
    findings: List[Finding] = []
    edges = lock_order_edges(classes)
    # reentrancy: a self-edge is legal only for declared RLocks
    for (a, b), (where, via) in sorted(edges.items()):
        if a == b:
            decl = locks.get(a)
            if decl is not None and not decl.reentrant:
                findings.append(Finding(
                    rule="concurrency-lock-order", severity="error",
                    where=where,
                    message=(
                        f"{a} re-acquired while already held (via {via}) but is "
                        "not declared reentrant — a plain threading.Lock "
                        "self-deadlocks here"
                    ),
                    hint=(
                        "make it an RLock and declare reentrant=True in "
                        "analysis/rules/locks.py, or restructure so the inner "
                        "acquisition happens after release"
                    ),
                ))
    for pair in forbidden:
        for a, b in (pair, pair[::-1]):
            hit = edges.get((a, b))
            if hit is not None:
                where, via = hit
                findings.append(Finding(
                    rule="concurrency-lock-order", severity="error",
                    where=where,
                    message=(
                        f"{b} acquired while {a} is held (via {via}) — this "
                        "pair is declared never-nesting"
                    ),
                    hint=(
                        "the PR 8 contract: recorder and histogram locks never "
                        "nest, so a scrape's fold can never block a producer's "
                        "submit — release the outer lock first (swap the data "
                        "out under it, work after)"
                    ),
                ))
    cycle = _find_cycle(edges.keys())
    if cycle is not None:
        legs = [
            f"{a} -> {b} (at {edges[(a, b)][0]} via {edges[(a, b)][1]})"
            for a, b in zip(cycle, cycle[1:])
        ]
        findings.append(Finding(
            rule="concurrency-lock-order", severity="error",
            where=edges[(cycle[0], cycle[1])][0],
            message=(
                "lock-order cycle: two threads taking these locks in opposite "
                "orders deadlock — " + "; ".join(legs)
            ),
            hint=(
                "pick ONE global order for the locks in the cycle and "
                "restructure the odd acquisition out (the engine's standing "
                "order: ladder lock > state lock > leaf subsystem locks)"
            ),
        ))
    return findings


# -------------------------------------------------------- dispatch-under-lock


def _rule_dispatch_under_lock(
    classes: Mapping[str, ClassModel],
    locks: Mapping[str, LockDecl],
) -> List[Finding]:
    no_dispatch = {lid for lid, d in locks.items() if not d.dispatch_ok}
    # transitive "does this method dispatch?" with a sample label, fixpoint
    dispatches: Dict[Tuple[str, str], Optional[str]] = {}
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            dispatches[(cname, m)] = s.dispatch[0].label if s.dispatch else None
    changed = True
    while changed:
        changed = False
        for cname, cls in classes.items():
            for m, s in cls.methods.items():
                if dispatches[(cname, m)] is not None:
                    continue
                for call in s.calls:
                    sub = dispatches.get((call.cls_name, call.method))
                    if sub is not None:
                        dispatches[(cname, m)] = (
                            f"{call.cls_name}.{call.method} -> {sub}"
                        )
                        changed = True
                        break
    findings: List[Finding] = []
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            for d in s.dispatch:
                bad = sorted(d.held & no_dispatch)
                if bad:
                    findings.append(_dispatch_finding(
                        cls, m, d.lineno, d.label, bad
                    ))
            for call in s.calls:
                bad = sorted(call.held & no_dispatch)
                if not bad:
                    continue
                sub = dispatches.get((call.cls_name, call.method))
                if sub is not None:
                    findings.append(_dispatch_finding(
                        cls, m, call.lineno,
                        f"{call.cls_name}.{call.method} -> {sub}", bad,
                    ))
    findings.sort(key=lambda f: f.where)
    return findings


def _dispatch_finding(
    cls: ClassModel, method: str, lineno: int, label: str, held: List[str]
) -> Finding:
    return Finding(
        rule="concurrency-dispatch-under-lock", severity="error",
        where=f"{cls.filename}:{lineno}",
        message=(
            f"jax dispatch {label} reachable while {', '.join(held)} is held "
            f"(in {cls.decl.name}.{method}) — a hot-path lock held across a "
            "device dispatch stalls every thread that needs it"
        ),
        hint=(
            "swap the data out under the lock and dispatch AFTER releasing it "
            "(the FixedBucketHistogram.flush pattern), or — if this lock is "
            "meant to serialize device work — declare dispatch_ok=True in "
            "analysis/rules/locks.py with a comment saying why"
        ),
    )


# ------------------------------------------------------------- check-then-act


def _rule_check_then_act(classes: Mapping[str, ClassModel]) -> List[Finding]:
    findings: List[Finding] = []
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            regions = sorted(s.regions, key=lambda r: r.order)
            for i, first in enumerate(regions):
                if not first.reads or not first.binds:
                    continue
                for second in regions[i + 1:]:
                    if second.lock_id != first.lock_id:
                        continue
                    overlap = sorted(first.reads & second.writes)
                    if not overlap:
                        continue
                    # the released-window dependency: a branch BETWEEN the
                    # two holds steers on a name bound under the first (a
                    # branch after the second hold steers nothing it wrote)
                    steering = [
                        lineno
                        for lineno, names in s.branch_uses
                        if first.lineno <= lineno < second.lineno
                        and names & first.binds
                    ]
                    if not steering:
                        continue
                    findings.append(Finding(
                        rule="concurrency-check-then-act", severity="warning",
                        where=f"{cls.filename}:{second.lineno}",
                        message=(
                            f"check-then-act on {', '.join('self.' + a for a in overlap)}: "
                            f"read under {first.lock_id} at line {first.lineno}, "
                            f"lock released, branch at line {steering[0]} steers "
                            "on the stale value, then the lock is re-acquired to "
                            f"write it (in {cls.decl.name}.{m})"
                        ),
                        hint=(
                            "between release and re-acquire another thread may "
                            "have changed the attribute — widen the hold over "
                            "the whole read-decide-write, or re-validate after "
                            "re-acquiring (the stop() TOCTOU shape, fixed in "
                            "PR 11 by re-checking liveness inside the loop)"
                        ),
                    ))
    findings.sort(key=lambda f: f.where)
    return findings


# ----------------------------------------- externally-locked call-site checks


def _rule_external_callsites(classes: Mapping[str, ClassModel]) -> List[Finding]:
    """Calls into an ``external_lock`` class's MUTATING methods must hold the
    declared lock (part of the lockset contract: the class is bookkeeping,
    the caller owns the serialization)."""
    # transitively-mutating methods per external-locked class
    mutating: Dict[Tuple[str, str], bool] = {}
    for cname, cls in classes.items():
        if cls.decl.external_lock is None:
            continue
        for m, s in cls.methods.items():
            mutating[(cname, m)] = bool(s.mutations)
    changed = True
    while changed:
        changed = False
        for (cname, m), flag in list(mutating.items()):
            if flag:
                continue
            for call in classes[cname].methods[m].calls:
                if mutating.get((call.cls_name, call.method)):
                    mutating[(cname, m)] = True
                    changed = True
                    break
    findings: List[Finding] = []
    for cname, cls in classes.items():
        for m, s in cls.methods.items():
            for call in s.calls:
                callee_cls = classes.get(call.cls_name)
                if callee_cls is None or callee_cls.decl.external_lock is None:
                    continue
                if call.cls_name == cname:
                    continue  # internal calls ride the entry contract
                lock = callee_cls.decl.external_lock
                if lock in call.held:
                    continue
                if not mutating.get((call.cls_name, call.method)):
                    continue  # pure reads are the caller's staleness to own
                findings.append(Finding(
                    rule="concurrency-lockset", severity="error",
                    where=f"{cls.filename}:{call.lineno}",
                    message=(
                        f"{call.cls_name}.{call.method}() mutates state that "
                        f"{lock} guards, called without it (in "
                        f"{cls.decl.name}.{m}) — the class is declared "
                        "caller-locked bookkeeping"
                    ),
                    hint=(
                        "take the lock around the call, or move the call into "
                        "a lock-held method (declared in analysis/rules/locks.py)"
                    ),
                ))
    findings.sort(key=lambda f: f.where)
    return findings


# ------------------------------------------------------------------- drivers


def check_concurrency_sources(
    sources: Mapping[str, str],
    specs: Optional[Mapping[str, Sequence[ClassDecl]]] = None,
    forbidden: Optional[Tuple[Tuple[str, str], ...]] = None,
) -> Report:
    """Run all four rules over ``{filename: source}`` (fixtures and tests
    inject their own ``specs``/``forbidden``; the package sweep uses the
    shipped declarations)."""
    specs = CONCURRENCY_SPECS if specs is None else specs
    forbidden = FORBIDDEN_NESTINGS if forbidden is None else forbidden
    classes, findings = build_class_models(sources, specs)
    locks = _lock_registry(specs)
    findings = list(findings)
    findings.extend(lockset_findings(classes))
    findings.extend(_rule_external_callsites(classes))
    findings.extend(_rule_lock_order(classes, locks, forbidden))
    findings.extend(_rule_dispatch_under_lock(classes, locks))
    findings.extend(_rule_check_then_act(classes))
    report = Report()
    report.extend(filter_suppressed(
        findings, {fn: parse_suppressions(src) for fn, src in sources.items()}
    ))
    n_locks = len(locks)
    n_methods = sum(len(c.methods) for c in classes.values())
    report.note(
        f"concurrency plane: {len(sources)} files, {len(classes)} classes, "
        f"{n_locks} declared locks, {n_methods} methods walked"
    )
    return report


def check_concurrency_tree(
    root: str,
    specs: Optional[Mapping[str, Sequence[ClassDecl]]] = None,
    package_rel: bool = True,
) -> Report:
    """The package sweep: read every declared module under ``root`` (the
    ``metrics_tpu`` package dir) and run the plane. A declared module that
    no longer exists is a loud finding — deleting a threaded module must
    shrink the declarations in the same diff."""
    specs = CONCURRENCY_SPECS if specs is None else specs
    root = os.path.abspath(root)
    rel_base = os.path.dirname(root) if package_rel else root
    sources: Dict[str, str] = {}
    missing: List[str] = []
    for suffix in sorted(specs):
        path = os.path.join(root, suffix)
        if not os.path.exists(path):
            missing.append(suffix)
            continue
        rel = os.path.relpath(path, rel_base).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            sources[rel] = fh.read()
    report = check_concurrency_sources(sources, specs)
    for suffix in missing:
        report.extend([Finding(
            rule="concurrency-decl-unresolved", severity="error",
            where=f"{suffix}:1",
            message=(
                f"declared module {suffix} not found under {root} — the "
                "concurrency declarations no longer match the tree"
            ),
            hint="update CONCURRENCY_SPECS in analysis/rules/locks.py alongside the refactor",
        )])
    return report
