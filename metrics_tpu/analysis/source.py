"""Source plane: an AST lint over ``metrics_tpu/`` for known trace hazards.

Every rule here encodes a failure class this repo (or its reference) has
actually hit — the lint is institutional memory, not style policing:

* ``traced-python-branch`` — ``if``/``while`` on a value reachable from a
  jit/vmap-traced parameter: a ``TracerBoolConversionError`` at best, one
  branch silently baked into the compiled program at worst.
* ``closure-identity-trace-cache`` — tracing the SAME function object under
  two lowering-changing contexts (``use_backend``): JAX caches traces by
  function identity + avals, so the second context reuses the first jaxpr
  (the PR-4 footgun; build a fresh closure per context).
* ``lock-discipline`` — the engine declares which attributes the dispatcher's
  state lock guards (:data:`LOCK_SPECS`); mutating one outside
  ``with self._state_lock`` (or outside a method declared lock-held) races a
  step that DONATES the live buffers (the PR-3 ``reset_stream`` RMW race).
  Since ISSUE 14 this rule is an ALIAS over the concurrency plane's lockset
  rule (one implementation, :mod:`metrics_tpu.analysis.rules.locks`): the
  declarations live in ``CONCURRENCY_SPECS`` — per-class, multi-lock,
  package-wide — and :data:`LOCK_SPECS` is a derived view kept for the
  original two-file surface (existing suppressions/baselines keep working).
* ``raise-tuple`` — multi-arg / tuple-literal raises render mangled tuple
  messages (the PR-1 reference-inherited bug, generalized).
* ``wallclock-in-jit`` — wall-clock or host-RNG calls inside jitted step
  builders bake one trace-time value into every later execution.

Suppress per line with ``# analysis: disable=rule-id -- reason`` (trailing
the offending line, or a comment-only directive on the line above); the
reason is required. Findings point at ``file:line``.
"""
import ast
import os
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.core import (
    Finding,
    Report,
    filter_suppressed,
    parse_suppressions,
)
from metrics_tpu.analysis.rules.locks import (
    CONCURRENCY_SPECS,
    build_class_models,
    decls_for_file,
    lockset_findings,
)

__all__ = ["LOCK_SPECS", "LockSpec", "check_source_text", "check_source_tree"]

# attribute reads that are STATIC metadata, legal to branch on under a trace
_METADATA_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "aval", "sharding"}
# builtins whose result over a traced value is host-side metadata
_METADATA_CALLS = {"isinstance", "hasattr", "getattr", "callable", "len", "type", "id"}
# context managers that change how a function LOWERS without changing its identity
_LOWERING_CTXS = {"use_backend", "kernel_fault_scope", "default_matmul_precision", "enable_x64"}
# call heads that trace their callable argument
_TRACE_HEADS = {"make_jaxpr", "jit", "op_costs", "trace_primitive_counts"}
# wall-clock / host-RNG dotted-call prefixes (jax.random is fine: key-driven)
_WALLCLOCK_PREFIXES = (
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.now",
    "np.random.", "numpy.random.", "random.",
)
@dataclass(frozen=True)
class LockSpec:
    """The declared state-lock discipline of one engine module (the original
    PR 7 vocabulary — now a VIEW derived from the per-class declarations in
    ``analysis/rules/locks.py::CONCURRENCY_SPECS``, which is the single
    source of truth for all lock declarations)."""

    lock_attr: str
    guarded: FrozenSet[str]
    #: methods the call graph only reaches with the lock already held (the
    #: lexical analysis cannot see callers); the ``*_locked`` naming
    #: convention is recognized automatically on top of this list
    locked_methods: FrozenSet[str]
    exempt_methods: FrozenSet[str] = frozenset({"__init__"})


def _derive_lock_specs() -> Dict[str, LockSpec]:
    """The legacy two-file view over CONCURRENCY_SPECS: the state lock and
    the guarded set whose findings still carry the ``lock-discipline`` id."""
    out: Dict[str, LockSpec] = {}
    for suffix in ("engine/pipeline.py", "engine/multistream.py"):
        decl = CONCURRENCY_SPECS[suffix][0]
        state = next(l for l in decl.locks if l.attr == "_state_lock")
        legacy = next(g for g in decl.guards if g.rule_id == "lock-discipline")
        out[suffix] = LockSpec(state.attr, legacy.guarded, state.locked_methods)
    return out


#: path-suffix -> declared discipline. The analyzer applies the spec whose
#: suffix matches the linted file; everything else skips the rule.
LOCK_SPECS: Dict[str, LockSpec] = _derive_lock_specs()


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute chains rooted at a bare Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_head(node: ast.AST) -> Optional[str]:
    """Last segment of a call's dotted callee ('jit' for jax.jit)."""
    d = _dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def _is_jit_decorator(dec: ast.AST) -> bool:
    d = _dotted(dec)
    if d in ("jax.jit", "jit", "jax.vmap", "vmap"):
        return True
    if isinstance(dec, ast.Call):
        head = _dotted(dec.func)
        if head in ("jax.jit", "jit", "jax.vmap", "vmap"):
            return True
        if head in ("partial", "functools.partial") and dec.args:
            return _dotted(dec.args[0]) in ("jax.jit", "jit", "jax.vmap", "vmap")
    return False


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return {n for n in names if n not in ("self", "cls")}


def _static_params_from_call(call: ast.Call, fn: ast.AST) -> Set[str]:
    """Parameter names a jit decoration/call declares STATIC — those are host
    values, branchable at will (``static_argnames``/``static_argnums``)."""
    out: Set[str] = set()
    a = fn.args
    positional = [p.arg for p in a.posonlyargs + a.args]
    for kw in call.keywords:
        vals = (
            kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) else [kw.value]
        )
        consts = [v.value for v in vals if isinstance(v, ast.Constant)]
        if kw.arg == "static_argnames":
            out.update(str(c) for c in consts)
        elif kw.arg == "static_argnums":
            for c in consts:
                if isinstance(c, int) and 0 <= c < len(positional):
                    out.add(positional[c])
    return out


def _jit_target_functions(tree: ast.Module) -> List[Tuple[ast.AST, Set[str]]]:
    """``(function, traced_param_names)`` for every function whose body runs
    under a trace: decorated with jit/vmap, or passed BY NAME to
    ``jax.jit``/``jax.vmap``/``jax.make_jaxpr``/``jax.shard_map``/``lax.scan``
    anywhere in the module. Parameters declared static are excluded."""
    traced_calls: Dict[str, List[ast.Call]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            head = _dotted(node.func)
            tail = head.rsplit(".", 1)[-1] if head else None
            if tail in ("jit", "vmap", "make_jaxpr", "shard_map", "scan", "fori_loop", "while_loop"):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        traced_calls.setdefault(arg.id, []).append(node)
    out: List[Tuple[ast.AST, Set[str]]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        jit_calls: List[ast.Call] = []
        is_target = False
        for dec in node.decorator_list:
            if _is_jit_decorator(dec):
                is_target = True
                if isinstance(dec, ast.Call):
                    jit_calls.append(dec)
        if node.name in traced_calls:
            is_target = True
            jit_calls.extend(traced_calls[node.name])
        if not is_target:
            continue
        traced = _param_names(node)
        for call in jit_calls:
            traced -= _static_params_from_call(call, node)
        out.append((node, traced))
    return out


def _traced_value_uses(node: ast.AST, traced: Set[str]) -> List[ast.Name]:
    """Name nodes in an expression that read a traced value AS A VALUE —
    metadata reads (``x.shape``/``x is None``/``isinstance(x, ...)``) are
    host-side facts and excluded."""
    if isinstance(node, ast.Name):
        return [node] if node.id in traced else []
    if isinstance(node, ast.Attribute):
        return [] if node.attr in _METADATA_ATTRS else _traced_value_uses(node.value, traced)
    if isinstance(node, ast.Call):
        head = _call_head(node.func)
        if head in _METADATA_CALLS:
            return []
        out: List[ast.Name] = []
        for child in list(node.args) + [kw.value for kw in node.keywords]:
            out.extend(_traced_value_uses(child, traced))
        return out
    if isinstance(node, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
    ):
        return []
    out = []
    for child in ast.iter_child_nodes(node):
        out.extend(_traced_value_uses(child, traced))
    return out


# ------------------------------------------------------------------ the rules


def _rule_traced_branch(tree: ast.Module, filename: str) -> List[Finding]:
    findings = []
    for fn, traced in _jit_target_functions(tree):
        if not traced:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            uses = _traced_value_uses(node.test, traced)
            if uses:
                names = sorted({u.id for u in uses})
                findings.append(Finding(
                    rule="traced-python-branch", severity="error",
                    where=f"{filename}:{node.lineno}",
                    message=(
                        f"Python {'if' if isinstance(node, ast.If) else 'while'} on "
                        f"traced parameter(s) {names} of jitted function {fn.name!r}"
                    ),
                    hint=(
                        "a traced value has no host truth value: branch with "
                        "jnp.where/lax.cond/lax.select, or hoist the decision to a "
                        "static (metadata) property — .shape/.dtype/is None are fine"
                    ),
                ))
    return findings


def _scope_walk(scope: ast.AST) -> Iterable[ast.AST]:
    """Walk one scope WITHOUT descending into nested function bodies — each
    function is its own scope, so shared with-blocks are never double-counted."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _rule_closure_identity(tree: ast.Module, filename: str) -> List[Finding]:
    findings: List[Finding] = []
    scopes: List[ast.AST] = [tree] + [
        n for n in ast.walk(tree) if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    for scope in scopes:
        withs: List[ast.With] = [n for n in _scope_walk(scope) if isinstance(n, ast.With)]
        ctx_withs = [
            w for w in withs
            if any(
                isinstance(item.context_expr, ast.Call)
                and _call_head(item.context_expr.func) in _LOWERING_CTXS
                for item in w.items
            )
        ]
        if len(ctx_withs) < 2:
            continue
        ctx_withs.sort(key=lambda w: w.lineno)  # findings anchor on the RE-trace
        seen: Dict[str, Tuple[ast.With, int]] = {}
        for w in ctx_withs:
            for node in ast.walk(w):
                if not (isinstance(node, ast.Call) and _call_head(node.func) in _TRACE_HEADS):
                    continue
                for arg in node.args[:1]:
                    if not isinstance(arg, ast.Name):
                        continue  # lambdas / fresh closures are the fix, not the bug
                    if _defined_inside(w, arg.id):
                        continue
                    prev = seen.get(arg.id)
                    if prev is not None and prev[0] is not w:
                        findings.append(Finding(
                            rule="closure-identity-trace-cache", severity="warning",
                            where=f"{filename}:{node.lineno}",
                            message=(
                                f"{arg.id!r} re-traced under a second lowering context "
                                f"(first traced at line {prev[1]}): JAX caches traces by "
                                "function identity + avals, so this reuses the FIRST "
                                "context's jaxpr"
                            ),
                            hint=(
                                "wrap in a fresh closure per context — "
                                f"`lambda *a: {arg.id}(*a)` — or rebuild the function "
                                "inside each `with` block (ops/kernels/dispatch.py "
                                "documents the trace-cache caveat)"
                            ),
                        ))
                    else:
                        seen.setdefault(arg.id, (w, node.lineno))
    return findings


def _defined_inside(w: ast.With, name: str) -> bool:
    for node in ast.walk(w):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == name:
            return True
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


def _rule_lock_discipline(tree: ast.Module, filename: str) -> List[Finding]:
    """Delegates to the concurrency plane's lockset walker (ONE
    implementation — ``analysis/rules/locks.py``) and keeps only the findings
    carrying the legacy ``lock-discipline`` rule id: the state-lock guarded
    set of the two original engine modules. The full multi-lock, package-wide
    check (plus lock-order/dispatch/check-then-act) runs as the concurrency
    plane; ``tools/analyze.py`` dedupes the overlap by finding key."""
    decls = decls_for_file(filename)
    if not any(
        g.rule_id == "lock-discipline" for d in decls for g in d.guards
    ):
        return []  # only pipeline/multistream carry the legacy alias guard
    classes, decl_findings = build_class_models({filename: tree})
    findings = [f for f in decl_findings if f.rule == "lock-discipline"]
    findings.extend(lockset_findings(classes, only_rule="lock-discipline"))
    return findings


def _rule_raise_tuple(tree: ast.Module, filename: str) -> List[Finding]:
    findings = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Raise) and isinstance(node.exc, ast.Call)):
            continue
        bad = None
        if len(node.exc.args) > 1:
            bad = f"{len(node.exc.args)} positional args"
        elif len(node.exc.args) == 1 and isinstance(node.exc.args[0], ast.Tuple):
            bad = "a tuple literal argument"
        if bad:
            findings.append(Finding(
                rule="raise-tuple", severity="error",
                where=f"{filename}:{node.lineno}",
                message=f"exception raised with {bad} — str(exc) renders a mangled tuple",
                hint=(
                    "join the pieces into ONE formatted string (the reference "
                    "checks.py comma bug, fixed in PR 1: a wrapped long message "
                    "left a stray comma between two string literals)"
                ),
            ))
    return findings


def _rule_wallclock(tree: ast.Module, filename: str) -> List[Finding]:
    findings = []
    for fn, _traced in _jit_target_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            if any(
                d == p or (p.endswith(".") and d.startswith(p)) for p in _WALLCLOCK_PREFIXES
            ):
                findings.append(Finding(
                    rule="wallclock-in-jit", severity="error",
                    where=f"{filename}:{node.lineno}",
                    message=(
                        f"host call {d}() inside jitted function {fn.name!r} — the value "
                        "freezes at trace time and replays in every execution"
                    ),
                    hint=(
                        "pass times/randomness in as arguments (or jax.random with an "
                        "explicit key); host clocks and numpy RNG are trace-time "
                        "constants inside a compiled program"
                    ),
                ))
    return findings


_SOURCE_RULES = (
    _rule_traced_branch,
    _rule_closure_identity,
    _rule_lock_discipline,
    _rule_raise_tuple,
    _rule_wallclock,
)


# ---------------------------------------------------------------- the drivers


def check_source_text(
    source: str, filename: str = "<string>", rules: Optional[Iterable[Any]] = None
) -> List[Finding]:
    """Lint one file's text. Suppression directives are honored here, so every
    caller (CLI, tests, sweeps) sees identical behavior; a directive missing
    its reason surfaces as ``suppression-missing-reason``."""
    tree = ast.parse(source, filename=filename)
    findings: List[Finding] = []
    for rule in rules or _SOURCE_RULES:
        findings.extend(rule(tree, filename))
    return filter_suppressed(findings, {filename: parse_suppressions(source)})


def check_source_tree(root: str, package_rel: bool = True) -> Report:
    """Lint every ``*.py`` under ``root`` (skipping caches); findings carry
    repo-relative paths so baselining survives checkouts in different dirs."""
    report = Report()
    root = os.path.abspath(root)
    rel_base = os.path.dirname(root) if package_rel else root
    n_files = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d != "__pycache__"]
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, rel_base).replace(os.sep, "/")
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            try:
                report.extend(check_source_text(source, filename=rel))
            except SyntaxError as e:
                report.note(f"{rel}: unparseable ({e})")
            n_files += 1
    report.note(f"source plane: {n_files} files linted under {os.path.basename(root)}/")
    return report
