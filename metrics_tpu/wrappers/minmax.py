"""MinMaxMetric wrapper: track running min/max of a base metric's compute.

Parity: reference ``torchmetrics/wrappers/minmax.py:23``.
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


class MinMaxMetric(Metric):
    """Wraps a metric and additionally reports the min and max value seen so far.

    The extremes track the RUNNING accumulated value after every update — the
    contract pinned by the reference's ``tests/wrappers/test_minmax.py:28-36``
    (compare_fn evaluates the base metric on each growing prefix). Reading
    accumulated state inside ``update`` makes this a ``full_state_update``
    metric: forward keeps the snapshot path instead of delta-merging (a
    batch-local delta would fold per-batch values, not prefix values).

    ``fold_on_compute=True`` selects the reference's LITERAL ``update()`` path
    instead (``wrappers/minmax.py:70-88``): extremes fold only when ``compute``
    runs, so ``update x N; compute`` yields ``min == max == raw`` exactly as the
    reference does outside its forward-per-step usage. Default False (prefix
    semantics — what the reference's own test contract exercises).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MinMaxMetric
        >>> minmax = MinMaxMetric(Accuracy())
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> _ = minmax(jnp.asarray([0, 1, 0, 0]), target)  # running acc 0.75
        >>> _ = minmax(jnp.asarray([1, 1, 0, 0]), target)  # running acc 0.875
        >>> {k: f"{float(v):.4f}" for k, v in minmax.compute().items()}
        {'raw': '0.8750', 'max': '0.8750', 'min': '0.7500'}
    """

    full_state_update = True

    def __init__(self, base_metric: Metric, fold_on_compute: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.fold_on_compute = bool(fold_on_compute)
        # registered states (not plain attrs): the pure update/compute API
        # snapshots+restores registered state only, and min/max ARE the right
        # cross-device reductions for these
        self.add_state("min_val", jnp.asarray(jnp.inf), dist_reduce_fx="min")
        self.add_state("max_val", jnp.asarray(-jnp.inf), dist_reduce_fx="max")

    def _fold_extremes(self, val: Array) -> None:
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a float or scalar tensor, but got {val}.")
        self.max_val = jnp.where(self.max_val < val, val, self.max_val)
        self.min_val = jnp.where(self.min_val > val, val, self.min_val)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)
        if not self.fold_on_compute:
            self._fold_extremes(self._base_metric._inner_compute())

    def compute(self) -> Dict[str, Array]:
        # the WRAPPED compute: under eager multihost it merges the child across
        # processes — the merged value folds into the extremes too (reference
        # minmax.py:103-104), while update() folds local running values
        val = self._base_metric.compute()
        self._fold_extremes(val)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    def reset(self) -> None:
        super().reset()
        self._base_metric.reset()

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, jax.Array):
            return val.size == 1
        return False
