"""BootStrapper wrapper: bootstrapped confidence estimates for any metric.

Parity: reference ``torchmetrics/wrappers/bootstrapping.py:49`` (_bootstrap_sampler
:25, per-update resampling :138-155, compute mean/std/quantile/raw :157).

TPU-native difference: ``multinomial`` resampling draws its indices with the jax
PRNG from a key derived from a REGISTERED draw counter — static shapes + pure
functions, so a multinomial BootStrapper works inside jit/shard_map (each device
decorrelates by folding in its mesh position). ``poisson`` keeps the reference's
repeat-interleave semantics, whose output length is data-dependent — host-side
and eager-only, exactly like upstream.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.parallel.collectives import in_mapped_context
from metrics_tpu.parallel.mesh import current_metric_axis
from metrics_tpu.utils.data import ARRAY_TYPES, apply_to_collection

Array = jax.Array


def _bootstrap_sampler(
    size: int,
    sampling_strategy: str = "poisson",
    rng: Optional[np.random.RandomState] = None,
) -> Array:
    """Host resampling indices for one poisson bootstrap draw. Parity: reference
    ``:25-46``. Only the poisson strategy routes here — multinomial draws with
    the jax PRNG inside ``BootStrapper.update`` so it stays trace-safe."""
    rng = rng or np.random
    if sampling_strategy == "poisson":
        n = rng.poisson(1, size)
        return jnp.asarray(np.repeat(np.arange(size), n))
    raise ValueError("Unknown sampling strategy")


class BootStrapper(Metric):
    """Computes bootstrapped mean/std/quantile of a base metric.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BootStrapper, MeanSquaredError
        >>> boot = BootStrapper(MeanSquaredError(), num_bootstraps=4,
        ...                     sampling_strategy="multinomial", seed=0)
        >>> _ = boot(jnp.asarray([1.0, 2.0, 3.0, 4.0]), jnp.asarray([1.1, 2.1, 2.9, 4.2]))
        >>> sorted(boot.compute().keys())
        ['mean', 'std']
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Array]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.RandomState(seed)
        # seed=None draws OS entropy (matching RandomState(None)); a fixed
        # default would make unseeded runs identical replays
        self._base_key = jax.random.PRNGKey(
            np.random.RandomState().randint(0, 2**31) if seed is None else seed
        )
        # registered counter: advances the PRNG stream across explicit
        # functional updates (state carried by the caller), travels with the
        # state pytree (trace-safe; psum on sync is harmless bookkeeping)
        self.add_state("draw_count", jnp.asarray(0, dtype=jnp.uint32), dist_reduce_fx="sum")

    def _forward_jit_safe(self) -> bool:
        # poisson resamples with the host numpy RNG per update; a compiled
        # forward would bake ONE draw into the executable and replay it every
        # batch (and its repeat-interleave output length is data-dependent)
        return self.sampling_strategy != "poisson" and super()._forward_jit_safe()

    def _batch_size(self, args, kwargs) -> int:
        args_sizes = apply_to_collection(args, ARRAY_TYPES, lambda x: x.shape[0])
        kwargs_sizes = apply_to_collection(kwargs, ARRAY_TYPES, lambda x: x.shape[0])
        if len(args_sizes) > 0:
            return args_sizes[0]
        if len(kwargs_sizes) > 0:
            return next(iter(kwargs_sizes.values()))
        raise ValueError("None of the input contained tensors, so could not determine the sampling size")

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch per bootstrap replica and update it. Parity: ``:138-155``."""
        size = self._batch_size(args, kwargs)
        if self.sampling_strategy == "multinomial":
            # jax-PRNG path: static shapes, works under jit/shard_map.
            # The key folds in (a) the registered draw counter — advances when
            # the caller carries state functionally — and (b) a hash of the
            # batch content, which decorrelates consecutive batches on paths
            # that rebuild a fresh delta state per step (Metric.forward);
            # identical (batch, counter) pairs resample identically — the
            # deterministic-by-content semantics of a functional framework.
            key = jax.random.fold_in(self._base_key, self.draw_count)
            first = args[0] if args else next(iter(kwargs.values()))
            batch_hash = jax.lax.bitcast_convert_type(
                jnp.sum(jnp.asarray(first)).astype(jnp.float32), jnp.int32
            )
            key = jax.random.fold_in(key, batch_hash)
            axis = current_metric_axis()
            if axis is not None and in_mapped_context(axis):
                key = jax.random.fold_in(key, jax.lax.axis_index(axis))
            self.draw_count = self.draw_count + 1
            for idx in range(self.num_bootstraps):
                sample_idx = jax.random.randint(jax.random.fold_in(key, idx), (size,), 0, size)
                new_args = apply_to_collection(args, ARRAY_TYPES, jnp.take, sample_idx, axis=0)
                new_kwargs = apply_to_collection(kwargs, ARRAY_TYPES, jnp.take, sample_idx, axis=0)
                self.metrics[idx].update(*new_args, **new_kwargs)
            return
        for idx in range(self.num_bootstraps):
            sample_idx = _bootstrap_sampler(size, self.sampling_strategy, self._rng)
            if sample_idx.size == 0:
                continue
            new_args = apply_to_collection(args, ARRAY_TYPES, jnp.take, sample_idx, axis=0)
            new_kwargs = apply_to_collection(kwargs, ARRAY_TYPES, jnp.take, sample_idx, axis=0)
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """Mean/std/quantile/raw over the bootstrap dim. Parity: ``:157-176``."""
        computed_vals = jnp.stack([m.compute() for m in self.metrics], axis=0)
        output_dict = {}
        if self.mean:
            output_dict["mean"] = jnp.mean(computed_vals, axis=0)
        if self.std:
            output_dict["std"] = jnp.std(computed_vals, axis=0, ddof=1)
        if self.quantile is not None:
            output_dict["quantile"] = jnp.quantile(computed_vals, self.quantile, axis=0)
        if self.raw:
            output_dict["raw"] = computed_vals
        return output_dict

    def reset(self) -> None:
        for m in self.metrics:
            m.reset()
        super().reset()
