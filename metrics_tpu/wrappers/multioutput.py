"""MultioutputWrapper: apply a metric independently along an output dimension.

Parity: reference ``torchmetrics/wrappers/multioutput.py:23`` (N internal clones
indexed along ``output_dim``, optional NaN-row removal :11,116).
"""
from copy import deepcopy
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import ARRAY_TYPES, apply_to_collection

Array = jax.Array


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where ANY of the tensors has a NaN. Parity: reference ``:11-21``."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel = tensors[0]
    nan_idxs = jnp.zeros(len(sentinel), dtype=bool)
    for tensor in tensors:
        permuted = tensor.reshape(len(sentinel), -1)
        nan_idxs = nan_idxs | jnp.any(jnp.isnan(permuted), axis=1)
    return nan_idxs


class MultioutputWrapper(Metric):
    """Evaluate ``base_metric`` separately on each slice along ``output_dim``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredError, MultioutputWrapper
        >>> mse2 = MultioutputWrapper(MeanSquaredError(), num_outputs=2)
        >>> preds = jnp.asarray([[1.0, 2.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[1.0, 2.5], [2.0, 2.5]])
        >>> _ = mse2(preds, target)
        >>> [f"{float(v):.4f}" for v in mse2.compute()]
        ['0.0000', '0.2500']
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice inputs per output index. NaN rows are dropped eagerly (data-dependent
        shape — eager-only, like the reference's boolean indexing)."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            # numpy arrays are first-class inputs everywhere else in the
            # package, so slice them here too (they would otherwise pass
            # through unsliced and fail at the squeeze below)
            selected_args = apply_to_collection(
                args, ARRAY_TYPES, jnp.take, jnp.asarray([i]), axis=self.output_dim
            )
            selected_kwargs = apply_to_collection(
                kwargs, ARRAY_TYPES, jnp.take, jnp.asarray([i]), axis=self.output_dim
            )
            if self.remove_nans:
                tensors = list(selected_args) + list(selected_kwargs.values())
                if tensors:
                    nan_idxs = _get_nan_indices(*tensors)
                    keep = ~nan_idxs
                    selected_args = [arg[keep] for arg in selected_args]
                    selected_kwargs = {k: v[keep] for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [jnp.squeeze(arg, axis=self.output_dim) for arg in selected_args]
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        return jnp.stack([m.compute() for m in self.metrics], 0)

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        results = []
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            results.append(metric(*selected_args, **selected_kwargs))
        self._mark_updated()  # per-output children updated through their own forwards
        if results[0] is None:
            return None
        return jnp.stack(results, 0)

    def reset(self) -> None:
        for metric in self.metrics:
            metric.reset()
        Metric.reset(self)
