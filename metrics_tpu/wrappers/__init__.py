from metrics_tpu.wrappers.bootstrapping import BootStrapper
from metrics_tpu.wrappers.minmax import MinMaxMetric
from metrics_tpu.wrappers.multioutput import MultioutputWrapper
from metrics_tpu.wrappers.tracker import MetricTracker
