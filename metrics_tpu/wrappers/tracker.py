"""MetricTracker wrapper: track a metric (or collection) over multiple epochs.

Parity: reference ``torchmetrics/wrappers/tracker.py:23`` (increment :76 snapshots a
new clone, best_metric :110).
"""
from copy import deepcopy
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric

Array = jax.Array


class MetricTracker:
    """A list of metric snapshots, one per ``increment()`` call.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricTracker
        >>> tracker = MetricTracker(Accuracy())
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> for epoch_preds in [jnp.asarray([0, 1, 0, 0]), jnp.asarray([1, 1, 0, 0])]:
        ...     tracker.increment()
        ...     _ = tracker(epoch_preds, target)
        >>> best, step = tracker.best_metric(return_step=True)
        >>> print(f"{float(best):.4f} at step {int(step)}")
        1.0000 at step 1
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a metrics_tpu"
                f" `Metric` or `MetricCollection` but got {metric}"
            )
        self._base_metric = metric
        self._metrics: List[Union[Metric, MetricCollection]] = []
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list) and isinstance(metric, MetricCollection) and len(maximize) != len(metric):
            raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        self.maximize = maximize
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of tracked metrics (increments so far)."""
        return len(self._metrics)

    def increment(self) -> None:
        """Create a new (clean) instance of the metric to track."""
        self._increment_called = True
        self._metrics.append(deepcopy(self._base_metric))
        self._metrics[-1].reset()

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self._metrics[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Union[Array, Dict[str, Array]]:
        """Compute all tracked metrics, stacked over steps."""
        self._check_for_increment("compute_all")
        res = [metric.compute() for metric in self._metrics]
        if isinstance(self._base_metric, MetricCollection):
            keys = res[0].keys()
            return {k: jnp.stack([r[k] for r in res], axis=0) for k in keys}
        return jnp.stack(res, axis=0)

    def reset(self) -> None:
        """Reset the current metric being tracked."""
        self._metrics[-1].reset()

    def reset_all(self) -> None:
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[float, Tuple[int, float], Dict[str, float], Tuple[Dict[str, int], Dict[str, float]]]:
        """Best value seen (and optionally which step it was). Parity: ``:110-140``."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            value, idx = {}, {}
            for i, (k, v) in enumerate(res.items()):
                fn = jnp.argmax if maximize[i] else jnp.argmin
                out = fn(v, axis=0)
                value[k] = float(v[out])
                idx[k] = int(out)
            if return_step:
                return idx, value
            return value
        fn = jnp.argmax if self.maximize else jnp.argmin
        idx = int(fn(res, axis=0))
        if return_step:
            return idx, float(res[idx])
        return float(res[idx])

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")
