"""Stream-capacity bench: ``python -m metrics_tpu.engine.stream_bench``.

The pinned protocol behind ``BENCH.stream_capacity`` (ISSUE 9), run by
``bench.py`` in a subprocess with an 8-device virtual CPU mesh. One run
produces every ratio, so no number is stitched across environments:

* S = 10^4 Zipfian streams served by a stream-sharded MultiStreamEngine at
  ``resident=16`` slots per shard — device state is the WORKING SET
  (world x resident x n rows), not S;
* streams-served-per-chip (S / world) and p50/p99 ``result()`` latency under
  the Zipfian law (value-in-hand, 200 sampled streams);
* the same-S UNSHARDED deferred-mesh engine is constructed alongside and its
  carried buffers measured: every shard holds all S stream rows, i.e. world x
  the global bytes and S/resident x the sharded engine's per-shard bytes —
  the replication the stream shard deletes;
* zero steady compiles after warmup (the routed program set is closed).

Absolute rates on the virtual CPU mesh are host-noise-bound → the entry
carries ``liveness_only``; the durable facts are the byte ratios, the shape
assertions, and the compile/dispatch counts (docs/benchmarking.md, "the four
hazards"). The CPU-scaled S=10^4 stands in for the ROADMAP's 10^5-10^6
target — capacity scales with host RAM through the pager, not with S-shaped
device buffers, which is exactly what the byte assertion pins.
"""
import json
import sys
import time

NUM_DEVICES = 8
S = 10_000
# 16 slots/shard = 128 resident streams total: the 320-batch Zipf stream
# touches ~190 distinct streams, so the LRU MUST spill — the bench proves
# paging bounds resident bytes, not just that sharding divides them
RESIDENT = 16
BUCKETS = (64, 256)
N_BATCHES = 320
N_RESULT_SAMPLES = 200


def run() -> dict:
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import AotCache, EngineConfig, MultiStreamEngine
    from metrics_tpu.engine.stats import _percentile
    from metrics_tpu.engine.traffic import zipf_stream_ids, zipf_traffic

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        return {"error": f"need {NUM_DEVICES} devices, have {len(devs)}"}
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))

    def col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    traffic = zipf_traffic(S, N_BATCHES, alpha=1.05, seed=97)
    cache = AotCache()
    engine = MultiStreamEngine(
        col(), S,
        EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp", mesh_sync="deferred"),
        aot_cache=cache, stream_shard=True, resident_streams=RESIDENT,
    )
    sizes = engine._layout.buffer_sizes()
    rows = 0
    with engine:
        t0 = time.perf_counter()
        for sid, p, t in traffic:
            engine.submit(sid, p, t)
            rows += p.shape[0]
        engine.flush()
        ingest_s = time.perf_counter() - t0
        warm = cache.misses
        # steady repeat: same shapes, zero compiles (closed routed set)
        for sid, p, t in traffic[:40]:
            engine.submit(sid, p, t)
        engine.flush()
        steady_compiles = cache.misses - warm
        # p50/p99 result() under the Zipf law, value-in-hand
        sample = zipf_stream_ids(S, N_RESULT_SAMPLES, alpha=1.05, seed=131)
        lat = []
        for sid in sample:
            t1 = time.perf_counter()
            jax.block_until_ready(
                jax.tree_util.tree_leaves(engine.result(int(sid)))
            )
            lat.append((time.perf_counter() - t1) * 1e6)
        lat.sort()

    shapes = {k: tuple(v.shape) for k, v in engine._state.items()}
    assert shapes == {
        k: (NUM_DEVICES, RESIDENT, n) for k, n in sizes.items()
    }, f"per-shard resident state is not (world, resident, n): {shapes}"
    sharded_bytes = sum(
        NUM_DEVICES * RESIDENT * n * np.dtype(k).itemsize for k, n in sizes.items()
    )

    # the unsharded deferred-mesh engine at the SAME S: every shard carries
    # ALL S stream rows — measured from its real carried buffers
    unsharded = MultiStreamEngine(
        col(), S,
        EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp", mesh_sync="deferred"),
        aot_cache=cache,
    )
    unsharded_bytes = sum(
        int(np.prod(v.shape)) * np.dtype(str(v.dtype)).itemsize
        for v in unsharded._state.values()
    )
    assert unsharded_bytes >= NUM_DEVICES * sum(
        S * n * np.dtype(k).itemsize for k, n in sizes.items()
    ), "unsharded engine does not replicate the full S-stream state per shard"

    st = engine.stats
    return {
        "value": round(S / NUM_DEVICES, 1),
        "unit": f"streams/chip (S={S}, {NUM_DEVICES}-dev virtual mesh, resident={RESIDENT}/shard)",
        "p50_result_us": round(_percentile(lat, 0.5), 1),
        "p99_result_us": round(_percentile(lat, 0.99), 1),
        "ingest_rows_per_s": round(rows / ingest_s, 1),
        "streams": S,
        "world": NUM_DEVICES,
        "resident_rows_per_shard": RESIDENT,
        "device_state_bytes_sharded_paged": int(sharded_bytes),
        "device_state_bytes_unsharded": int(unsharded_bytes),
        "bytes_ratio_unsharded_over_sharded": round(unsharded_bytes / sharded_bytes, 1),
        "steady_compiles_after_warmup": int(steady_compiles),
        "paging": {
            "page_hits": st.page_hits,
            "page_faults": st.page_faults,
            "page_ins": st.page_ins,
            "page_outs": st.page_outs,
            "resident_streams": st.resident_streams,
            "spilled_streams": st.spilled_streams,
        },
        "routed_steps": st.routed_steps,
        "protocol": (
            f"{N_BATCHES} Zipf(alpha=1.05, seed=97) batches over S={S} streams, "
            f"stream_shard resident={RESIDENT}; p50/p99 over {N_RESULT_SAMPLES} "
            "Zipf-sampled result() calls value-in-hand; unsharded deferred engine "
            "constructed at the same S for the byte comparison; ratios-in-one-run"
        ),
        "liveness_only": True,
        "note": (
            "virtual CPU mesh timeshares one host: absolute rates are topology "
            "liveness; the durable facts are the byte ratio, the (world, resident, n) "
            "shape assertion, and steady_compiles_after_warmup == 0"
        ),
    }


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    print(json.dumps(run()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
