"""Multi-stream serving: S independent evaluation streams, ONE executable.

The ROADMAP's serving regime is many concurrent evaluation streams (one per
user/session/model-variant), each a separate accumulation with its own
result. One :class:`~metrics_tpu.engine.pipeline.StreamingEngine` per stream
multiplies everything that makes small-batch serving dispatch-bound: S AOT
program sets, S dispatcher threads, S donated state transfers per scheduling
quantum. ``MultiStreamEngine`` collapses all of it:

* every member state leaf gains a leading **stream axis** of length
  ``num_streams`` — with arenas on (default), the whole S-stream state is
  still just one buffer per dtype;
* a step takes ``(state, (stream_ids,)+batch, mask)``: the vmapped per-row
  deltas reduce into the addressed stream rows with each reduction's own op
  (``Metric.update_state_segmented``, dispatched through
  ``metrics_tpu/ops/kernels`` — a scatter-free Pallas compare-reduce on TPU,
  ``.at[ids].add/min/max`` on an identity-filled base under the XLA reference
  path), so ONE dispatch can carry rows for MANY streams at once;
* megabatch coalescing composes for free: queued batches from DIFFERENT
  streams concatenate into one step (their rows address different state
  rows), which is exactly the cross-stream amortization a per-stream engine
  can never do;
* ``result(stream_id)`` runs one shared compiled compute program whose
  stream index is a runtime argument — S streams, one compute executable;
* ``results()`` runs ONE batched (vmapped) all-streams compute program —
  a single device computation for any S, never S dispatches;
* snapshots carry all streams in one (per-dtype) payload; restore brings
  every stream back at once.

The compiled-program budget is UNCHANGED in S: at most ``len(buckets)``
update programs + 1 per-stream compute + 1 batched all-streams compute, for
any stream count.

**Stream sharding + paging (ISSUE 9).** ``stream_shard=True`` (mesh under
deferred sync required) shards the STREAM AXIS itself over the mesh: shard
``w`` of W owns the streams with ``stream_id % W == w``, and the carried
state is one ``(W, resident, n)`` paged-arena buffer per dtype, dim-0
sharded — per-shard resident state is ``resident`` rows, NOT S. The
dispatcher routes each megabatch host-side (rows ordered by home shard,
per-shard segments padded to ``bucket/W``), so the steady routed step is
COLLECTIVE-FREE at jaxpr and HLO level, exactly like PR 5's deferred mode
(``parallel/embedded.py::stream_sharded_step``; pinned by the
``no-collectives-in-deferred-step`` rule over the bootstrap matrix). On top,
``resident_streams=R`` bounds per-shard HBM by the ACTIVE WORKING SET: an
LRU pager (``engine/paging.py``) spills cold streams' arena rows to host RAM
through the snapshot codec and faults them back on the next submit —
capacity scales past HBM, and a Zipfian tenant population costs one resident
working set. ``result(sid)`` moves only the read stream's row (one shard's
slot, or the host-spilled copy — never the whole state); kill/resume covers
resident AND spilled rows with exact replay, and snapshot meta carries the
full stream-shard topology for the restore matrix
({sharded+paged → same-world verbatim, → single-device merged}).

Scope: single-device serving, or a mesh under DEFERRED sync
(``EngineConfig(mesh=..., mesh_sync="deferred")``): without ``stream_shard``
each shard carries its own (S, ...)-stacked local states and ``result()``
rides one boundary merge; with it each shard carries only its own streams.
The step-sync mesh form does not exist — the per-step segmented scatter has
no exact shard-and-merge. Metrics must support the generic delta masked path
(``segmented_update_unsupported_reason`` is None): custom fused masked forms
and scan-fallback members have no segmented counterpart.

Quickstart::

    from metrics_tpu import Accuracy
    from metrics_tpu.engine import EngineConfig, MultiStreamEngine

    engine = MultiStreamEngine(Accuracy(), num_streams=64,
                               config=EngineConfig(buckets=(64, 256)))
    with engine:
        engine.submit(stream_id, preds, target)   # any stream, any order
        ...
        acc_7 = engine.result(7)                  # per-stream compute
"""
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.aot import AotCache
from metrics_tpu.engine.arena import ArenaLayout
from metrics_tpu.engine.faults import InjectedFault
from metrics_tpu.engine.paging import StreamPager
from metrics_tpu.engine.pipeline import EngineConfig, StreamingEngine
from metrics_tpu.engine.trace import ENGINE_TRACE
from metrics_tpu.ops.kernels import MEGASTEP_BACKENDS
from metrics_tpu.utils.data import is_batch_leaf
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["MultiStreamEngine"]


class MultiStreamEngine(StreamingEngine):
    """Serve ``num_streams`` independent accumulations of one metric from a
    single AOT program set and a single dispatcher.

    Args:
        metric: the served metric/collection (segmented update path required).
        num_streams: S — independent accumulations.
        config: engine config; ``stream_shard`` requires ``mesh`` +
            ``mesh_sync="deferred"`` + ``use_arena=True``.
        aot_cache: optional shared AOT cache.
        stream_shard: shard the stream axis over the mesh — shard ``w`` owns
            streams with ``stream_id % world == w``; per-shard resident state
            is ``resident_streams`` rows instead of S.
        resident_streams: per-shard paged-arena slot count (defaults to
            ``ceil(S / world)`` — everything resident, paging never fires).
            Smaller values bound HBM by the working set; cold streams spill
            to host RAM.
    """

    def __init__(
        self,
        metric: Any,
        num_streams: int,
        config: Optional[EngineConfig] = None,
        aot_cache: Optional[AotCache] = None,
        stream_shard: bool = False,
        resident_streams: Optional[int] = None,
    ):
        if not isinstance(num_streams, int) or num_streams <= 0:
            raise MetricsTPUUserError(f"num_streams must be a positive int, got {num_streams!r}")
        if config is not None and config.mesh is not None and config.mesh_sync != "deferred":
            raise MetricsTPUUserError(
                "MultiStreamEngine has no step-sync mesh form: the segmented scatter "
                "has no exact per-step shard-and-merge; serve the mesh with "
                "EngineConfig(mesh_sync='deferred') (shard-local stream states, "
                "boundary merge) or use one StreamingEngine per mesh"
            )
        self._num_streams = int(num_streams)
        self._stream_shard = bool(stream_shard)
        self._pager: Optional[StreamPager] = None
        if self._stream_shard:
            if config is None or config.mesh is None or config.mesh_sync != "deferred":
                raise MetricsTPUUserError(
                    "stream_shard=True needs EngineConfig(mesh=..., mesh_sync='deferred'): "
                    "the stream axis shards over the mesh and the routed step follows "
                    "the deferred (collective-free) contract"
                )
            if not config.use_arena:
                raise MetricsTPUUserError(
                    "stream_shard=True requires use_arena=True: the paged per-stream "
                    "arena rows are the unit the pager spills and faults"
                )
            axes = (config.axis,) if isinstance(config.axis, str) else tuple(config.axis)
            world = int(np.prod([config.mesh.shape[a] for a in axes]))
            # windowed stream sharding (ISSUE 13): the pane EXTENDS the local
            # stream coordinate (eloc = loc * panes + pane) — each (stream,
            # pane) pair is its own pager row, so cold panes spill through
            # the existing compressed pager and rotation is pure bookkeeping
            win = config.window
            if win is not None and win.kind == "ewma":
                raise MetricsTPUUserError(
                    "ewma windows are not supported under stream_shard=True: the "
                    "decay would have to scale resident arena rows AND every "
                    "host-spilled row in place — serve ewma on an unsharded "
                    "engine, or use a tumbling/sliding ring"
                )
            self._pane_rows = win.panes if (win is not None and win.stacked) else 1
            self._local_streams = -(-self._num_streams // world) * self._pane_rows
            r = int(resident_streams) if resident_streams is not None else self._local_streams
            if r <= 0:
                raise MetricsTPUUserError(
                    f"resident_streams must be positive, got {resident_streams!r}"
                )
            self._resident = min(r, self._local_streams)
        else:
            if resident_streams is not None:
                raise MetricsTPUUserError(
                    "resident_streams only applies to stream_shard=True engines "
                    "(the unsharded engine carries every stream resident)"
                )
            self._resident = 0
            self._pane_rows = 1
        super().__init__(metric, config=config, aot_cache=aot_cache)
        self._row_codec = None
        if self._stream_shard:
            self._pager = StreamPager(self._world, self._resident)
            self._stats.mesh_sync = "stream_shard"
            # one stream's packed init row per dtype, host numpy — the
            # fault-in source for never-touched (and reset) streams
            row = self._layout.pack(jax.tree.map(jnp.asarray, self._metric.init_state()))
            self._init_row = {k: np.asarray(v) for k, v in row.items()}
            # the per-row at-rest codec (ISSUE 10). Built whenever the
            # metric's policy quantizes ANYTHING — decode capability must
            # exist even with compress_payloads off, so a compressed
            # snapshot restores into an uncompressed same-policy engine —
            # while ENCODING (spill rows, snapshot arenas) is gated on the
            # config flag.
            from metrics_tpu.engine.quantize import ArenaRowCodec

            self._row_codec = ArenaRowCodec.for_metric(self._metric)
        # q8-RESIDENT cold rows (ISSUE 16): under the megastep backends a
        # compressing stream-sharded engine seats faulted-in spilled rows
        # WITHOUT the host dequant for the segment-eligible dtypes — their
        # quantized columns stay ZERO in the arena while the int8 codes +
        # per-element f32 scales ride the next routed payload as replicated
        # leaves, and the segment grid decodes them on touch (bit-identical
        # arithmetic: int8→f32, one f32 multiply, one cast). Staged state is
        # host numpy and lives for exactly one round: the step consumes it
        # (every flagged slot decodes at the grid's seed) or a failed step
        # flushes it back through the host decode, so chaos replays stay
        # bit-identical to fault-free runs.
        self._q8_enabled = (
            self._stream_shard
            and self._compress
            and self._row_codec is not None
            and self._megastep_plan is not None
            and self._kernel_tag() in MEGASTEP_BACKENDS
        )
        self._q8_keys: Tuple[str, ...] = ()
        self._q8_stage: Dict[str, Any] = {}
        self._q8_reset_stage()

    # -------------------------------------------------------------- capability checks

    def _update_path_unsupported_reason(self, metric: Any) -> Optional[str]:
        # only the UPDATE capability is multi-stream-specific; the base check
        # keeps running the mesh-mode gates (notably the deferred-sync stacked
        # merge requirement) on top of this — a metric that folds fine but
        # cannot merge must refuse at construction, not at the first result()
        return metric.segmented_update_unsupported_reason()

    def _megastep_unsupported_reason(self) -> Optional[str]:
        if self._layout is None:
            return "no_arena"
        if not self._stream_shard:
            # the unsharded engine's (S, ...)-stacked arena packs the stream
            # axis INSIDE each leaf's columns — no per-column opcode row
            # describes that buffer. The stream-sharded form is the megastep
            # target: its carried buffers are (world, resident, n)
            # slot-stacked rows, exactly the segment grid's shape (the mesh
            # is fine there — the routed step is collective-free and the
            # grid runs per shard).
            return "stacked_layout"
        return None

    def _megastep_fallback_reasons(self) -> Dict[str, str]:
        # the SEGMENT form's tighter bound: the whole (resident, n)
        # slot-stacked buffer must fit a VMEM block, not just one row
        if self._megastep_plan is None:
            return {}
        return self._megastep_plan.segment_fallback_reasons(self._resident)

    # ----------------------------------------------------------------- state plumbing

    @property
    def num_streams(self) -> int:
        return self._num_streams

    @property
    def stream_shard(self) -> bool:
        return self._stream_shard

    @property
    def resident_streams(self) -> Optional[int]:
        """Per-shard paged-arena slot count (None for unsharded engines)."""
        return self._resident if self._stream_shard else None

    def _kind_init_state_tree(self) -> Any:
        if self._stream_shard:
            # ONE (stream, pane) row's logical state: the stream-sharded
            # carried form is built row-wise by _put_state, never as a full
            # (S, ...) tree — under windows the pane extends the pager's
            # local stream coordinate, so the row shape is unchanged
            return self._metric.init_state()
        base = self._metric.init_state()
        return jax.tree.map(
            lambda x: jnp.tile(jnp.asarray(x)[None], (self._num_streams,) + (1,) * jnp.ndim(x)),
            base,
        )

    def _kind_abstract_state_tree(self) -> Any:
        if self._stream_shard:
            # per-(stream, pane) template: the engine's ArenaLayout then
            # describes one row (n elements per dtype) — the pager's spill unit
            return self._metric.abstract_state()
        base = self._metric.abstract_state()
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((self._num_streams,) + tuple(s.shape), s.dtype),
            base,
        )

    def _put_state(self, state: Any, packed: bool = False, stacked: bool = False) -> Any:
        if not self._stream_shard:
            return super()._put_state(state, packed=packed, stacked=stacked)
        sh = self._shard_sharding()
        if stacked:
            # already the (W, resident, n) per-dtype paged-arena buffers
            return {k: jax.device_put(jnp.asarray(v), sh) for k, v in state.items()}
        # logical single-stream tree -> fresh arena: every slot = the init row
        row = self._layout.pack(jax.tree.map(jnp.asarray, state))
        return {
            k: jax.device_put(
                jnp.tile(jnp.reshape(v, (1, 1, -1)), (self._world, self._resident, 1)), sh
            )
            for k, v in row.items()
        }

    def _abstract_state(self) -> Any:
        if not self._stream_shard:
            return super()._abstract_state()
        sh = self._shard_sharding()
        return {
            k: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)
            for k, s in self._layout.abstract_stream_stacked(self._world, self._resident).items()
        }

    # ------------------------------------------------------------------ AOT programs

    def _update_kind(self) -> str:
        return "update_sstream" if self._stream_shard else "update_mstream"

    def _sync_tag(self) -> str:
        # stream-sharded programs lower over a DIFFERENT carried form than
        # plain deferred ones; a distinct tag keeps a shared AotCache honest
        return "stream_shard" if self._stream_shard else super()._sync_tag()

    def _payload_leaf_info(self) -> Optional[Any]:
        # the unsharded multistream merge syncs the (S, ...)-STACKED state:
        # every leaf the bundle moves carries a leading stream axis, so the
        # payload accounting scales by S (same correction the analysis
        # plane's EngineAnalysis._sync_leaf_info applies). Stream-sharded
        # engines route host-side and never record a sync payload.
        info = super()._payload_leaf_info()
        if info is None or self._stream_shard:
            return info
        return [
            (fx, jax.ShapeDtypeStruct((self._num_streams,) + tuple(leaf.shape), leaf.dtype), prec)
            for fx, leaf, prec in info
        ]

    def _fleet_leaf_info(self) -> Optional[Any]:
        # the fleet fold moves this host's LOGICAL state — the host-side
        # reassembled ``(S, ...)`` tree (``(panes, S, ...)`` under ring
        # windows) for stream-sharded engines, so the FLEET accounting
        # S-scales even though the per-mesh accounting stays unscaled (the
        # routed steady step never puts the stacked state on the wire);
        # unsharded engines inherit the (panes x) S-scaled base form
        if not self._stream_shard:
            return super()._fleet_leaf_info()
        info = StreamingEngine._payload_leaf_info(self)
        if not info:
            return info
        lead = (
            (self._num_streams,)
            if self._pane_rows == 1
            else (self._pane_rows, self._num_streams)
        )
        return [
            (fx, jax.ShapeDtypeStruct(lead + tuple(leaf.shape), leaf.dtype), prec)
            for fx, leaf, prec in info
        ]

    def _traced_update(self, state_tree: Any, payload: Any, mask: Any) -> Any:
        a, kw = payload
        ids, rest = a[0], a[1:]
        # sharded mode addresses pager SLOTS within the shard (num_segments =
        # resident); unsharded mode addresses global stream rows
        num = self._resident if self._stream_shard else self._num_streams
        return self._metric.update_state_segmented(
            state_tree, *rest, mask=mask,
            segment_ids=ids, num_segments=num, **kw,
        )

    def _step_callable(self, payload_abs: Any, mask_abs: Any):
        if not self._stream_shard:
            return super()._step_callable(payload_abs, mask_abs)
        from metrics_tpu.parallel.embedded import stream_sharded_step

        plan = self._megastep_plan
        mega = plan is not None and self._kernel_tag() in MEGASTEP_BACKENDS
        q8_keys = self._q8_keys
        if not (mega or q8_keys):
            return stream_sharded_step(
                self._traced_update, self._cfg.mesh, self._cfg.axis, payload_abs, mask_abs,
                state_template=self._abstract_state(),
                unpack=self._layout.unpack_stacked, pack=self._layout.pack_stacked,
            )
        # whole-step SEGMENT megakernel body (ISSUE 16): the carried
        # (world, resident, n) buffers are already the slot-stacked shape the
        # segment grid folds, so the body takes them RAW (unpack/pack None) —
        # one megastep_segment launch per eligible dtype, pager slot ids as
        # segment ids. Staged q8-resident slots ride the payload TAIL as
        # replicated (1, W, ...) leaves; each shard dynamically picks its own
        # plane, so staging changes arguments, never the trace. The PER-LEAF
        # body below also consumes the tail (substituting the staged decodes
        # with plain jnp ops first): a mid-step ``degrade_kernel`` demotion to
        # "xla" rebuilds on it with the SAME payload, losing nothing.
        from jax import lax

        resident = self._resident
        axis = self._cfg.axis
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        # static row-major axis strides — the linear shard index must match
        # the P(axis) dim-0 device order the router homes rows by
        axis_sizes = [int(self._cfg.mesh.shape[a]) for a in axes]
        q8_cols = (
            {k: self._row_codec._q_mask[k] for k in q8_keys} if q8_keys else None
        )

        def update_fn(bufs, payload, mask):
            a, kw = payload
            q8_stage = None
            if q8_keys:
                tail = 1 + 2 * len(q8_keys)
                a, staged = a[:-tail], a[-tail:]
                w = lax.axis_index(axes[0])
                for name, size in zip(axes[1:], axis_sizes[1:]):
                    w = w * size + lax.axis_index(name)

                def pick(x):
                    return lax.dynamic_index_in_dim(x[0], w, 0, keepdims=False)

                flags = pick(staged[0])
                q8_stage = {
                    k: (flags, pick(staged[1 + 2 * i]), pick(staged[2 + 2 * i]))
                    for i, k in enumerate(q8_keys)
                }
            if mega:
                ids, rest = a[0], a[1:]
                return plan.apply_segmented(
                    bufs, rest, kw, mask, ids, resident,
                    q8_stage=q8_stage, q8_cols=q8_cols,
                )
            # per-leaf (demoted) body: substitute the staged decodes FIRST —
            # the reference arithmetic, bit-identical to the grid's seed —
            # then the ordinary segmented update on the unpacked tree
            if q8_stage:
                bufs = dict(bufs)
                for k, (flags, codes, scales) in q8_stage.items():
                    qcol = jnp.reshape(
                        jnp.asarray(q8_cols[k], jnp.int32), (1, -1)
                    )
                    on = (
                        jnp.reshape(flags.astype(jnp.int32), (-1, 1)) != 0
                    ) & (qcol != 0)
                    dec = (codes.astype(jnp.float32) * scales).astype(bufs[k].dtype)
                    bufs[k] = jnp.where(on, dec, bufs[k])
            tree = self._layout.unpack_stacked(bufs)
            new_tree = self._traced_update(tree, (a, kw), mask)
            return self._layout.pack_stacked(new_tree)

        return stream_sharded_step(
            update_fn, self._cfg.mesh, axis, payload_abs, mask_abs,
            state_template=self._abstract_state(), unpack=None, pack=None,
        )

    def _compute_program(self):
        """One executable computes ANY stream: the stream index is a runtime
        scalar argument, so S streams never cost S compiles. Under deferred
        sync the input is the boundary-merged (S, ...)-stacked global state
        instead of the carried shard-local arena. Ring windows fold the pane
        axis FIRST (it stacks outside the stream axis), with the tumbling
        cursor as one more runtime scalar — window shape and policy stay in
        the program key, pane values never do."""
        sid_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = self._aot.program_key(
            f"compute_mstream+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=(self._compute_input_abstract(),) + self._compute_extra_abs() + (sid_abs,),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric = self._metric

        def build():
            def compute(state, *rest):
                extra, sid = rest[:-1], rest[-1]
                tree = self._window_fold_traced(self._compute_tree(state), *extra)
                row = jax.tree.map(lambda x: x[sid], tree)
                return metric.compute_from(row)

            with self._kernel_scope():
                return (
                    jax.jit(compute)
                    .lower(
                        self._compute_input_abstract(),
                        *self._compute_extra_abs(),
                        sid_abs,
                    )
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _pane_values_program(self):
        """EVERY stream's value of ONE runtime-indexed pane — the windowed
        multi-stream drift observable (one batched device computation per
        rotation, any S). For tumbling rings this is exactly the batched
        all-streams program (its fold IS the pane index)."""
        if self._window.kind == "tumbling":
            return self._results_program()
        pane_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = self._aot.program_key(
            f"pane_values+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=(self._compute_input_abstract(), pane_abs),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric = self._metric

        def build():
            from jax import lax

            def pane_values(state, pane):
                tree = self._compute_tree(state)
                row = jax.tree.map(
                    lambda x: lax.dynamic_index_in_dim(x, pane, 0, keepdims=False), tree
                )
                return jax.vmap(metric.compute_from)(row)

            with self._kernel_scope():
                return (
                    jax.jit(pane_values)
                    .lower(self._compute_input_abstract(), pane_abs)
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _drift_values_locked(self):
        """Per-stream closing-pane results (state lock held): one batched
        device computation, sliced host-side into ``(stream_id, value)``
        series for the detector."""
        state = self._merged_state() if self._deferred else self._state
        vals = jax.device_get(
            self._pane_values_program()(state, jnp.asarray(self._pane_cursor, jnp.int32))
        )
        return [
            (sid, jax.tree.map(lambda x: x[sid], vals))
            for sid in range(self._num_streams)
        ]

    def _row_compute_program(self):
        """Stream-sharded per-stream compute: ONE stream's packed arena row
        (per-dtype ``(n,)`` host vectors — the only bytes ``result(sid)``
        moves) -> the metric's value. Mesh-free: the row is already gathered."""
        row_abs = {
            k: jax.ShapeDtypeStruct((n,), jnp.dtype(k))
            for k, n in self._layout.buffer_sizes().items()
        }
        key = self._aot.program_key(
            f"compute_sstream+k.{self._kernel_tag()}", self._metric_fp,
            arg_tree=row_abs, mesh=None, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric, layout = self._metric, self._layout

        def build():
            with self._kernel_scope():
                return (
                    jax.jit(lambda row: metric.compute_from(layout.unpack(row)))
                    .lower(row_abs)
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _results_traced(self, state: Any, *extra: Any) -> Any:
        """Traced body of the batched all-streams compute: ONE vmapped
        ``compute_from`` over the stream axis (after the window's pane fold
        — the pane axis stacks outside the stream axis) — the jaxpr's op
        count is CONSTANT in S (pinned by the dispatch-count regression
        test), so a dashboard scrape at S=10^5 costs one device computation,
        not 10^5."""
        tree = self._window_fold_traced(self._compute_tree(state), *extra)
        return jax.vmap(self._metric.compute_from)(tree)

    def _results_program(self):
        key = self._aot.program_key(
            f"compute_mstream_all+k.{self._kernel_tag()}+w.{self._window_tag()}",
            self._metric_fp,
            arg_tree=(self._compute_input_abstract(),) + self._compute_extra_abs(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )

        def build():
            with self._kernel_scope():
                return (
                    jax.jit(self._results_traced)
                    .lower(self._compute_input_abstract(), *self._compute_extra_abs())
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _results_traced_sharded(self, stacked: Any) -> Any:
        """Stream-sharded batched compute: the host-reassembled ``(S, n)``
        row matrices -> every stream's value, one vmap."""
        return jax.vmap(self._metric.compute_from)(self._layout.unpack_stacked(stacked))

    def _results_program_sharded(self):
        stacked_abs = {
            k: jax.ShapeDtypeStruct((self._num_streams, n), jnp.dtype(k))
            for k, n in self._layout.buffer_sizes().items()
        }
        key = self._aot.program_key(
            f"compute_sstream_all+k.{self._kernel_tag()}", self._metric_fp,
            arg_tree=stacked_abs, mesh=None, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )

        def build():
            with self._kernel_scope():
                return jax.jit(self._results_traced_sharded).lower(stacked_abs).compile()

        return self._aot.get_or_compile(key, build)

    # --------------------------------------------------------------------- producers

    def _check_stream(self, stream_id: Any) -> int:
        sid = int(stream_id)
        if not 0 <= sid < self._num_streams:
            raise MetricsTPUUserError(
                f"stream_id {sid} out of range for num_streams={self._num_streams}"
            )
        return sid

    def submit(
        self, stream_id: int, *args: Any, timeout: Optional[float] = None, **kwargs: Any
    ) -> None:
        """Enqueue one (ragged) batch for ``stream_id``. Blocks when full;
        ``timeout`` bounds the wait exactly like the base engine's (sticky
        dispatcher error preferred over :class:`BackpressureTimeout`)."""
        sid = self._check_stream(stream_id)
        self._raise_if_failed()
        self.start()
        # the base helper traces the submit when a recorder is attached —
        # _item_context puts the stream_id on the span (every span this
        # batch's journey produces carries it through the group context)
        if self._admission is not None:
            # per-STREAM admission: the token bucket and priority class are
            # the stream's own — a shed class rejects here, typed, before
            # the batch can consume a cursor (refunded if the enqueue fails)
            self._admitted_submit(sid, (sid, args, kwargs), (args, kwargs), timeout)
        else:
            self._submit_item((sid, args, kwargs), timeout)

    # ---------------------------------------------------------- fault context

    def _screen_payload(self, item: Any) -> Any:
        # the screen policy must see exactly what the metric's update sees —
        # strip the engine-internal stream id
        return (item[1], item[2])

    def _item_context(self, item: Any) -> Dict[str, Any]:
        return {"stream_id": item[0]}

    def _group_context(self, group: List[Any]) -> Dict[str, Any]:
        # the sticky error names every stream whose traffic rode the failed
        # group — the poisoned input is in one of THOSE streams' logs
        sids = sorted({it[0] for it in group if isinstance(it, tuple) and len(it) == 3})
        return {"stream_ids": sids} if sids else {}

    # ------------------------------------------------------- stream-sharded routing

    def _home(self, sid: int) -> Tuple[int, int]:
        """Global stream id -> (home shard, local stream index)."""
        return sid % self._world, sid // self._world

    def _home_row(self, sid: int, pane: Optional[int] = None) -> Tuple[int, int]:
        """Global stream id (+ pane under a ring window) -> (home shard,
        pager row coordinate). The pane EXTENDS the local index
        (``loc * panes + pane``): each (stream, pane) pair owns its own
        pager row, which is exactly what lets cold panes spill through the
        existing LRU/codec machinery unchanged."""
        w, loc = self._home(sid)
        if self._pane_rows == 1:
            return w, loc
        return w, loc * self._pane_rows + (self._pane_cursor if pane is None else int(pane))

    def _route_locs(self, sids: np.ndarray) -> np.ndarray:
        """Vectorized home-row coordinates for the CURRENT pane (the routed
        step only ever touches the pane being written)."""
        locs = np.asarray(sids, np.int64) // self._world
        if self._pane_rows > 1:
            locs = locs * self._pane_rows + self._pane_cursor
        return locs

    def _refresh_gauges(self) -> None:
        if self._pager is not None:
            self._stats.resident_streams = self._pager.resident_count()
            self._stats.spilled_streams = self._pager.spilled_count()
            self._stats.spilled_bytes = self._pager.spill_nbytes()

    # -------------------------------------------------- stream-shard pane rotation

    def _plan_rotation(self, incoming: int) -> Any:
        """Stream-sharded rotation plan: a PURE enumeration of every pager
        row (resident or spilled) belonging to the INCOMING pane — those
        rows expire (tumbling: the pane restarts; sliding: the oldest pane
        falls out of the window) and their next touch faults in the init
        row. No device work: the ring lives in the pager's coordinate space,
        which is exactly what makes a stream-sharded rotation free."""
        if not self._stream_shard:
            return super()._plan_rotation(incoming)

        def plan() -> Any:
            self._fault("pane_rotate")
            P = self._pane_rows
            drops = []
            for w in range(self._world):
                for row in self._pager.resident_streams(w):
                    if row % P == incoming:
                        drops.append((w, row))
                for row in self._pager.spilled_streams(w):
                    if row % P == incoming:
                        drops.append((w, row))
            return sorted(set(drops))

        return self._retry_transient(plan)

    def _commit_rotation(self, planned: Any, incoming: int) -> None:
        if not self._stream_shard:
            return super()._commit_rotation(planned, incoming)
        for w, row in planned:
            self._pager.drop(w, row)
        self._state_version += 1
        self._refresh_gauges()

    # ------------------------------------------------------------ elastic reshard

    def _topology_state(self) -> Dict[str, Any]:
        t = super()._topology_state()
        if self._stream_shard:
            t.update(
                pager=self._pager,
                resident=self._resident,
                local_streams=self._local_streams,
            )
        return t

    def _apply_topology_state(self, t: Dict[str, Any]) -> None:
        super()._apply_topology_state(t)
        if self._stream_shard:
            self._pager = t["pager"]
            self._resident = t["resident"]
            self._local_streams = t["local_streams"]
            self._q8_reset_stage()

    def _apply_topology(
        self, mesh: Any, world: int, policy: Any, resident_streams: Optional[int] = None,
    ) -> None:
        super()._apply_topology(mesh, world, policy)
        if self._stream_shard:
            # the stream-shard factor IS the world: re-derive the per-shard
            # stream census and seat a FRESH pager — _restore_commit right
            # after this re-homes every row (verbatim same-topology, spill-
            # seeded otherwise)
            self._local_streams = -(-self._num_streams // world) * self._pane_rows
            r = int(resident_streams) if resident_streams is not None else self._resident
            self._resident = min(max(1, r), self._local_streams)
            self._pager = StreamPager(world, self._resident)
            self._q8_reset_stage()

    def _execute_payload(
        self, merged: Tuple[Tuple[Any, ...], Dict[str, Any]], n: int,
        n_coalesced: int, queue_wait_us: float,
    ) -> None:
        if not self._stream_shard:
            return super()._execute_payload(merged, n, n_coalesced, queue_wait_us)
        self._execute_routed(merged, int(n), n_coalesced, queue_wait_us)

    def _execute_routed(
        self, merged: Tuple[Tuple[Any, ...], Dict[str, Any]], n: int,
        n_coalesced: int, queue_wait_us: float,
    ) -> None:
        """Route one merged megabatch to the stream shards, host-side.

        Rows order by home shard (``sid % W``, stable — per-stream arrival
        order is preserved, which is all exactness needs), then run in ROUNDS:
        each round takes up to ``bucket/W`` rows per shard, capped so no shard
        touches more than ``resident`` distinct streams (the pager can always
        seat a round), pages the round's streams resident, and executes ONE
        padded collective-free step whose segment ids are the pager's slot
        indices. The chosen bucket is the smallest whose per-shard slice
        covers the round's largest segment — the program set stays closed.
        """
        t_route0 = time.perf_counter()
        W = self._world
        args, kwargs = merged
        sids = np.asarray(args[0], np.int32)
        rest = tuple(args[1:])
        home = sids % W
        order = np.argsort(home, kind="stable")
        leaves, treedef = jax.tree_util.tree_flatten((rest, kwargs))
        perm = [
            np.asarray(leaf)[order] if is_batch_leaf(leaf, n) else leaf for leaf in leaves
        ]
        sids_o = sids[order]
        home_o = home[order]
        # home-row coordinates for the WHOLE group, once: the pane cursor is
        # constant across this call (rotation happens between groups; the
        # shard-loss re-route recurses and recomputes), so every per-row /
        # per-segment consumer below indexes this one vector
        locs_o = self._route_locs(sids_o)
        starts = np.searchsorted(home_o, np.arange(W)).astype(np.int64)
        stops = np.searchsorted(home_o, np.arange(W), side="right").astype(np.int64)
        route_us = (time.perf_counter() - t_route0) * 1e6
        per_top = self._policy.buckets[-1] // W
        cursors = starts.copy()
        committed = 0
        rounds = 0
        tr = self._trace
        try:
            while bool(np.any(cursors < stops)):
                t0 = time.perf_counter()
                # ---- segment this round: <= per_top rows and <= resident
                # distinct streams per shard
                segs: List[Tuple[int, int]] = []
                max_len = 0
                for w in range(W):
                    s0, s1 = int(cursors[w]), int(stops[w])
                    end = s0
                    distinct: set = set()
                    while end < s1 and (end - s0) < per_top:
                        loc = int(locs_o[end])
                        if loc not in distinct and len(distinct) >= self._resident:
                            break
                        distinct.add(loc)
                        end += 1
                    segs.append((s0, end))
                    max_len = max(max_len, end - s0)
                bucket = self._policy.bucket_for(max_len * W)
                per = bucket // W
                # ---- page the round's streams resident (slot assignment)
                self._page_round(
                    {w: [int(x) for x in locs_o[segs[w][0]: segs[w][1]]] for w in range(W)}
                )
                # ---- build the padded routed payload: shard w's rows land in
                # slice [w*per, w*per+len(seg)) — P(axis) then hands each
                # device exactly its own streams' rows
                src = np.concatenate(
                    [np.arange(s0, s1, dtype=np.int64) for s0, s1 in segs]
                ) if segs else np.zeros((0,), np.int64)
                dst = np.concatenate(
                    [w * per + np.arange(s1 - s0, dtype=np.int64) for w, (s0, s1) in enumerate(segs)]
                ) if segs else np.zeros((0,), np.int64)
                valid = int(src.size)
                # same refusal as BucketPolicy.pad_chunk: a broadcast leaf
                # whose leading dim collides with the bucket (or per-shard
                # rows) would be silently classified batch-carried at lowering
                # and mis-sharded — this path builds its padded payloads
                # itself, so it must re-state the guard
                ambiguous = {bucket, per} - {int(n)}
                out_leaves = []
                for leaf in perm:
                    if not is_batch_leaf(leaf, n) and any(
                        is_batch_leaf(leaf, a) for a in ambiguous
                    ):
                        raise ValueError(
                            f"non-batch array argument with leading dimension "
                            f"{leaf.shape[0]} is ambiguous against routed bucket "
                            f"{bucket} (batch size here is {n}, per-shard rows "
                            f"{per}); reshape it (e.g. add a leading axis of 1) "
                            "or choose buckets that cannot collide"
                        )
                    if is_batch_leaf(leaf, n):
                        arr = np.asarray(leaf)
                        out = np.full((bucket,) + arr.shape[1:], self._cfg.pad_value, arr.dtype)
                        out[dst] = arr[src]
                        out_leaves.append(out)
                    else:
                        out_leaves.append(leaf)
                slot_ids = np.zeros((bucket,), np.int32)
                mask = np.zeros((bucket,), bool)
                mask[dst] = True
                for w, (s0, s1) in enumerate(segs):
                    if s1 <= s0:
                        continue
                    # one pager lookup per DISTINCT seated stream (<= resident),
                    # then a vectorized gather over the shard's rows
                    locs = locs_o[s0:s1]
                    uniq = np.unique(locs)
                    slots = np.asarray(
                        [self._pager.slot_of(w, int(u)) for u in uniq], np.int32
                    )
                    slot_ids[w * per: w * per + (s1 - s0)] = slots[
                        np.searchsorted(uniq, locs)
                    ]
                a_pad, kw_pad = jax.tree_util.tree_unflatten(treedef, out_leaves)
                try:
                    self._run_padded_step(
                        (slot_ids,) + tuple(a_pad) + self._q8_payload(),
                        kw_pad, mask, bucket, valid,
                        n_coalesced if committed == 0 else 1,
                        queue_wait_us if committed == 0 else 0.0,
                        t0,
                    )
                    # the step's seed decoded EVERY staged slot in-device —
                    # the staging is consumed, drop the flags
                    self._q8_clear()
                except BaseException as e:
                    # a failed step never ran the grid's decode: any staged
                    # slots' quantized columns are still zero in the arena —
                    # flush them through the host decode (bit-identical)
                    # before ANY recovery path reads or snapshots the state
                    # (the shard-loss reshard below does both)
                    self._q8_flush()
                    target = (
                        self._shard_loss_target()
                        if isinstance(e, InjectedFault)
                        and e.site == "shard_loss"
                        and not e.transient
                        else None
                    )
                    if target is None:
                        raise
                    # a dead shard under routed serving: reshard to the
                    # surviving world (rows re-home via the spill-seeded
                    # restore matrix), then RE-ROUTE everything this group
                    # has not committed — the routing tables (home order,
                    # cursors, slot ids) were built for the dead topology
                    # and cannot be patched in place. The recursive call
                    # emits the group's ONE route span and inherits the
                    # group accounting when nothing committed yet (a
                    # partially-committed group already attributed its
                    # coalesce count / queue wait to the committed rounds).
                    self._reshard_locked(world=target, auto=True)
                    rem = np.concatenate(
                        [
                            np.arange(int(cursors[w]), int(stops[w]), dtype=np.int64)
                            for w in range(W)
                        ]
                    )
                    rem_leaves = [
                        np.asarray(l)[rem] if is_batch_leaf(l, n) else l for l in perm
                    ]
                    a_rem, kw_rem = jax.tree_util.tree_unflatten(treedef, rem_leaves)
                    self._execute_routed(
                        ((sids_o[rem],) + tuple(a_rem), kw_rem), int(rem.size),
                        n_coalesced if committed == 0 else 1,
                        queue_wait_us if committed == 0 else 0.0,
                    )
                    return
                committed += 1
                rounds += 1
                self._stats.routed_steps += 1
                for w, (s0, s1) in enumerate(segs):
                    cursors[w] = s1
                    if s1 > s0:
                        self._pager.touch(w, [int(x) for x in locs_o[s0:s1]])
        except BaseException as e:  # noqa: BLE001 - shrink-on-retry contract
            try:
                # accumulate: the shard-loss re-route nests one
                # _execute_routed inside another, and the shrink-on-retry
                # exactness gate needs the TOTAL committed count
                e._committed_chunks = getattr(e, "_committed_chunks", 0) + committed
            except Exception:  # noqa: BLE001 - exotic exception without a dict
                pass
            raise
        if tr is not None:
            tr.complete(
                "route", trace=self._group_tid or ENGINE_TRACE,
                dur_us=route_us, rows=int(n), rounds=rounds,
            )
            tr.observe("route_us", route_us)

    def _page_round(self, needed: Dict[int, List[int]]) -> None:
        """Make every stream in ``needed`` resident on its shard: plan with
        the pager, spill the evicted rows to host RAM (``page_out`` fault
        site), scatter the faulted-in rows (spilled or init) into their slots
        (``page_in``), then commit the bookkeeping. Both device phases are
        batched per dtype and wrapped in the engine's bounded transient
        retry; the pager commits LAST, so a retried injected fault can never
        leave the tables ahead of the buffers."""
        all_ops, hits, faults = [], 0, 0
        for w in sorted(needed):
            streams = needed[w]
            if not streams:
                continue
            ops, h, f = self._pager.plan_residency(w, streams)
            all_ops.extend(ops)
            hits += h
            faults += f
        self._stats.page_hits += hits
        self._stats.page_faults += faults
        evicts = [op for op in all_ops if op.kind == "evict"]
        loads = [op for op in all_ops if op.kind == "load"]
        tr = self._trace
        gid = self._group_tid or ENGINE_TRACE
        spilled: Dict[Tuple[int, int], Dict[str, np.ndarray]] = {}
        if evicts:
            ws = np.asarray([op.shard for op in evicts])
            js = np.asarray([op.slot for op in evicts])

            def spill_once() -> Tuple[Dict[str, np.ndarray], float]:
                self._fault("page_out")
                t0 = time.perf_counter()
                # one row-gather per dtype; only the evicted rows move to host
                rows = {
                    k: np.asarray(jax.device_get(v[ws, js])) for k, v in self._state.items()
                }
                if self._compress and self._row_codec is not None:
                    # quantize the spilled rows BEFORE they land in host RAM
                    # (the pager's spill store then holds the compressed
                    # form — the whole point of compress_payloads). Encode is
                    # pure in `rows`, so a retry re-encodes from the same
                    # fetched values — scales are never applied twice.
                    self._fault("quant_encode")
                    rows = self._row_codec.encode_buffers(rows)
                return rows, t0

            rows, t0 = self._retry_transient(spill_once)
            dur = (time.perf_counter() - t0) * 1e6
            for i, op in enumerate(evicts):
                spilled[(op.shard, op.stream)] = {k: rows[k][i].copy() for k in rows}
            self._stats.page_outs += len(evicts)
            if tr is not None:
                tr.complete("page_out", trace=gid, dur_us=dur, rows=len(evicts))
                tr.observe("page_out_us", dur)
        if loads:
            ws = np.asarray([op.shard for op in loads])
            js = np.asarray([op.slot for op in loads])
            sh = self._shard_sharding()

            stage_q8 = bool(self._q8_keys)

            def load_once() -> Tuple[Tuple[Dict[str, Any], List[Any]], float]:
                self._fault("page_in")
                t0 = time.perf_counter()
                src_rows: List[Dict[str, np.ndarray]] = []
                staged: List[Any] = []
                for op in loads:
                    raw = (
                        self._pager.spilled_row(op.shard, op.stream) if stage_q8 else None
                    )
                    if raw is not None and self._row_codec.is_encoded(raw):
                        # q8-RESIDENT seat (ISSUE 16): the eligible dtypes'
                        # quantized columns stay int8 — seeded zero here, the
                        # codes/scales stage host-side and the segment grid
                        # decodes them on touch. The fault site still fires
                        # (the exact remainder and ineligible dtypes decode
                        # host-side as before); stage_buffers is pure in the
                        # stored row, so the outer transient retry is safe.
                        self._fault("quant_decode")
                        seed, st = self._row_codec.stage_buffers(raw, self._q8_keys)
                        src_rows.append(seed)
                        staged.append(st)
                    else:
                        src_rows.append(
                            self._decoded_spill_row(op.shard, op.stream) or self._init_row
                        )
                        staged.append(None)
                new_state = {}
                for k, buf in self._state.items():
                    rows_np = np.stack([r[k] for r in src_rows]).astype(buf.dtype)
                    # one batched scatter per dtype; re-pin the shard sharding
                    # so the eager .at update cannot drift the placement
                    new_buf = buf.at[ws, js].set(jnp.asarray(rows_np))
                    new_state[k] = jax.device_put(new_buf, sh)
                return (new_state, staged), t0

            (new_state, staged), t0 = self._retry_transient(load_once)
            dur = (time.perf_counter() - t0) * 1e6
            self._state = new_state
            self._state_version += 1
            # publish the staging ONLY after the scatter landed (a failed /
            # retried load must never leave flags ahead of the buffers)
            for op, st in zip(loads, staged):
                if st is not None:
                    self._q8_stage["flags"][0, op.shard, op.slot] = 1
                    for k in self._q8_keys:
                        codes, scales = self._q8_stage[k]
                        codes[0, op.shard, op.slot] = st[k][0]
                        scales[0, op.shard, op.slot] = st[k][1]
            self._stats.page_ins += len(loads)
            if tr is not None:
                tr.complete("page_in", trace=gid, dur_us=dur, rows=len(loads))
                tr.observe("page_in_us", dur)
        if all_ops:
            self._pager.commit(all_ops, spilled)
        self._refresh_gauges()

    # -------------------------------------------------------- q8-resident staging

    def _q8_reset_stage(self) -> None:
        """Recompute the staged dtype set and (re)allocate the host staging
        arrays for the current topology — flags ``(1, W, R)`` i32 plus per
        eligible dtype codes ``(1, W, R, n)`` i8 and scales ``(1, W, R, n)``
        f32. Leading axis 1 keeps every leaf unambiguously broadcast
        (replicated) against any bucket. Re-run on reshard/restore: a changed
        ``resident`` moves the segment form's VMEM gate, so the eligible set
        is re-judged, and the step's payload tail re-sizes with it (its
        program key changes — the demoted/promoted step recompiles once)."""
        if not getattr(self, "_q8_enabled", False):
            self._q8_keys = ()
            self._q8_stage = {}
            return
        fall = self._megastep_fallback_reasons()
        self._q8_keys = tuple(
            k
            for k in self._megastep_plan.eligible_keys()
            if k not in fall and k in self._row_codec._q_mask
        )
        if not self._q8_keys:
            self._q8_stage = {}
            return
        sizes = self._layout.buffer_sizes()
        w, r = self._world, self._resident
        self._q8_stage = {"flags": np.zeros((1, w, r), np.int32)}
        for k in self._q8_keys:
            self._q8_stage[k] = (
                np.zeros((1, w, r, sizes[k]), np.int8),
                np.zeros((1, w, r, sizes[k]), np.float32),
            )

    def _q8_payload(self) -> Tuple[Any, ...]:
        """The staged q8 leaves appended to every routed payload (empty when
        staging is off): zero-filled when nothing is staged, so the payload
        signature — and with it the program set — stays closed."""
        if not self._q8_keys:
            return ()
        out: List[Any] = [self._q8_stage["flags"]]
        for k in self._q8_keys:
            out.extend(self._q8_stage[k])
        return tuple(out)

    def _q8_clear(self) -> None:
        if self._q8_keys:
            self._q8_stage["flags"].fill(0)

    def _q8_flush(self) -> None:
        """Seat any PENDING staged slots through the host decode instead: a
        failed (or abandoned) step never ran the grid's seed, so the staged
        slots' quantized columns are still zero in the arena. The patch is
        the codec's own arithmetic (int8→f32, one f32 multiply, one cast) —
        a chaos run that flushes is bit-identical to the device decode."""
        if not self._q8_keys:
            return
        flags = self._q8_stage["flags"][0]
        ws, js = np.nonzero(flags)
        if ws.size:
            sh = self._shard_sharding()
            new_state = dict(self._state)
            for k in self._q8_keys:
                codes, scales = self._q8_stage[k]
                mask = self._row_codec._q_mask[k]
                rows = np.asarray(jax.device_get(new_state[k][ws, js]))
                dec = (
                    codes[0][ws, js].astype(np.float32) * scales[0][ws, js]
                ).astype(rows.dtype)
                rows[:, mask] = dec[:, mask]
                new_state[k] = jax.device_put(
                    new_state[k].at[ws, js].set(jnp.asarray(rows)), sh
                )
            self._state = new_state
            self._state_version += 1
        self._q8_clear()

    # --------------------------------------------------------------------- readers

    def _decoded_spill_row(self, shard: int, stream: int) -> Optional[Dict[str, np.ndarray]]:
        """One stream's spilled row from host RAM, decoded when the spill
        store holds the compressed form (the at-rest codec: ISSUE 10). The
        decode is pure in the stored row, so a ``quant_decode`` transient
        retries without side effects."""
        row = self._pager.spilled_row(shard, stream)
        if row is None:
            return None
        if self._row_codec is not None and self._row_codec.is_encoded(row):

            def decode_once() -> Dict[str, np.ndarray]:
                self._fault("quant_decode")
                return self._row_codec.decode_buffers(row)

            row = self._retry_transient(decode_once)
        return row

    def _decoded_pager_payload(
        self, payload: Dict[str, Any], codec: Optional[Any] = None
    ) -> Dict[str, Any]:
        """A pager snapshot payload with its spill matrices decoded (the
        slot table and coordinates pass through) — what the host-side row
        reassembly consumes when spills were stored compressed. ``codec``
        overrides the engine's own row codec (the cross-topology restore
        builds one ad hoc on an unsharded target)."""
        codec = codec if codec is not None else self._row_codec
        if codec is None:
            return payload
        spill = {
            k[len("spill_"):]: payload[k]
            for k in payload
            if k.startswith("spill_") and k != "spill_coords"
        }
        if not spill or not codec.is_encoded(spill):
            return payload

        def decode_once() -> Dict[str, np.ndarray]:
            self._fault("quant_decode")
            return codec.decode_buffers(spill)

        decoded = self._retry_transient(decode_once)
        out = {
            k: v
            for k, v in payload.items()
            if not (k.startswith("spill_") and k != "spill_coords")
        }
        for k, v in decoded.items():
            out[f"spill_{k}"] = v
        return out

    def _normalized_pager_payload(
        self, payload: Dict[str, Any], snap_codec: Optional[Any]
    ) -> Dict[str, Any]:
        """A restored pager payload re-expressed in THIS engine's spill-store
        form. The snapshot's compression state may legitimately differ from
        ``compress_payloads`` (a compressed snapshot restores into an
        uncompressed same-policy engine, and vice versa) — but a MIXED spill
        store, restored rows in one form and later evictions in the other,
        would break the per-key stacking ``snapshot_payload`` relies on. So
        restore converts once, here."""
        spill = {
            k[len("spill_"):]: v
            for k, v in payload.items()
            if k.startswith("spill_") and k != "spill_coords"
        }
        if not spill:
            return payload
        is_encoded = snap_codec is not None and snap_codec.is_encoded(spill)
        want_encoded = self._compress and self._row_codec is not None
        if is_encoded == want_encoded:
            return payload
        if is_encoded:  # compressed snapshot -> verbatim-storing engine
            return self._decoded_pager_payload(payload, codec=snap_codec)

        # verbatim snapshot -> compressing engine: encode the spill matrices
        def encode_once() -> Dict[str, np.ndarray]:
            self._fault("quant_encode")
            return self._row_codec.encode_buffers(
                {k: np.asarray(v) for k, v in spill.items()}
            )

        encoded = self._retry_transient(encode_once)
        out = {
            k: v
            for k, v in payload.items()
            if not (k.startswith("spill_") and k != "spill_coords")
        }
        for k, v in encoded.items():
            out[f"spill_{k}"] = v
        return out

    def _fetch_row(self, sid: int, pane: Optional[int] = None) -> Dict[str, np.ndarray]:
        """ONE (stream, pane) packed arena row (per-dtype host vectors):
        from its home shard's slot when resident (only that row crosses to
        host), read-through from the host spill store when paged out (no
        eviction — residency changes only on the submit path; the row
        decodes through the at-rest codec when spills are compressed), or
        the init row for a never-touched stream/pane. ``pane`` defaults to
        the current cursor. Caller holds the state lock."""
        w, loc = self._home_row(sid, pane)
        slot = self._pager.slot_of(w, loc)
        if slot is not None:
            return {k: np.asarray(jax.device_get(v[w, slot])) for k, v in self._state.items()}
        spilled = self._decoded_spill_row(w, loc)
        if spilled is not None:
            return spilled
        return self._init_row

    def _windowed_row_result(self, sid: int) -> Any:
        """``result(sid)`` for the stream-sharded engine: the current pane's
        row for cumulative/tumbling reads (one row moves, exactly as before
        windows); a sliding read stacks the stream's ``panes`` rows (each
        resident, spilled, or init) and folds them through one compiled
        merge+compute program."""
        if self._pane_rows == 1 or self._window.kind == "tumbling":
            return self._row_compute_program()(self._fetch_row(sid))
        rows = [self._fetch_row(sid, pane=p) for p in range(self._pane_rows)]
        stacked = {k: np.stack([r[k] for r in rows]) for k in rows[0]}
        return self._row_window_compute_program()(stacked)

    def _row_window_compute_program(self):
        """ONE stream's pane-stacked rows ``{dtype: (panes, n)}`` -> the
        sliding-window value: unpack the ring, fold via
        ``merge_stacked_states``, compute. Mesh-free (rows are already
        gathered host-side), cached like every program."""
        row_abs = {
            k: jax.ShapeDtypeStruct((self._pane_rows, n), jnp.dtype(k))
            for k, n in self._layout.buffer_sizes().items()
        }
        key = self._aot.program_key(
            f"compute_sstream_win+k.{self._kernel_tag()}+w.{self._window_tag()}",
            self._metric_fp,
            arg_tree=row_abs, mesh=None, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric, layout = self._metric, self._layout

        def build():
            def fold(rows):
                tree = layout.unpack_stacked(rows)
                return metric.compute_from(metric.merge_stacked_states(tree))

            with self._kernel_scope():
                return jax.jit(fold).lower(row_abs).compile()

        return self._aot.get_or_compile(key, build)

    def _ext_universe(self) -> int:
        """Size of the EXTENDED row-id space under windows: every (shard,
        row-coordinate) pair maps to ``row * world + shard`` — covering
        ceil(S/W) * panes rows per shard, including the ghost tail of ids
        past S (never touched, reassembled as init rows, sliced away)."""
        return self._local_streams * self._world

    def _ext_id(self, sid: int, pane: int) -> int:
        """Extended row id of (stream, pane) — consistent with the pager's
        ``row * world + shard`` coordinates the reassembly indexes by."""
        return ((sid // self._world) * self._pane_rows + pane) * self._world + (
            sid % self._world
        )

    def _ext_ids(self, panes: Any) -> np.ndarray:
        """Vectorized :meth:`_ext_id`: the ``(len(panes), S)`` extended-id
        index matrix — a pure arange computation, so a results() scrape at
        S=10^5 never walks a Python loop over (stream, pane) pairs."""
        sids = np.arange(self._num_streams, dtype=np.int64)
        base = (sids // self._world) * self._pane_rows * self._world + sids % self._world
        return base[None, :] + np.asarray(panes, np.int64)[:, None] * self._world

    def _sharded_results_values(self) -> Any:
        """The batched all-streams values (state lock held). Windowed rings
        reassemble the EXTENDED row universe once, regroup it host-side to
        pane-stacked per-stream matrices, and run one fold program; the
        tumbling read slices the current pane and reuses the plain batched
        program."""
        if self._pane_rows == 1:
            return self._results_program_sharded()(self._global_rows_host())
        ext = self._global_rows_host()
        if self._window.kind == "tumbling":
            # only the open pane is read: gather its S rows directly
            idx = self._ext_ids([self._pane_cursor])[0]
            cur = {k: np.asarray(v)[idx] for k, v in ext.items()}  # (S, n)
            return self._results_program_sharded()(cur)
        # (P, S) pane-major index, matching the logical (panes, S, ...) layout
        idx = self._ext_ids(range(self._pane_rows))
        stacked = {k: np.asarray(v)[idx] for k, v in ext.items()}  # (P, S, n)
        return self._results_window_program_sharded()(stacked)

    def _results_window_program_sharded(self):
        """Every stream's sliding value from the ``{dtype: (panes, S, n)}``
        pane-stacked row matrices: ONE vmapped merge+compute over the stream
        axis — still a single device computation per scrape, any S."""
        stacked_abs = {
            k: jax.ShapeDtypeStruct((self._pane_rows, self._num_streams, n), jnp.dtype(k))
            for k, n in self._layout.buffer_sizes().items()
        }
        key = self._aot.program_key(
            f"compute_sstream_win_all+k.{self._kernel_tag()}+w.{self._window_tag()}",
            self._metric_fp,
            arg_tree=stacked_abs, mesh=None, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric, layout = self._metric, self._layout

        def build():
            def fold_all(stacked):
                tree = layout.unpack_stacked(stacked, lead=2)  # (panes, S, ...)
                merged = metric.merge_stacked_states(tree)     # fold panes -> (S, ...)
                return jax.vmap(metric.compute_from)(merged)

            with self._kernel_scope():
                return jax.jit(fold_all).lower(stacked_abs).compile()

        return self._aot.get_or_compile(key, build)

    def _global_rows_host(self) -> Dict[str, np.ndarray]:
        """Reassemble every stream's packed row host-side: resident slots out
        of the (device) arena, spilled rows out of host RAM, init rows for the
        untouched tail — the ``(S, n)`` per-dtype matrices ``results()`` /
        ``state()`` / the merged restore path all share (``(EXT, n)`` over
        the extended (stream, pane) universe under ring windows). Caller
        holds the state lock."""
        arena = {k: np.asarray(jax.device_get(v)) for k, v in self._state.items()}
        num = self._num_streams if self._pane_rows == 1 else self._ext_universe()
        return self._rows_from_parts(
            arena, self._decoded_pager_payload(self._pager.snapshot_payload()),
            self._init_row, num, self._world,
        )

    @staticmethod
    def _rows_from_parts(
        arena: Dict[str, Any],
        pager_payload: Dict[str, Any],
        init_row: Dict[str, np.ndarray],
        num_streams: int,
        world: int,
    ) -> Dict[str, np.ndarray]:
        """``(S, n)`` per-dtype row matrices from a (host) paged arena + pager
        payload — shared by the live readers and the cross-topology restore
        (which reconstructs from a SNAPSHOT's parts, no live pager needed)."""
        out = {
            k: np.tile(np.asarray(init_row[k])[None], (num_streams, 1)) for k in arena
        }
        # both passes are single fancy-index assignments: at S=10^4+ a
        # per-row Python walk would dominate the scrape the batched
        # one-dispatch compute exists to make cheap
        slots = np.asarray(pager_payload["slots"])
        w_idx, j_idx = np.nonzero(slots >= 0)
        if w_idx.size:
            g = slots[w_idx, j_idx].astype(np.int64) * world + w_idx
            keep = g < num_streams
            for k in out:
                out[k][g[keep]] = np.asarray(arena[k])[w_idx[keep], j_idx[keep]]
        coords = np.asarray(
            pager_payload.get("spill_coords", np.zeros((0, 2), np.int64))
        ).reshape(-1, 2)
        if coords.size:
            g = coords[:, 1].astype(np.int64) * world + coords[:, 0].astype(np.int64)
            keep = g < num_streams
            for k in out:
                out[k][g[keep]] = np.asarray(pager_payload[f"spill_{k}"])[keep]
        return out

    def _seeded_pager_payload(
        self,
        rows: Dict[str, np.ndarray],
        init_row: Dict[str, np.ndarray],
        num_rows: Optional[int] = None,
    ) -> Dict[str, Any]:
        """A pager payload (EMPTY slot table + spill store) carrying every
        non-init stream row under THIS engine's ``(world, resident)`` homing
        — the cross-topology half of the stream-shard restore matrix.
        ``num_rows`` overrides the row-universe size for pane-EXTENDED
        windowed rings (same coordinate math — ``e % world`` / ``e // world``
        — over the larger id space). Init-equal rows are skipped (their
        streams fault in the init row like any untouched stream); a row
        containing NaN compares unequal and spills — conservative, never
        lossy."""
        n = int(num_rows) if num_rows is not None else self._num_streams
        payload: Dict[str, Any] = {
            "slots": np.full((self._world, self._resident), -1, np.int64)
        }
        keys = sorted(rows)
        diff = np.zeros((n,), bool)
        for k in keys:
            diff |= ~np.all(
                np.asarray(rows[k]) == np.asarray(init_row[k])[None], axis=1
            )
        sids = np.nonzero(diff)[0].astype(np.int64)
        if sids.size:
            payload["spill_coords"] = np.stack(
                [sids % self._world, sids // self._world], axis=1
            ).astype(np.int64)
            for k in keys:
                payload[f"spill_{k}"] = np.asarray(rows[k])[sids]
        return payload

    @staticmethod
    def sshard_piece_logical(metric: Any, state: Any, meta: Dict[str, Any]) -> Any:
        """One stream-shard snapshot piece -> its LOGICAL state tree:
        ``(S, ...)`` unwindowed, ``(panes, S, ...)`` for a pane-stacked ring.
        Static and engine-free — ``restore_fleet_into`` folds one piece per
        host without standing up H sharded engines. Resident slots, spilled
        rows, and init rows reassemble exactly as the single-process merged
        restore does; a compressed piece decodes through the metric's own
        at-rest codec (same policy-fingerprint contract as ``_restore_commit``,
        which the caller checks against ``meta['codec_fp']``)."""
        arena = state.get("arena") if isinstance(state, dict) else None
        pager_payload = state.get("pager") if isinstance(state, dict) else None
        if arena is None or pager_payload is None:
            raise MetricsTPUUserError(
                "stream-shard snapshot payload is missing arena/pager parts"
            )
        world = int(meta.get("world", 1))
        s_snap = int(meta.get("num_streams", 0))
        pane_rows = (
            int(meta.get("panes", 0) or 0)
            if str(meta.get("window", "") or "")
            else 1
        ) or 1
        if str(meta.get("codec", "") or ""):
            from metrics_tpu.engine.quantize import ArenaRowCodec as _ARC

            codec = _ARC.for_metric(metric)
            if codec is not None and codec.is_encoded(arena):
                arena = codec.decode_buffers(
                    {k: np.asarray(v) for k, v in arena.items()}
                )
            spill = {
                k[len("spill_"):]: pager_payload[k]
                for k in pager_payload
                if k.startswith("spill_") and k != "spill_coords"
            }
            if spill and codec is not None and codec.is_encoded(spill):
                decoded = codec.decode_buffers(spill)
                pager_payload = {
                    k: v
                    for k, v in pager_payload.items()
                    if not (k.startswith("spill_") and k != "spill_coords")
                }
                for k, v in decoded.items():
                    pager_payload[f"spill_{k}"] = v
        layout = ArenaLayout.for_state(metric.abstract_state())
        init_row = {
            k: np.asarray(v)
            for k, v in layout.pack(
                jax.tree.map(jnp.asarray, metric.init_state())
            ).items()
        }
        if pane_rows == 1:
            rows = MultiStreamEngine._rows_from_parts(
                arena, pager_payload, init_row, s_snap, world
            )
            return layout.unpack_stacked({k: jnp.asarray(v) for k, v in rows.items()})
        # pane-extended ring: reassemble the EXT universe then regroup each
        # (pane, stream) row through the same ext-id bijection the live
        # engine routes by — a pure function of (world, pane_rows)
        num_rows = -(-s_snap // world) * pane_rows * world
        rows = MultiStreamEngine._rows_from_parts(
            arena, pager_payload, init_row, num_rows, world
        )
        sids = np.arange(s_snap, dtype=np.int64)
        ext = (
            (sids // world) * pane_rows + np.arange(pane_rows, dtype=np.int64)[:, None]
        ) * world + (sids % world)[None, :]
        stacked = {k: jnp.asarray(np.asarray(v)[ext]) for k, v in rows.items()}
        return layout.unpack_stacked(stacked, lead=2)

    def result(self, stream_id: int) -> Any:  # type: ignore[override]
        """Flush, then compute ``stream_id``'s accumulated value. Unsharded:
        the shared compiled program with the stream index at runtime (under
        deferred sync, after one boundary merge of ALL streams). Stream-
        sharded: ONLY the read stream's row moves — its home shard's slot (or
        the host-spilled copy), never the whole state."""
        sid = self._check_stream(stream_id)
        tr = self._trace
        if self._defer_cold_reads:
            # ladder rung 'defer_cold_reads' (ISSUE 11): a COLD stream's read
            # serves the last computed value instead of paying a row fetch /
            # boundary merge while the engine is overloaded. Cold = not
            # resident on its home shard (stream-sharded — the pager's own
            # notion of cold); unsharded engines defer any repeat read. The
            # staleness window closes when the ladder de-escalates (the rung
            # release clears the cache), and writes invalidate per stream.
            with self._state_lock:
                cached = self._result_cache.get(sid)
                cold = (
                    self._pager.slot_of(*self._home(sid)) is None
                    if self._stream_shard
                    else True
                )
            if cached is not None and cold:
                self._stats.record_deferred_read()
                if tr is not None:
                    tr.event("deferred_read", trace=ENGINE_TRACE, stream_id=sid)
                return cached
        handle = (
            tr.begin("result", trace=ENGINE_TRACE, stream_id=sid) if tr is not None else None
        )
        self.flush()
        # analysis: disable=concurrency-check-then-act -- stale-tolerant by design: the defer rung SERVES staleness (bounded by the rung release clearing the cache), and the re-acquired write stores a FRESH value computed under this same hold, never the stale read
        with self._state_lock:
            if self._stream_shard:
                value = self._windowed_row_result(sid)
            else:
                state = self._merged_state() if self._deferred else self._state
                value = self._compute_program()(
                    state, *self._compute_extra(), jnp.asarray(sid, jnp.int32)
                )
            self._stats.result_device_calls += 1
            if self._ladder is not None:
                # the defer rung's staleness source: only ladder-armed
                # engines pay the cache (zero cost otherwise)
                self._result_cache[sid] = value
        if handle is not None:
            jax.block_until_ready(value)  # the SLO observable is value-in-hand
            tr.observe("result_latency_us", tr.end(handle))
        return value

    def results(self) -> Dict[int, Any]:
        """Every stream's value from ONE device computation, for any S: the
        batched (vmapped) all-streams program runs once and the per-stream
        values are sliced host-side — at S=10^5 the former per-stream loop
        was 10^5 dispatches per dashboard scrape. Under deferred sync the
        flush is followed by ONE boundary merge; stream-sharded engines
        reassemble the row matrices host-side (resident + spilled + init)
        first."""
        self.flush()
        with self._state_lock:
            if self._stream_shard:
                vals = self._sharded_results_values()
            else:
                state = self._merged_state() if self._deferred else self._state
                vals = self._results_program()(state, *self._compute_extra())
            self._stats.result_device_calls += 1
        host = jax.device_get(vals)
        return {
            sid: jax.tree.map(lambda x: x[sid], host) for sid in range(self._num_streams)
        }

    def reset_stream(self, stream_id: int) -> None:
        """Zero ONE stream's accumulation; all other streams keep theirs.

        Safe against live traffic on OTHER streams: the read-modify-write
        holds the engine's state lock, so it cannot interleave with a step
        that donates the live buffers (or be overwritten by one). Batches for
        this stream submitted after the call land in the fresh accumulation.
        Under deferred sync the stream's row zeroes in EVERY shard's local
        state (no collective needed — the write is shard-elementwise); under
        stream sharding the pager simply FORGETS the stream (slot freed,
        spill entry dropped) and the next access faults in the init row.
        """
        sid = self._check_stream(stream_id)
        self.flush()
        if self._stream_shard:
            with self._state_lock:
                # a ring window resets EVERY live pane of the stream, not
                # just the current one — "forget this tenant" must not leave
                # history panes serving stale windows
                for p in range(self._pane_rows):
                    w, row = self._home_row(sid, p)
                    self._pager.drop(w, row)
                self._result_cache.pop(sid, None)
                self._state_version += 1
                self._refresh_gauges()
            return
        init = self._metric.init_state()
        # the stream axis sits one level deeper under a ring window (pane
        # axis outermost): slice accordingly, in both carried forms
        if self._deferred:
            set_init = (
                (lambda x, i: x.at[:, :, sid].set(jnp.asarray(i, x.dtype)))
                if self._win_stacked
                else (lambda x, i: x.at[:, sid].set(jnp.asarray(i, x.dtype)))
            )
        else:
            set_init = (
                (lambda x, i: x.at[:, sid].set(jnp.asarray(i, x.dtype)))
                if self._win_stacked
                else (lambda x, i: x.at[sid].set(jnp.asarray(i, x.dtype)))
            )
        with self._state_lock:
            if self._deferred:
                stacked = (
                    self._layout.unpack_stacked(
                        self._state, lead=2 if self._win_stacked else 1
                    )
                    if self._layout is not None
                    else self._state
                )
                tree = jax.tree.map(set_init, stacked, init)
                self._state = self._put_state(tree, stacked=True)
            else:
                tree = jax.tree.map(set_init, self._unpack(self._state), init)
                self._state = self._put_state(tree)
            self._result_cache.pop(sid, None)
            self._state_version += 1

    def _reset_locked(self) -> None:
        # pager tables and the fresh arena swap under the SAME lock hold: a
        # group dispatched right after reset() must never fault pre-reset
        # spilled rows back into the zeroed state
        if self._pager is not None:
            self._pager.reset()
        super()._reset_locked()
        if self._pager is not None:
            self._refresh_gauges()

    def state(self) -> Any:
        """The global (S, ...)-stacked LOGICAL state — ``(panes, S, ...)``
        under ring windows, the pane axis outermost like every windowed
        engine. Stream-sharded engines reassemble it host-side (resident +
        spilled + init rows); other modes defer to the base engine (merged
        under deferred sync, defensive copy single-device)."""
        if not self._stream_shard:
            return super().state()
        self.flush()
        with self._state_lock:
            rows = self._global_rows_host()
        if self._pane_rows == 1:
            return self._layout.unpack_stacked(
                {k: jnp.asarray(v) for k, v in rows.items()}
            )
        idx = self._ext_ids(range(self._pane_rows))
        return self._layout.unpack_stacked(
            {k: jnp.asarray(np.asarray(v)[idx]) for k, v in rows.items()}, lead=2
        )

    def stream_state(self, stream_id: int) -> Any:
        """One stream's LOGICAL state pytree (post-flush). A defensive copy
        on the single-device path (the live buffers are donated into later
        steps); under deferred sync the boundary-merged arrays are ordinary
        non-donated program outputs, returned as-is; stream-sharded engines
        unpack the one fetched row."""
        sid = self._check_stream(stream_id)
        self.flush()
        with self._state_lock:
            if self._stream_shard:
                if self._pane_rows > 1:
                    rows = [self._fetch_row(sid, pane=p) for p in range(self._pane_rows)]
                    stacked = {
                        k: jnp.asarray(np.stack([r[k] for r in rows])) for k in rows[0]
                    }
                    return self._layout.unpack_stacked(stacked)
                row = self._fetch_row(sid)
                return self._layout.unpack({k: jnp.asarray(v) for k, v in row.items()})
            # under a ring window the pane axis stacks OUTSIDE the stream
            # axis: index the stream on axis 1, keeping the pane ring intact
            pick = (
                (lambda x: x[:, sid]) if self._win_stacked else (lambda x: x[sid])
            )
            if self._deferred:
                return jax.tree.map(pick, self._merged_state())
            return jax.tree.map(
                lambda x: jnp.array(pick(x), copy=True), self._unpack(self._state)
            )

    # ------------------------------------------------------------- snapshot/restore

    def _snapshot_state(self) -> Any:
        if not self._stream_shard:
            return super()._snapshot_state()
        # the paged-arena payload: resident buffers AND the pager's spilled
        # rows + slot tables — kill/resume must cover rows living in host RAM.
        # Under compress_payloads the arena buffers encode through the row
        # codec (the spill rows in the pager payload are ALREADY compressed —
        # they were encoded on their way to host RAM), so bytes-on-disk track
        # the quantized footprint.
        arena: Any = {k: np.asarray(jax.device_get(v)) for k, v in self._state.items()}
        if self._compress and self._row_codec is not None:
            host = arena

            def encode_once() -> Dict[str, np.ndarray]:
                self._fault("quant_encode")
                return self._row_codec.encode_buffers(host)

            arena = self._retry_transient(encode_once)
        return {
            "arena": arena,
            "pager": self._pager.snapshot_payload(),
        }

    def _snapshot_meta_extra(self) -> Dict[str, Any]:
        if not self._stream_shard:
            return {}
        return {
            "stream_shard": 1,
            "num_streams": self._num_streams,
            "resident": self._resident,
            "world": self._world,
        }

    def _restore_commit(self, state: Any, meta: Dict[str, Any]) -> None:
        """The stream-shard restore matrix, covering EXACTLY:

        * sharded+paged snapshot -> SAME-(world, resident) sharded engine
          (same S): verbatim — each shard resumes with exactly its resident
          slots and the pager with exactly its spilled rows, so replay from
          ``batches_done`` is bit-exact;
        * sharded+paged snapshot -> sharded engine with a DIFFERENT world or
          residency (grow/shrink — the live-reshard path, ISSUE 11): every
          stream's row reassembles host-side and SEEDS the new pager's spill
          store under the new ``sid % world`` homing; slots start empty and
          rows fault in on first touch, bit-exactly (slot tables are
          topology-local and cannot transfer, but the rows can);
        * sharded+paged snapshot -> SINGLE-DEVICE unsharded MultiStreamEngine
          (same S): the resident + spilled + init rows merge host-side into
          the (S, ...) stacked state.

        Everything else refuses loudly (a plain snapshot has no residency
        provenance a sharded engine could seat; a mesh target must be the
        sharded engine itself).
        """
        snap_shard = bool(int(meta.get("stream_shard", 0) or 0))
        if not snap_shard and not self._stream_shard:
            return super()._restore_commit(state, meta)
        # the window-provenance refusal applies to the stream-shard matrix
        # too (the base path re-checks it harmlessly): pager row coordinates
        # MEAN (stream, pane) only under the policy that wrote them
        self._check_window_provenance(meta)
        if snap_shard and str(meta.get("window", "") or ""):
            # windowed stream-shard snapshots restore into the SAME WORLD
            # only: the pane-extended row id ``eloc = loc * panes + pane``
            # is a pure function of (world, panes), so a same-world engine
            # with a DIFFERENT residency re-homes exactly through the spill
            # store (ISSUE 20 — resident_streams is an HBM budget, not a
            # coordinate), while a world change or a merged unsharded target
            # would re-interleave mid-pane ring coordinates
            w_snap = int(meta.get("world", 1))
            if not self._stream_shard or w_snap != self._world:
                raise MetricsTPUUserError(
                    "a WINDOWED stream-shard snapshot restores into a "
                    f"same-world stream-sharded topology only (snapshot world "
                    f"{w_snap}): pane-extended pager rows have no exact "
                    "cross-world re-homing — restore into a same-world engine "
                    "(any resident_streams), or snapshot from an unwindowed one"
                )
        if not snap_shard:
            raise MetricsTPUUserError(
                "snapshot was not written by a stream-sharded engine; the stream-shard "
                "restore matrix covers {sharded+paged -> same-world, -> single-device "
                "merged} exactly — restore it into a non-sharded MultiStreamEngine"
            )
        s_snap = int(meta.get("num_streams", 0))
        world_snap = int(meta.get("world", 1))
        r_snap = int(meta.get("resident", 0))
        if s_snap != self._num_streams:
            raise MetricsTPUUserError(
                f"snapshot serves {s_snap} streams, this engine {self._num_streams}"
            )
        arena = state.get("arena") if isinstance(state, dict) else None
        pager_payload = state.get("pager") if isinstance(state, dict) else None
        if arena is None or pager_payload is None:
            raise MetricsTPUUserError("stream-shard snapshot payload is missing arena/pager parts")
        # compressed (codec-bearing) snapshots: the buffer-form codec is NOT
        # self-describing (element positions come from layout + policy), so
        # the policy fingerprint in meta must match this engine's — decoding
        # with a different plan would silently unscramble rows
        snap_codec = None
        if str(meta.get("codec", "") or ""):
            if str(meta.get("codec_fp", "") or "") != self._precision_tag:
                raise MetricsTPUUserError(
                    "compressed stream-shard snapshot was written under sync_precision "
                    f"policy {meta.get('codec_fp')!r}, this engine's metric declares "
                    f"{self._precision_tag!r}; restore it with the matching policy"
                )
            snap_codec = self._row_codec
            if snap_codec is None:
                from metrics_tpu.engine.quantize import ArenaRowCodec as _ARC

                snap_codec = _ARC.for_metric(self._metric)
            if snap_codec is not None and snap_codec.is_encoded(arena):

                def decode_once() -> Dict[str, np.ndarray]:
                    self._fault("quant_decode")
                    return snap_codec.decode_buffers(
                        {k: np.asarray(v) for k, v in arena.items()}
                    )

                arena = self._retry_transient(decode_once)
        row_layout = ArenaLayout.for_state(self._metric.abstract_state())
        sizes = row_layout.buffer_sizes()
        if set(arena) != set(sizes) or any(
            tuple(np.shape(arena[k])) != (world_snap, r_snap, n) for k, n in sizes.items()
        ):
            raise MetricsTPUUserError(
                "stream-shard snapshot arena does not match this metric's per-stream "
                "layout; was the metric reconfigured since the snapshot?"
            )
        if self._stream_shard:
            if world_snap == self._world and r_snap == self._resident:
                new_state = self._put_state(arena, packed=True, stacked=True)
                with self._state_lock:
                    self._finish_restore(new_state, meta)
                    self._pager.load_payload(
                        self._normalized_pager_payload(pager_payload, snap_codec)
                    )
                    self._refresh_gauges()
                return
            # cross-topology (the grow/shrink half of the matrix): reassemble
            # the (S, n) row matrices from the snapshot's parts and seed the
            # NEW pager's spill store with every non-init row under this
            # engine's homing rule — the arena starts all-init, rows fault in
            # on first touch, and replay from the cursor stays bit-exact.
            # Windowed rings reach here only with world_snap == self._world
            # (the refusal above), so the pane-EXTENDED row universe keeps
            # its coordinates and only residency re-homes
            init_row = {
                k: np.asarray(v)
                for k, v in row_layout.pack(
                    jax.tree.map(jnp.asarray, self._metric.init_state())
                ).items()
            }
            num_rows = (
                self._num_streams if self._pane_rows == 1 else self._ext_universe()
            )
            rows = self._rows_from_parts(
                arena, self._decoded_pager_payload(pager_payload, codec=snap_codec),
                init_row, num_rows, world_snap,
            )
            seeded = self._seeded_pager_payload(rows, init_row, num_rows=num_rows)
            new_state = self._put_state(self._metric.init_state())
            with self._state_lock:
                self._finish_restore(new_state, meta)
                self._pager.load_payload(
                    self._normalized_pager_payload(seeded, None)
                )
                self._refresh_gauges()
            return
        if self._cfg.mesh is not None:
            raise MetricsTPUUserError(
                "the merged side of the stream-shard restore matrix is the SINGLE-DEVICE "
                "MultiStreamEngine; restore sharded snapshots into the same-world sharded "
                "engine or an unsharded single-device one"
            )
        init_row = {
            k: np.asarray(v)
            for k, v in row_layout.pack(
                jax.tree.map(jnp.asarray, self._metric.init_state())
            ).items()
        }
        stacked = self._rows_from_parts(
            arena, self._decoded_pager_payload(pager_payload, codec=snap_codec),
            init_row, self._num_streams, world_snap,
        )
        tree = row_layout.unpack_stacked({k: jnp.asarray(v) for k, v in stacked.items()})
        self._finish_restore(self._put_state(tree), meta)

    # ------------------------------------------------------------------- coalescing

    def _latch_payload(self, merged: Any) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
        # strip the engine-internal stream_ids arg: the latch row must see
        # exactly what the metric's update signature expects
        args, kwargs = merged
        return tuple(args[1:]), kwargs

    def _coalescible(self, ref: Any, item: Any) -> bool:
        # stream ids NEVER block coalescing — cross-stream megabatches are the
        # point; only the (args, kwargs) payloads must be concatenable
        return super()._coalescible(ref[1:], item[1:])

    def _merge_sized(
        self, nonempty: List[Tuple[Any, int]]
    ) -> Optional[Tuple[Tuple[Any, ...], Dict[str, Any]]]:
        # pre-sized by the caller (one tree-flatten per item total): sizes
        # feed both the per-row stream-id build and the concat. broadcast_to
        # accepts both forms of item id — a scalar stream id (classic
        # multistream) and an already-per-row id array (the ragged engine's
        # group keys), which must be length n
        if not nonempty:
            return None
        stream_ids = np.concatenate(
            [
                np.ascontiguousarray(
                    np.broadcast_to(np.asarray(it[0], np.int32), (n,))
                )
                for it, n in nonempty
            ]
        )
        merged = self._concat_sized([((a, kw), n) for ((_, a, kw), n) in nonempty])
        args, kwargs = merged
        return (stream_ids,) + tuple(args), kwargs
