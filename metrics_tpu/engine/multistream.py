"""Multi-stream serving: S independent evaluation streams, ONE executable.

The ROADMAP's serving regime is many concurrent evaluation streams (one per
user/session/model-variant), each a separate accumulation with its own
result. One :class:`~metrics_tpu.engine.pipeline.StreamingEngine` per stream
multiplies everything that makes small-batch serving dispatch-bound: S AOT
program sets, S dispatcher threads, S donated state transfers per scheduling
quantum. ``MultiStreamEngine`` collapses all of it:

* every member state leaf gains a leading **stream axis** of length
  ``num_streams`` — with arenas on (default), the whole S-stream state is
  still just one buffer per dtype;
* a step takes ``(state, (stream_ids,)+batch, mask)``: the vmapped per-row
  deltas reduce into the addressed stream rows with each reduction's own op
  (``Metric.update_state_segmented``, dispatched through
  ``metrics_tpu/ops/kernels`` — a scatter-free Pallas compare-reduce on TPU,
  ``.at[ids].add/min/max`` on an identity-filled base under the XLA reference
  path), so ONE dispatch can carry rows for MANY streams at once;
* megabatch coalescing composes for free: queued batches from DIFFERENT
  streams concatenate into one step (their rows address different state
  rows), which is exactly the cross-stream amortization a per-stream engine
  can never do;
* ``result(stream_id)`` runs one shared compiled compute program whose
  stream index is a runtime argument — S streams, one compute executable;
* snapshots carry all streams in one (per-dtype) payload; restore brings
  every stream back at once.

The compiled-program budget is UNCHANGED from the single-stream engine: at
most ``len(buckets)`` update programs + 1 compute program, for any S.

Scope: single-device serving, or a mesh under DEFERRED sync
(``EngineConfig(mesh=..., mesh_sync="deferred")``): each shard then carries
its own (S, ...)-stacked local states, the segmented scatter runs entirely
within the shard (collective-free steady step), and ``result()`` rides one
boundary merge of all streams at once. The step-sync mesh form does not
exist — the per-step segmented scatter has no exact shard-and-merge. Metrics
must support the generic delta masked path
(``segmented_update_unsupported_reason`` is None): custom fused masked forms
and scan-fallback members have no segmented counterpart.

Quickstart::

    from metrics_tpu import Accuracy
    from metrics_tpu.engine import EngineConfig, MultiStreamEngine

    engine = MultiStreamEngine(Accuracy(), num_streams=64,
                               config=EngineConfig(buckets=(64, 256)))
    with engine:
        engine.submit(stream_id, preds, target)   # any stream, any order
        ...
        acc_7 = engine.result(7)                  # per-stream compute
"""
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.aot import AotCache
from metrics_tpu.engine.pipeline import EngineConfig, StreamingEngine
from metrics_tpu.engine.trace import ENGINE_TRACE
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["MultiStreamEngine"]


class MultiStreamEngine(StreamingEngine):
    """Serve ``num_streams`` independent accumulations of one metric from a
    single AOT program set and a single dispatcher."""

    def __init__(
        self,
        metric: Any,
        num_streams: int,
        config: Optional[EngineConfig] = None,
        aot_cache: Optional[AotCache] = None,
    ):
        if not isinstance(num_streams, int) or num_streams <= 0:
            raise MetricsTPUUserError(f"num_streams must be a positive int, got {num_streams!r}")
        if config is not None and config.mesh is not None and config.mesh_sync != "deferred":
            raise MetricsTPUUserError(
                "MultiStreamEngine has no step-sync mesh form: the segmented scatter "
                "has no exact per-step shard-and-merge; serve the mesh with "
                "EngineConfig(mesh_sync='deferred') (shard-local stream states, "
                "boundary merge) or use one StreamingEngine per mesh"
            )
        self._num_streams = int(num_streams)
        super().__init__(metric, config=config, aot_cache=aot_cache)

    # -------------------------------------------------------------- capability checks

    def _update_path_unsupported_reason(self, metric: Any) -> Optional[str]:
        # only the UPDATE capability is multi-stream-specific; the base check
        # keeps running the mesh-mode gates (notably the deferred-sync stacked
        # merge requirement) on top of this — a metric that folds fine but
        # cannot merge must refuse at construction, not at the first result()
        return metric.segmented_update_unsupported_reason()

    # ----------------------------------------------------------------- state plumbing

    @property
    def num_streams(self) -> int:
        return self._num_streams

    def _init_state_tree(self) -> Any:
        base = self._metric.init_state()
        return jax.tree.map(
            lambda x: jnp.tile(jnp.asarray(x)[None], (self._num_streams,) + (1,) * jnp.ndim(x)),
            base,
        )

    def _abstract_state_tree(self) -> Any:
        base = self._metric.abstract_state()
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((self._num_streams,) + tuple(s.shape), s.dtype),
            base,
        )

    # ------------------------------------------------------------------ AOT programs

    def _update_kind(self) -> str:
        return "update_mstream"

    def _traced_update(self, state_tree: Any, payload: Any, mask: Any) -> Any:
        a, kw = payload
        stream_ids, rest = a[0], a[1:]
        return self._metric.update_state_segmented(
            state_tree, *rest, mask=mask,
            segment_ids=stream_ids, num_segments=self._num_streams, **kw,
        )

    def _compute_program(self):
        """One executable computes ANY stream: the stream index is a runtime
        scalar argument, so S streams never cost S compiles. Under deferred
        sync the input is the boundary-merged (S, ...)-stacked global state
        instead of the carried shard-local arena."""
        sid_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = self._aot.program_key(
            f"compute_mstream+k.{self._kernel_tag()}", self._metric_fp,
            arg_tree=(self._compute_input_abstract(), sid_abs),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
        )
        metric = self._metric

        def build():
            def compute(state, sid):
                row = jax.tree.map(lambda x: x[sid], self._compute_tree(state))
                return metric.compute_from(row)

            with self._kernel_scope():
                return jax.jit(compute).lower(self._compute_input_abstract(), sid_abs).compile()

        return self._aot.get_or_compile(key, build)

    # --------------------------------------------------------------------- producers

    def _check_stream(self, stream_id: Any) -> int:
        sid = int(stream_id)
        if not 0 <= sid < self._num_streams:
            raise MetricsTPUUserError(
                f"stream_id {sid} out of range for num_streams={self._num_streams}"
            )
        return sid

    def submit(
        self, stream_id: int, *args: Any, timeout: Optional[float] = None, **kwargs: Any
    ) -> None:
        """Enqueue one (ragged) batch for ``stream_id``. Blocks when full;
        ``timeout`` bounds the wait exactly like the base engine's (sticky
        dispatcher error preferred over :class:`BackpressureTimeout`)."""
        sid = self._check_stream(stream_id)
        self._raise_if_failed()
        self.start()
        # the base helper traces the submit when a recorder is attached —
        # _item_context puts the stream_id on the span (every span this
        # batch's journey produces carries it through the group context)
        self._submit_item((sid, args, kwargs), timeout)

    # ---------------------------------------------------------- fault context

    def _screen_payload(self, item: Any) -> Any:
        # the screen policy must see exactly what the metric's update sees —
        # strip the engine-internal stream id
        return (item[1], item[2])

    def _item_context(self, item: Any) -> Dict[str, Any]:
        return {"stream_id": item[0]}

    def _group_context(self, group: List[Any]) -> Dict[str, Any]:
        # the sticky error names every stream whose traffic rode the failed
        # group — the poisoned input is in one of THOSE streams' logs
        sids = sorted({it[0] for it in group if isinstance(it, tuple) and len(it) == 3})
        return {"stream_ids": sids} if sids else {}

    def result(self, stream_id: int) -> Any:  # type: ignore[override]
        """Flush, then compute ``stream_id``'s accumulated value (shared
        compiled program, stream index passed at runtime). Under deferred
        sync the flush is followed by one boundary merge of ALL streams'
        shard-local states."""
        sid = self._check_stream(stream_id)
        tr = self._trace
        handle = (
            tr.begin("result", trace=ENGINE_TRACE, stream_id=sid) if tr is not None else None
        )
        self.flush()
        with self._state_lock:
            state = self._merged_state() if self._deferred else self._state
            value = self._compute_program()(state, jnp.asarray(sid, jnp.int32))
        if handle is not None:
            jax.block_until_ready(value)  # the SLO observable is value-in-hand
            tr.observe("result_latency_us", tr.end(handle))
        return value

    def results(self) -> Dict[int, Any]:
        """Every stream's value (one flush — and under deferred sync ONE
        boundary merge — then S cached-program calls)."""
        self.flush()
        with self._state_lock:
            state = self._merged_state() if self._deferred else self._state
            program = self._compute_program()
            return {
                sid: program(state, jnp.asarray(sid, jnp.int32))
                for sid in range(self._num_streams)
            }

    def reset_stream(self, stream_id: int) -> None:
        """Zero ONE stream's accumulation; all other streams keep theirs.

        Safe against live traffic on OTHER streams: the read-modify-write
        holds the engine's state lock, so it cannot interleave with a step
        that donates the live buffers (or be overwritten by one). Batches for
        this stream submitted after the call land in the fresh accumulation.
        Under deferred sync the stream's row zeroes in EVERY shard's local
        state (no collective needed — the write is shard-elementwise).
        """
        sid = self._check_stream(stream_id)
        self.flush()
        init = self._metric.init_state()
        with self._state_lock:
            if self._deferred:
                stacked = (
                    self._layout.unpack_stacked(self._state)
                    if self._layout is not None
                    else self._state
                )
                tree = jax.tree.map(
                    lambda x, i: x.at[:, sid].set(jnp.asarray(i, x.dtype)), stacked, init
                )
                self._state = self._put_state(tree, stacked=True)
            else:
                tree = jax.tree.map(
                    lambda x, i: x.at[sid].set(jnp.asarray(i, x.dtype)),
                    self._unpack(self._state), init,
                )
                self._state = self._put_state(tree)
            self._state_version += 1

    def stream_state(self, stream_id: int) -> Any:
        """One stream's LOGICAL state pytree (post-flush). A defensive copy
        on the single-device path (the live buffers are donated into later
        steps); under deferred sync the boundary-merged arrays are ordinary
        non-donated program outputs, returned as-is."""
        sid = self._check_stream(stream_id)
        self.flush()
        with self._state_lock:
            if self._deferred:
                return jax.tree.map(lambda x: x[sid], self._merged_state())
            return jax.tree.map(
                lambda x: jnp.array(x[sid], copy=True), self._unpack(self._state)
            )

    # ------------------------------------------------------------------- coalescing

    def _latch_payload(self, merged: Any) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
        # strip the engine-internal stream_ids arg: the latch row must see
        # exactly what the metric's update signature expects
        args, kwargs = merged
        return tuple(args[1:]), kwargs

    def _coalescible(self, ref: Any, item: Any) -> bool:
        # stream ids NEVER block coalescing — cross-stream megabatches are the
        # point; only the (args, kwargs) payloads must be concatenable
        return super()._coalescible(ref[1:], item[1:])

    def _merge_sized(
        self, nonempty: List[Tuple[Any, int]]
    ) -> Optional[Tuple[Tuple[Any, ...], Dict[str, Any]]]:
        # pre-sized by the caller (one tree-flatten per item total): sizes
        # feed both the per-row stream-id build and the concat
        if not nonempty:
            return None
        stream_ids = np.concatenate(
            [np.full((n,), it[0], np.int32) for it, n in nonempty]
        )
        merged = self._concat_sized([((a, kw), n) for ((_, a, kw), n) in nonempty])
        args, kwargs = merged
        return (stream_ids,) + tuple(args), kwargs
