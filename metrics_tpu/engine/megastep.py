"""Whole-step megakernel plan: one Pallas grid per arena dtype (ISSUE 16).

PR 3's arena packs every state leaf of one dtype into a single buffer; PR 4's
kernels fold each leaf's masked row deltas in one launch PER LEAF. This module
builds the static plan that combines the two: walk the arena's slice metadata
(:meth:`ArenaLayout.leaf_slices`), assign every COLUMN of each dtype buffer
its owning leaf's reduction opcode, and at step time pack all leaves' row
deltas into one column-aligned ``(N, F)`` matrix per dtype, folded by ONE
:func:`~metrics_tpu.ops.kernels.dispatch.megastep_fold` (or, for the
stream-sharded engine, :func:`megastep_segment`) launch. The unpack → per-leaf
fold → repack intermediates of the per-leaf path never exist: the packed
delta matrix is built directly from the vmapped row deltas and the output IS
the arena buffer.

Eligibility is PER DTYPE and fully static:

* every leaf of the dtype folds by ``sum``/``min``/``max`` through the
  generic delta path (members with custom masked forms or scan-strategy
  buffers mark their leaves ``none``) — reason ``"strategy"``;
* the dtype is one the Pallas kernels serve (f32/bf16/i32) — ``"dtype"``;
* the packed row fits a VMEM block (and, for the segment form, the whole
  slot-stacked ``(S, F)`` buffer fits) — ``"vmem"``.

An ineligible dtype silently degrades to the per-leaf kernels — under BOTH
``megastep`` and ``megastep_interpret``: per-dtype degradation is the
megakernel's contract, not an error (only an engine whose whole LAYOUT cannot
take the path raises under interpret — ``engine/pipeline.py``). Every
degraded dtype is visible in ``stats.kernel_fallbacks``.
"""
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.ops.kernels.common import (
    REDUCE_OPS,
    VMEM_BLOCK_BYTES,
    block_rows,
    supported_dtype,
)
from metrics_tpu.ops.kernels.dispatch import megastep_fold, megastep_segment

__all__ = ["MegastepPlan", "flat_reductions"]

Array = jax.Array

#: per-leaf marker for "this leaf cannot ride the generic delta fold"
NO_FOLD = "none"


def _is_collection(m: Any) -> bool:
    return hasattr(m, "items") and not hasattr(m, "_defaults")


def _metric_fx_tree(m: Any, foldable: bool) -> Dict[str, Any]:
    """Per-leaf reduction names, congruent to ``m``'s state tree. A foldable
    (delta-strategy) member contributes each state's own ``dist_reduce_fx``,
    recursing into nested metrics with THEIR reductions — exactly the leaves
    ``Metric._masked_reduce_into`` folds; anything else marks every leaf
    :data:`NO_FOLD`. Mirrors ``engine/quantize.py::_flat_precisions`` so the
    flatten order is the arena layout's."""
    out: Dict[str, Any] = {}
    for k in m._defaults:
        fx = m._reductions[k] if foldable else NO_FOLD
        out[k] = fx if fx in REDUCE_OPS else NO_FOLD
    children = m._child_metrics()
    if children:
        out[m._CHILD_KEY] = {
            name: (
                [_metric_fx_tree(c, foldable) for c in child]
                if isinstance(child, list)
                else _metric_fx_tree(child, foldable)
            )
            for name, child in children.items()
        }
    return out


def flat_reductions(metric: Any) -> List[str]:
    """Per-leaf reduction names (``"sum"``/``"min"``/``"max"``/``"none"``)
    in ``abstract_state`` tree-flatten order — the opcode source for
    :meth:`ArenaLayout.column_ops`."""

    def ptree(m: Any) -> Any:
        if _is_collection(m):
            return {k: ptree(mm) for k, mm in m.items(keep_base=True)}
        return _metric_fx_tree(m, m.masked_update_strategy() == "delta")

    return [str(f) for f in jax.tree_util.tree_leaves(ptree(metric))]


class MegastepPlan:
    """Static megastep plan for one metric/collection over its arena layout.

    Pure metadata (shares the engine's :class:`ArenaLayout`); the apply
    methods are traced inside the engine's step programs.
    """

    def __init__(self, metric: Any, layout: Any):
        self._metric = metric
        self._layout = layout
        self._fx = flat_reductions(metric)
        slices = layout.leaf_slices()
        if len(self._fx) != len(slices):  # pragma: no cover - same flatten order
            raise ValueError(
                f"reduction list ({len(self._fx)}) does not align with the arena "
                f"layout ({len(slices)} leaves)"
            )
        #: dtype key -> [(leaf_index, offset, size, shape, dtype)]
        self._by_key: Dict[str, List[Tuple[int, int, int, Tuple[int, ...], Any]]] = {}
        for i, (key, off, size, shape, dtype) in enumerate(slices):
            self._by_key.setdefault(key, []).append((i, off, size, shape, dtype))
        self._ops = layout.column_ops(
            [REDUCE_OPS.index(f) if f in REDUCE_OPS else 0 for f in self._fx]
        )
        totals = layout.buffer_sizes()
        self._reasons: Dict[str, str] = {}
        for key, items in self._by_key.items():
            if any(self._fx[i] not in REDUCE_OPS for i, *_ in items):
                self._reasons[key] = "strategy"
            elif not supported_dtype(key):
                self._reasons[key] = "dtype"
            elif block_rows(totals[key] * jnp.dtype(key).itemsize) is None:
                self._reasons[key] = "vmem"
        # member name -> rides the packed-delta path (None key = bare metric)
        self._member_delta: Dict[Optional[str], bool] = {}
        if _is_collection(metric):
            for k, m in metric.items(keep_base=True):
                self._member_delta[k] = m.masked_update_strategy() == "delta"
        else:
            self._member_delta[None] = metric.masked_update_strategy() == "delta"

    # ------------------------------------------------------------------ queries

    @property
    def layout(self) -> Any:
        return self._layout

    def eligible_keys(self) -> Tuple[str, ...]:
        """Dtype keys whose whole buffer updates in one megastep launch."""
        return tuple(k for k in sorted(self._by_key) if k not in self._reasons)

    def fallback_reasons(self) -> Dict[str, str]:
        """Per-dtype degradation reasons for the ineligible keys (the
        ``stats.kernel_fallbacks`` feed)."""
        return dict(self._reasons)

    def segment_fallback_reasons(self, num_segments: int) -> Dict[str, str]:
        """Per-dtype reasons for the SEGMENT form: the base reasons plus
        dtypes whose slot-stacked ``(S, F)`` buffer outgrows a VMEM block."""
        out = dict(self._reasons)
        for key, n in self._layout.buffer_sizes().items():
            if key in out:
                continue
            if int(num_segments) * n * jnp.dtype(key).itemsize > VMEM_BLOCK_BYTES:
                out[key] = "vmem"
        return out

    def column_mask(self, key: str, leaf_mask: List[bool]) -> np.ndarray:
        """Boolean column mask of ``key``'s buffer selecting the leaves where
        ``leaf_mask`` (tree-flatten order) is True — e.g. the q8-quantized
        columns the segment kernel decodes on touch."""
        out = np.zeros((self._layout.buffer_sizes()[key],), bool)
        for i, off, size, *_ in self._by_key[key]:
            if leaf_mask[i]:
                out[off : off + size] = True
        return out

    # ------------------------------------------------------------- step bodies

    def _mixed_deltas(self, tree: Any, args: Any, kwargs: Any, mask: Array) -> Any:
        """The state-congruent "mixed" tree: delta members contribute their
        ROW-STACKED deltas ``(N, *leaf)`` (folded later, per dtype or per
        leaf), everything else its full masked-updated state."""
        m = self._metric
        n = int(mask.shape[0])
        if _is_collection(m):
            out: Dict[str, Any] = {}
            for k, mm in m.items(keep_base=True):
                fkw = mm._filter_kwargs(**kwargs)
                if self._member_delta[k]:
                    out[k] = mm._stacked_row_deltas(args, fkw, n)
                else:
                    out[k] = mm.update_state_masked(tree[k], *args, mask=mask, **fkw)
            return out
        if self._member_delta[None]:
            return m._stacked_row_deltas(args, kwargs, n)
        return m.update_state_masked(tree, *args, mask=mask, **kwargs)

    def _packed_rows(self, key: str, mixed_leaves: List[Any], n: int) -> Array:
        """Column-aligned ``(N, F)`` delta matrix for dtype ``key`` — each
        leaf's stacked delta raveled per row into its arena columns."""
        parts = [
            jnp.reshape(jnp.asarray(mixed_leaves[i], dtype), (n, size))
            for i, _off, size, _shape, dtype in self._by_key[key]
        ]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)

    def apply_masked(
        self, arena: Dict[str, Array], args: Any, kwargs: Any, mask: Array
    ) -> Dict[str, Array]:
        """One masked collection step over the packed arena: eligible dtypes
        take one :func:`megastep_fold` launch each; ineligible dtypes fold
        per leaf (the PR 4 kernels) and repack."""
        from metrics_tpu.ops.kernels.dispatch import fold_rows_masked

        n = int(mask.shape[0])
        tree = self._layout.unpack(arena)
        mixed = self._mixed_deltas(tree, args, kwargs, mask)
        mixed_leaves = jax.tree_util.tree_flatten(mixed)[0]
        state_leaves = jax.tree_util.tree_flatten(tree)[0]
        out: Dict[str, Array] = {}
        for key, items in self._by_key.items():
            if key not in self._reasons:
                rows = self._packed_rows(key, mixed_leaves, n)
                out[key] = megastep_fold(arena[key], rows, mask, self._ops[key])
                continue
            parts = []
            for i, _off, _size, _shape, dtype in items:
                fx = self._fx[i]
                if fx in REDUCE_OPS:
                    leaf = fold_rows_masked(state_leaves[i], mixed_leaves[i], mask, fx)
                else:
                    leaf = mixed_leaves[i]
                parts.append(jnp.ravel(jnp.asarray(leaf, dtype)))
            out[key] = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        return out

    def apply_segmented(
        self,
        bufs: Dict[str, Array],
        args: Any,
        kwargs: Any,
        mask: Array,
        segment_ids: Array,
        num_segments: int,
        q8_stage: Optional[Dict[str, Tuple[Array, Array, Array]]] = None,
        q8_cols: Optional[Dict[str, np.ndarray]] = None,
    ) -> Dict[str, Array]:
        """One segmented (multi-stream) step over the slot-stacked arena
        buffers ``(S, F)``: pager slot ids are the segment ids. ``q8_stage``
        maps ELIGIBLE dtype keys to ``(flags, codes, scales)`` staged
        q8-resident slots (decoded on touch inside the grid; ``q8_cols``
        carries each key's static quantized-column mask)."""
        from metrics_tpu.ops.kernels.dispatch import segment_reduce_masked

        m = self._metric
        n = int(mask.shape[0])
        num_segments = int(num_segments)
        reasons = self.segment_fallback_reasons(num_segments)
        if q8_stage:
            bad = sorted(set(q8_stage) & set(reasons))
            if bad:  # pragma: no cover - engine stages eligible dtypes only
                raise ValueError(f"q8 staging on megastep-ineligible dtypes: {bad}")
        if _is_collection(m):
            mixed = {
                k: mm._stacked_row_deltas(args, mm._filter_kwargs(**kwargs), n)
                for k, mm in m.items(keep_base=True)
            }
        else:
            mixed = m._stacked_row_deltas(args, kwargs, n)
        mixed_leaves = jax.tree_util.tree_flatten(mixed)[0]
        out: Dict[str, Array] = {}
        for key, items in self._by_key.items():
            if key not in reasons:
                rows = self._packed_rows(key, mixed_leaves, n)
                q8 = None
                if q8_stage and key in q8_stage:
                    flags, codes, scales = q8_stage[key]
                    q8 = (flags, codes, scales, q8_cols[key])
                out[key] = megastep_segment(
                    bufs[key], rows, mask, segment_ids, num_segments,
                    self._ops[key], q8=q8,
                )
                continue
            parts = []
            for i, off, size, shape, dtype in items:
                state_leaf = jnp.reshape(
                    bufs[key][..., off : off + size], (num_segments,) + shape
                )
                fx = self._fx[i]
                if fx not in REDUCE_OPS:  # pragma: no cover - engine gates earlier
                    raise ValueError(
                        f"leaf {i} has no segmented reduction (fx={fx!r})"
                    )
                new_leaf = segment_reduce_masked(
                    state_leaf, mixed_leaves[i], mask, segment_ids, num_segments, fx
                )
                parts.append(jnp.reshape(jnp.asarray(new_leaf, dtype), (num_segments, size)))
            out[key] = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        return out
