"""Chaos smoke: ``python -m metrics_tpu.engine.chaos_smoke [telemetry.json]``.

The CI-shaped proof of the fault-tolerance contract (ISSUE 6), in seconds on
one CPU device (``make chaos-smoke``): a SEEDED fault sweep fires every
injection point in ``engine/faults.py::FAULT_SITES`` at least once, and the
engine recovers from all of it to a ``result()`` BIT-IDENTICAL to a
fault-free run on the same traffic:

1. **Transactional steps** — injected ingest/compile/step/watchdog faults
   roll back onto the pre-step shadow and retry; the arena is never torn
   (layout integrity asserted after the chaos stream).
2. **Quarantine** — a poisoned NaN batch rides the stream; the screen policy
   dead-letters it (it never reaches a compiled step), the ledger accounts
   for exactly its cursor and rows, and parity holds with the quarantined
   batch excluded by construction (the fault-free oracle never sees it).
3. **Graceful degradation** — a kernel-site fault demotes the engine
   ``pallas_interpret → xla`` mid-stream (bit-exact for this traffic: int
   counters and dyadic float sums); a coalesce fault (rate=1.0, also what
   pins every group to one batch so occurrence schedules are deterministic
   under ANY queue timing) degrades megabatching to singleton groups; a
   trace-time ``kernel_fault_scope`` hook proves the dispatcher's per-call
   silent fallback.
4. **Snapshot integrity** — one periodic snapshot write FAILS (contained:
   serving continues, counted), the LAST snapshot is bit-flipped on disk
   after a successful save, and the post-kill ``restore()`` falls back past
   the corrupt LATEST to the previous generation; replaying from its older
   cursor reproduces the uninterrupted result exactly.
5. **Deferred boundary merge** — on a 1-device mesh in deferred mode an
   injected merge fault retries behind ``result()`` (the merge is a
   non-donated read; the carried state stays consistent).
6. **Stream-shard paging** (ISSUE 9) — a resident-capped stream-sharded
   MultiStreamEngine under seeded Zipfian traffic: ``page_out``/``page_in``
   transients fire mid-stream and retry (the pager commits bookkeeping only
   after the bytes moved), every per-stream result stays bit-identical to an
   unsharded unpaged oracle, and a mid-stream snapshot taken WITH rows
   spilled backs the exact restore matrix {sharded+paged → same-world
   verbatim, → single-device merged} — plus the refusal of a plain snapshot
   into a sharded engine.
7. **Elastic serving** (ISSUE 11) — on a 1-device deferred engine with a
   generous admission policy: an ``admission``-site transient retries (the
   check is pure in its input), a TRANSIENT suspected ``shard_loss`` rolls
   back and retries in place, and a manual ``reshard()`` survives injected
   ``reshard_snapshot``/``reshard_restore`` transients — results stay
   bit-identical throughout (the non-transient shard loss with auto-reshard
   is ``make elastic-smoke``'s 8-device claim).
8. **Dead dispatcher** — a fatal fault kills the dispatcher thread outright;
   ``submit(timeout=)`` surfaces the sticky error instead of deadlocking,
   and ``reset()`` drains the dead queue and re-arms. A transient
   ``snapshot_read`` fault retries inside ``restore()``.

Since PR 8 the whole sweep runs under the flight recorder
(``engine/trace.py``): every injected firing must ALSO appear as a span
event, the recorded trace must export as valid Perfetto/Chrome trace-event
JSON (``out/trace_chaos.json``, schema-checked by ``tools/trace_export.py``),
and every megabatch span must link exactly the submit spans it absorbed.
(Same-seed span-sequence determinism is asserted by ``make obs-smoke``,
which runs a seeded chaos plan twice.)

The THREADING these recoveries depend on — producers submitting (and, with
admission armed, retrying and counting faults) concurrently with the
dispatcher's rollback/retry machinery — rides lock invariants that are now
statically checked by ``make analyze``'s concurrency plane (ISSUE 14,
``analysis/rules/locks.py``): the state lock guards the carried
state/replay-cursor/quarantine, every cross-thread stats counter (including
the per-site fault counts this smoke's accounting asserts on) goes through
``EngineStats``'s locked ``record_*`` methods, the ladder lock nests the
state lock and never the reverse, and the pager mutates only under the
engine's state lock. A refactor that deletes one of those locks fails
``make analyze`` before this smoke can flake on a lost increment or a torn
ledger.

Writes the chaos engine's telemetry JSON (the fault block renders via
``tools/engine_report.py``) and prints one PASS line. Exits nonzero on any
violated claim.
"""
import json
import os
import sys
import tempfile
import time

import numpy as np

# --------------------------------------------------------- shared chaos plan
# The canonical seeded chaos scenario, shared with ``obs_smoke`` (whose
# determinism gate replays THE SAME plan twice — true by construction, not
# by copy): both smokes build traffic, injectors, and engine configs from
# these factories, so a plan change here moves both CI gates in lockstep.


def chaos_collection():
    """The served metric set of the canonical plan — part of the scenario:
    the determinism and parity claims quantify over exactly these metrics."""
    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection

    return MetricCollection([Accuracy(), MeanSquaredError()])


def make_checker():
    """``(check, failed)``: the smoke-failure harness both chaos-plan gates
    share — one ``FAIL:`` line per violated claim (the format CI greps),
    collected for the exit code. Fresh per call, so two in-process runs
    never inherit each other's failures."""
    failed: list = []

    def check(ok: bool, what: str) -> None:
        if not ok:
            failed.append(what)
            print(f"FAIL: {what}")

    return check, failed


def chaos_traffic():
    """``(clean, traffic)``: a dyadic-rational clean stream (every partial
    float sum exactly representable, so parity holds under ANY grouping or
    lowering) and the same stream with one poisoned NaN batch at cursor 2."""
    rng = np.random.RandomState(0)
    clean = [
        ((rng.randint(0, 65, size=n) / 64.0).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in (5, 17, 8, 32, 3, 12, 32, 9)
    ]
    poison = (np.asarray([np.nan, 0.25], np.float32), np.asarray([1, 0], np.int32))
    return clean, clean[:2] + [poison] + clean[2:]


def chaos_injectors():
    """Fresh occurrence-deterministic injectors, one per chaos phase:
    ``chaos`` (seed 7) drives the single-device sweep over 8 sites,
    ``snapshot_read`` (seed 11) the transient read fault under restore,
    ``merge`` (seed 13) the deferred boundary-merge failure,
    ``dispatcher_kill`` (seed 17) the fatal worker death, ``paging``
    (seed 19) the stream-shard pager's spill/fault-in transients,
    ``quant`` (seed 29) the at-rest codec's encode/decode transients
    (ISSUE 10 — both pure functions of their input, so a retry can never
    double-apply scales), and ``elastic`` (seed 37) the ISSUE 11 sites:
    an admission-check transient on the second submit, a TRANSIENT
    suspected shard loss on the third chunk (rollback + in-place retry;
    the non-transient loss with auto-reshard is ``make elastic-smoke``'s
    8-device claim), and reshard capture/restore transients under a manual
    ``reshard()``. ``windows`` (seed 41, ISSUE 13) fires the pane-rotation
    plan phase and the closing-pane drift evaluation transiently — both are
    pure plan reads ahead of the commit, so the retry must neither
    double-decay/double-clear a pane nor double-record a drift series
    (pinned against fault-free windowed twins)."""
    from metrics_tpu.engine import FaultInjector, FaultSpec

    return {
        "fleet": FaultInjector(
            seed=47,
            plan={
                # ISSUE 15: the first snapshot-cut barrier entry and the
                # first boundary fold fail transiently — both sites are
                # consulted BEFORE the collective dispatches, so the retry
                # re-enters the (degenerate, 1-host) collective cleanly and
                # nothing folds twice
                "fleet_barrier": FaultSpec(schedule=(0,)),
                "host_loss": FaultSpec(schedule=(0,)),
            },
        ),
        "windows": FaultInjector(
            seed=41,
            plan={
                # first rotation's plan and first drift evaluation fail
                # transiently; the plan/commit split re-runs both against
                # the untouched carry/detector
                "pane_rotate": FaultSpec(schedule=(0,)),
                "drift_eval": FaultSpec(schedule=(0,)),
            },
        ),
        "ewma": FaultInjector(
            seed=43, plan={"pane_rotate": FaultSpec(schedule=(0,))}
        ),
        "elastic": FaultInjector(
            seed=37,
            plan={
                "admission": FaultSpec(schedule=(1,)),
                "shard_loss": FaultSpec(schedule=(2,)),
                "reshard_snapshot": FaultSpec(schedule=(0,)),
                "reshard_restore": FaultSpec(schedule=(0,)),
            },
        ),
        "quant": FaultInjector(
            seed=29,
            plan={
                # first snapshot encode and first restore decode fail
                # transiently; both re-run from the same host-side input
                "quant_encode": FaultSpec(schedule=(0,)),
                "quant_decode": FaultSpec(schedule=(0,)),
            },
        ),
        "paging": FaultInjector(
            seed=19,
            plan={
                # first spill and second fault-in fail transiently: both
                # retry against untouched buffers (the pager commits its
                # bookkeeping only after the bytes moved), so the chaos
                # stream's results stay bit-identical to fault-free
                "page_out": FaultSpec(schedule=(0,)),
                "page_in": FaultSpec(schedule=(1,)),
            },
        ),
        "chaos": FaultInjector(
            seed=7,
            plan={
                # rate=1.0 degrades EVERY group to one batch — which is also
                # what makes every other site's occurrence index
                # deterministic under any producer/dispatcher interleaving
                "coalesce": FaultSpec(rate=1.0),
                "ingest": FaultSpec(schedule=(1,)),
                "compile": FaultSpec(schedule=(1,)),
                "step": FaultSpec(schedule=(3,)),
                "kernel": FaultSpec(schedule=(0,)),
                "watchdog": FaultSpec(schedule=(6,)),
                "snapshot_write": FaultSpec(schedule=(0,)),
                "snapshot_corrupt": FaultSpec(schedule=(2,)),  # the LAST good save
            },
        ),
        "snapshot_read": FaultInjector(seed=11, plan={"snapshot_read": FaultSpec(schedule=(0,))}),
        "merge": FaultInjector(seed=13, plan={"merge": FaultSpec(schedule=(0,))}),
        "dispatcher_kill": FaultInjector(
            seed=17,
            plan={"dispatcher_kill": FaultSpec(schedule=(0,), transient=False, fatal=True)},
        ),
    }


def chaos_engine_config(snapdir, injector, trace=None):
    """The sweep engine: coalescing, demotable kernel backend, NaN
    quarantine, snapshot cadence 2 with a keep-ring of 4."""
    from metrics_tpu.engine import EngineConfig, ScreenPolicy

    return EngineConfig(
        buckets=(8, 32),
        coalesce=8,
        kernel_backend="pallas_interpret",  # demotable; xla is the floor
        screen=ScreenPolicy(non_finite="quarantine"),
        snapshot_every=2,
        snapshot_dir=snapdir,
        snapshot_keep=4,
        fault_injector=injector,
        trace=trace,
    )


def resume_engine_config(snapdir, injector, trace=None):
    """The kill+restore engine: same buckets and screen, no cadence — it
    replays from whatever generation the fallback walk lands on.
    ``coalesce=1``: group composition must be occurrence-deterministic for
    obs_smoke's same-seed span-sequence gate, and unlike the sweep engine
    (whose rate=1.0 coalesce fault pins every group to one batch) nothing
    else here decouples grouping from producer/dispatcher timing."""
    from metrics_tpu.engine import EngineConfig, ScreenPolicy

    return EngineConfig(
        buckets=(8, 32),
        coalesce=1,
        screen=ScreenPolicy(non_finite="quarantine"),
        snapshot_dir=snapdir,
        fault_injector=injector,
        trace=trace,
    )


def deferred_engine_config(injector, trace=None):
    """Deferred-sync on a 1-device mesh — the boundary-merge retry phase.
    ``coalesce=1`` for the same span-sequence determinism reason as
    :func:`resume_engine_config`."""
    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import EngineConfig

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    return EngineConfig(
        buckets=(8, 32), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
        fault_injector=injector, trace=trace,
    )


def quant_engine_config(injector, snapshot_dir, trace=None):
    """The quantized/compressed state-at-rest probe: deferred sync on a
    1-device mesh with ``compress_payloads`` on, so every snapshot rides the
    q8 codec (``quant_encode``) and every restore decodes (``quant_decode``).
    ``coalesce=1`` for span-sequence determinism, like the other phases."""
    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import EngineConfig

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    return EngineConfig(
        buckets=(8, 32), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
        snapshot_dir=snapshot_dir, compress_payloads=True,
        fault_injector=injector, trace=trace,
    )


def elastic_engine_config(injector, trace=None):
    """The overload/elasticity probe (ISSUE 11): deferred sync on a 1-device
    mesh (the reshard and shard-loss sites only exist on a mesh) with a
    GENEROUS AdmissionPolicy — the admission site is consulted only when a
    policy is armed, and nothing ever rejects, so the chaos parity claim
    stays bit-exact. ``coalesce=1`` + flush-per-submit in the phases keep
    every site's occurrence index producer-timing-independent."""
    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import AdmissionPolicy, EngineConfig

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    return EngineConfig(
        buckets=(8, 32), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
        admission=AdmissionPolicy(rows_per_s=1e9, burst_rows=1e9),
        fault_injector=injector, trace=trace,
    )


def windowed_engine_config(injector, trace=None, window=None, drift=None):
    """The windowed-semantics chaos probe (ISSUE 13): a sliding pane ring
    with a wired drift detector — ``pane_rotate`` fires in the rotation's
    PLAN phase (the non-donated rotate program re-runs against the untouched
    carry) and ``drift_eval`` in the closing-pane read (re-read, recorded
    once). ``coalesce=1`` for span-sequence determinism like every phase."""
    from metrics_tpu.engine import DriftDetector, EngineConfig, WindowPolicy

    return EngineConfig(
        buckets=(8, 32), coalesce=1,
        window=window or WindowPolicy.sliding(n_panes=2, pane_batches=3),
        drift=drift or DriftDetector(threshold=0.05, up_after=1, down_after=1),
        fault_injector=injector, trace=trace,
    )


def ewma_engine_config(injector, trace=None):
    """The EWMA double-decay probe: a float-sum metric under an ewma window
    with a transient ``pane_rotate`` — the decayed result must stay
    BIT-identical to a fault-free ewma twin (one decay per rotation, ever)."""
    from metrics_tpu.engine import EngineConfig, WindowPolicy

    return EngineConfig(
        buckets=(8, 32), coalesce=1,
        window=WindowPolicy.ewma(alpha=0.5, pane_batches=3),
        fault_injector=injector, trace=trace,
    )


def kill_engine_config(injector, trace=None):
    """The dead-dispatcher probe: tiny bounded queue so the fatal exit fills
    it and ``submit(timeout=)`` must surface the sticky error."""
    from metrics_tpu.engine import EngineConfig

    return EngineConfig(buckets=(8,), max_queue=2, fault_injector=injector, trace=trace)


# stream-shard chaos scenario (ISSUE 9): S streams behind a resident cap
# small enough that the seeded Zipf stream MUST spill — page_out/page_in are
# real row movements, not no-ops — on a 1-device mesh (W=1 lowers the same
# routed paged-arena program the 8-device mesh compiles; `make streams-smoke`
# covers the multi-shard topology)
SSHARD_STREAMS = 6
SSHARD_RESIDENT = 2


def stream_shard_traffic():
    """Seeded Zipfian ``(stream_id, preds, target)`` stream — skewed ids are
    what makes the LRU meaningful (``engine/traffic.py``; uniform traffic
    cannot distinguish a pager from a thrash loop). Dyadic values keep every
    parity claim bit-exact under any routing/paging order."""
    from metrics_tpu.engine.traffic import zipf_traffic

    return zipf_traffic(SSHARD_STREAMS, 18, alpha=1.1, seed=23)


def stream_shard_engine_config(injector, trace=None, snapshot_dir=None):
    """The paged stream-sharded chaos engine's config: 1-device mesh,
    deferred sync (the routed step's contract), ``coalesce=1`` for the same
    span-sequence determinism reason as :func:`resume_engine_config` —
    page-site occurrence indices must not depend on producer timing."""
    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import EngineConfig

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    return EngineConfig(
        buckets=(8, 32), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
        fault_injector=injector, trace=trace, snapshot_dir=snapshot_dir,
    )


FLEET_STREAMS = 6


def fleet_chaos_config(injector, snapdir, trace=None):
    """The degenerate-fleet chaos probe (ISSUE 15): a 1-host FleetEngine —
    the SAME boundary programs (merge/result/barrier, world 1) the
    two-process harness compiles, minus the second process, so
    ``host_loss``/``fleet_barrier`` transients exercise the real retry path
    tier-1-cheap. ``coalesce=1`` for span-sequence determinism like every
    other phase."""
    from metrics_tpu.engine import EngineConfig
    from metrics_tpu.engine.fleet import FleetConfig

    return FleetConfig(
        num_streams=FLEET_STREAMS,
        engine=EngineConfig(
            buckets=(8, 32), coalesce=1, fault_injector=injector, trace=trace
        ),
        snapshot_dir=snapdir,
    )


def run_fleet_phase(injector, snapdir, trace=None):
    """Serve the seeded Zipfian stream on a 1-host fleet, cut once (the
    barrier entry fails transiently and retries), then read every stream's
    result (the first boundary fold fails transiently and retries).
    Returns ``{sid: {metric: np.ndarray}}`` for the parity pin."""
    import numpy as np

    from metrics_tpu.engine.fleet import FleetEngine

    fleet = FleetEngine(chaos_collection(), fleet_chaos_config(injector, snapdir, trace=trace))
    with fleet:
        for sid, p, t in zipf_fleet_traffic():
            fleet.ingest(sid, p, t)
        fleet.fleet_snapshot()
        return {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in fleet.results().items()
        }


def zipf_fleet_traffic():
    """The fleet phase's seeded stream (dyadic values — parity is bit-exact
    under any grouping)."""
    from metrics_tpu.engine.traffic import zipf_traffic

    return zipf_traffic(FLEET_STREAMS, 12, alpha=1.1, seed=31)


def main(out_path: str = "out/chaos_telemetry.json") -> int:
    # sidecar artifacts default under the gitignored out/ dir — telemetry is
    # regenerated by every smoke run and must never land in the repo root
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import (
        BackpressureTimeout,
        EngineConfig,
        EngineDispatchError,
        MultiStreamEngine,
        StreamingEngine,
        TraceRecorder,
    )
    from metrics_tpu.engine.faults import FAULT_SITES

    # ONE flight recorder across the deterministic chaos engines: the
    # exported trace must show every injected firing as a span event and
    # every megabatch linking its submit spans. The dead-dispatcher section
    # gets its OWN recorder — its probe submits are never absorbed (the
    # dispatcher is dead), which is correct behavior there but would
    # (rightly) fail the link validator on the exported document.
    _check, _failed = make_checker()
    rec = TraceRecorder(capacity=1 << 15)
    rec_kill = TraceRecorder(capacity=4096)

    collection = chaos_collection

    clean, traffic = chaos_traffic()  # poison at stream cursor 2
    injs = chaos_injectors()

    # -------------------------------------------------------- fault-free truth
    ref = StreamingEngine(collection(), EngineConfig(buckets=(8, 32)))
    with ref:
        for b in clean:
            ref.submit(*b)
        want = {k: np.asarray(v) for k, v in ref.result().items()}

    fired_sites = set()

    # ------------------------------------------------- chaos run, single device
    snapdir = tempfile.mkdtemp(prefix="metrics_tpu_chaos_")
    inj = injs["chaos"]
    engine = StreamingEngine(collection(), chaos_engine_config(snapdir, inj, trace=rec))
    with engine:
        for b in traffic:
            engine.submit(*b)
        got = {k: np.asarray(v) for k, v in engine.result().items()}
    for k in want:
        _check(np.array_equal(got[k], want[k]), f"chaos parity: {k} {got[k]} != {want[k]}")
    st = engine.stats
    _check(st.rollbacks >= 3, f"expected >=3 pre-step rollbacks, saw {st.rollbacks}")
    _check(st.retries >= 3, f"expected >=3 retries, saw {st.retries}")
    _check(st.kernel_demotions == 1, f"expected 1 kernel demotion, saw {st.kernel_demotions}")
    _check(engine._kernel_backend == "xla", "engine did not demote to the xla backend")
    _check(st.watchdog_timeouts == 1, f"expected 1 watchdog expiry, saw {st.watchdog_timeouts}")
    # the coalesce site is consulted only when the drain limit exceeds 1 —
    # snapshot boundaries cap it to 1 on alternating groups at this cadence
    _check(st.coalesce_degraded >= 3, f"coalesce degradation barely fired: {st.coalesce_degraded}")
    _check(st.snapshot_failures == 1, f"expected 1 contained snapshot failure, saw {st.snapshot_failures}")
    # quarantine ledger accounts for EXACTLY the poisoned batch
    q = engine.quarantine()
    _check(
        st.quarantined_batches == 1 and st.quarantined_rows == 2,
        f"quarantine ledger off: {st.quarantined_batches} batches / {st.quarantined_rows} rows",
    )
    _check(
        len(q) == 1 and q[0].cursor == 2 and q[0].rows == 2 and "non-finite" in q[0].reason,
        f"quarantine record wrong: {[(r.cursor, r.rows, r.reason) for r in q]}",
    )
    # the arena is not torn: the carried buffers still match the layout
    layout = engine.arena_layout
    _check(
        layout is not None and layout.matches(engine._state),
        "carried arena does not match its layout after chaos",
    )
    engine.export_telemetry(out_path)
    fired_sites |= set(inj.fired)

    # --------------------------------- kill + restore past the corrupt LATEST
    del engine
    read_inj = injs["snapshot_read"]
    resumed = StreamingEngine(collection(), resume_engine_config(snapdir, read_inj, trace=rec))
    meta = resumed.restore()
    _check(
        int(meta.get("generations_skipped", 0)) == 1,
        f"restore should skip exactly the corrupted LATEST, skipped {meta.get('generations_skipped')}",
    )
    _check(resumed.stats.snapshot_fallbacks == 1, "snapshot fallback not counted")
    _check(resumed.stats.retries == 1, "transient snapshot_read was not retried")
    # saves fired at cursors 2 (write-failed), 4, 6, 8; the @8 payload was
    # bit-flipped after its save — fallback must land on the @6 generation
    cursor = int(meta["batches_done"])
    _check(cursor == 6, f"fallback generation cursor should be 6, got {cursor}")
    with resumed:
        for b in traffic[cursor:]:
            resumed.submit(*b)
        replayed = {k: np.asarray(v) for k, v in resumed.result().items()}
    for k in want:
        _check(
            np.array_equal(replayed[k], want[k]),
            f"replay-after-fallback parity: {k} {replayed[k]} != {want[k]}",
        )
    fired_sites |= set(read_inj.fired)

    # ------------------------------------- deferred boundary merge, 1-dev mesh
    merge_inj = injs["merge"]
    deferred = StreamingEngine(collection(), deferred_engine_config(merge_inj, trace=rec))
    with deferred:
        for b in clean:
            deferred.submit(*b)
        got_d = {k: np.asarray(v) for k, v in deferred.result().items()}
    for k in want:
        _check(
            np.array_equal(got_d[k], want[k]),
            f"deferred merge-retry parity: {k} {got_d[k]} != {want[k]}",
        )
    _check(merge_inj.fired.get("merge", 0) == 1, "merge fault did not fire")
    _check(deferred.stats.retries == 1, "merge fault was not retried")
    fired_sites |= set(merge_inj.fired)

    # ------------------- quantized state-at-rest codec under chaos (ISSUE 10)
    # The at-rest codec's fault sites are pure-input boundaries: an injected
    # quant_encode transient on the snapshot path and a quant_decode
    # transient under restore both retry from the SAME host-side values —
    # scales are never applied twice. Under the EXACT policy the compressed
    # snapshot wraps nothing, so the kill/resume replay is BIT-identical to
    # the fault-free run; a quantized-policy twin (same traffic, no faults)
    # then lands within the codec's bounded error.
    quant_inj = injs["quant"]
    q_dir = tempfile.mkdtemp(prefix="metrics_tpu_quant_")
    q_cut = 4
    qeng = StreamingEngine(collection(), quant_engine_config(quant_inj, q_dir, trace=rec))
    with qeng:
        for b in clean[:q_cut]:
            qeng.submit(*b)
        qeng.snapshot()  # quant_encode fires (occurrence 0) and retries
    _check(
        quant_inj.fired.get("quant_encode", 0) == 1,
        f"quant_encode did not fire: {dict(quant_inj.fired)}",
    )
    _check(qeng.stats.retries >= 1, "quant_encode transient was not retried")
    del qeng
    qres = StreamingEngine(collection(), quant_engine_config(quant_inj, q_dir, trace=rec))
    meta_q = qres.restore()  # quant_decode fires (occurrence 0) and retries
    _check(
        quant_inj.fired.get("quant_decode", 0) == 1,
        f"quant_decode did not fire: {dict(quant_inj.fired)}",
    )
    _check(
        str(meta_q.get("codec", "")) != "" and int(meta_q["batches_done"]) == q_cut,
        f"compressed snapshot meta wrong: codec={meta_q.get('codec')!r} "
        f"cursor={meta_q.get('batches_done')}",
    )
    with qres:
        for b in clean[q_cut:]:
            qres.submit(*b)
        got_q = {k: np.asarray(v) for k, v in qres.result().items()}
    for k in want:
        _check(
            np.array_equal(got_q[k], want[k]),
            f"exact-policy compressed kill/resume not bit-identical: {k} {got_q[k]} != {want[k]}",
        )
    # bounded-error twin: the same cycle with MSE's float accumulator quantized
    q2_dir = tempfile.mkdtemp(prefix="metrics_tpu_quant8_")
    qcoll = collection().set_sync_precision("q8_block")
    q8 = StreamingEngine(qcoll, quant_engine_config(None, q2_dir, trace=rec))
    with q8:
        for b in clean[:q_cut]:
            q8.submit(*b)
        q8.snapshot()
    del q8
    q8b = StreamingEngine(
        collection().set_sync_precision("q8_block"), quant_engine_config(None, q2_dir, trace=rec)
    )
    q8b.restore()
    with q8b:
        for b in clean[q_cut:]:
            q8b.submit(*b)
        got_q8 = {k: np.asarray(v) for k, v in q8b.result().items()}
    _check(
        np.array_equal(got_q8["Accuracy"], want["Accuracy"]),
        "quantized policy broke a count-backed metric (Accuracy must stay bit-exact)",
    )
    _check(
        bool(np.allclose(got_q8["MeanSquaredError"], want["MeanSquaredError"], rtol=1e-2)),
        f"quantized kill/resume outside bounds: MSE {got_q8['MeanSquaredError']} "
        f"vs {want['MeanSquaredError']}",
    )
    fired_sites |= set(quant_inj.fired)

    # ------------- elastic serving: admission + live reshard under chaos
    # (ISSUE 11) the four self-defense sites fire transiently on a 1-device
    # deferred engine and everything retries to a BIT-identical result: the
    # admission check re-runs (pure in its input), a suspected shard loss
    # rolls back and retries in place, and a manual reshard's capture and
    # restore both survive an injected transient — the engine that comes out
    # of reshard() serves the rest of the stream exactly.
    elastic_inj = injs["elastic"]
    ee = StreamingEngine(collection(), elastic_engine_config(elastic_inj, trace=rec))
    with ee:
        for b in clean[:3]:
            ee.submit(*b)
            ee.flush()  # occurrence indices stay producer-timing-independent
        info = ee.reshard(world=1)  # reshard_snapshot/_restore fire + retry
        for b in clean[3:]:
            ee.submit(*b)
            ee.flush()
        got_el = {k: np.asarray(v) for k, v in ee.result().items()}
    for k in want:
        _check(
            np.array_equal(got_el[k], want[k]),
            f"elastic chaos parity: {k} {got_el[k]} != {want[k]}",
        )
    _check(
        all(
            elastic_inj.fired.get(site, 0) == 1
            for site in ("admission", "shard_loss", "reshard_snapshot", "reshard_restore")
        ),
        f"elastic sites did not all fire exactly once: {dict(elastic_inj.fired)}",
    )
    _check(
        ee.stats.reshards == 1 and info["to_world"] == 1,
        f"reshard accounting wrong: {ee.stats.reshard_summary()} / {info}",
    )
    adm = ee.stats.admission_summary()
    _check(
        adm is not None and sum(adm["admitted_by_priority"].values()) == len(clean),
        f"admission block did not admit every batch: {adm}",
    )
    fired_sites |= set(elastic_inj.fired)

    # ----------------- windowed semantics: rotation + drift eval under chaos
    # (ISSUE 13) a sliding pane ring with a wired drift detector: the first
    # rotation's PLAN and the first closing-pane drift read both fail
    # transiently — the plan/commit split retries them against the untouched
    # carry/detector, so the windowed result AND the per-pane drift history
    # must be BIT-identical to a fault-free windowed twin (a double-cleared
    # pane or a double-recorded series would diverge both).
    from metrics_tpu.engine import DriftDetector

    win_inj = injs["windows"]
    det_chaos = DriftDetector(threshold=0.05, up_after=1, down_after=1)
    we = StreamingEngine(
        collection(), windowed_engine_config(win_inj, trace=rec, drift=det_chaos)
    )
    with we:
        for b in clean:
            we.submit(*b)
            we.flush()  # per-batch flush: site occurrence indices stay timing-free
        got_w = {k: np.asarray(v) for k, v in we.result().items()}
    det_ref = DriftDetector(threshold=0.05, up_after=1, down_after=1)
    wref = StreamingEngine(collection(), windowed_engine_config(None, drift=det_ref))
    with wref:
        for b in clean:
            wref.submit(*b)
            wref.flush()
        want_w = {k: np.asarray(v) for k, v in wref.result().items()}
    for k in want_w:
        _check(
            np.array_equal(got_w[k], want_w[k]),
            f"windowed chaos parity: {k} {got_w[k]} != {want_w[k]}",
        )
    _check(
        win_inj.fired.get("pane_rotate", 0) == 1
        and win_inj.fired.get("drift_eval", 0) == 1,
        f"window sites did not fire: {dict(win_inj.fired)}",
    )
    _check(we.stats.retries >= 2, f"window faults were not retried: {we.stats.retries}")
    for name in ("Accuracy", "MeanSquaredError"):
        _check(
            det_chaos.history(name=name) == det_ref.history(name=name),
            f"drift history diverged under retry for {name}: "
            f"{det_chaos.history(name=name)} != {det_ref.history(name=name)}",
        )
    _check(
        det_chaos.evals == det_ref.evals and we.rotations == wref.rotations,
        f"retried drift eval double-recorded: {det_chaos.evals} vs {det_ref.evals} "
        f"(rotations {we.rotations} vs {wref.rotations})",
    )
    fired_sites |= set(win_inj.fired)

    # EWMA double-decay proof: a float-sum metric under ewma(alpha=0.5) with
    # a transient pane_rotate — dyadic values + dyadic decay make the result
    # exactly representable, so one extra (double) decay would flip bits
    from metrics_tpu import MeanMetric

    ewma_inj = injs["ewma"]
    em = StreamingEngine(MeanMetric(), ewma_engine_config(ewma_inj, trace=rec))
    with em:
        for p, _t in clean:
            em.submit(p)
            em.flush()
        got_e = np.asarray(em.result())
    eref = StreamingEngine(MeanMetric(), ewma_engine_config(None))
    with eref:
        for p, _t in clean:
            eref.submit(p)
            eref.flush()
        want_e = np.asarray(eref.result())
    _check(
        np.array_equal(got_e, want_e),
        f"ewma retried rotation double-decayed: {got_e} != {want_e}",
    )
    _check(
        ewma_inj.fired.get("pane_rotate", 0) == 1
        and em.stats.ewma_decays == eref.stats.ewma_decays > 0,
        f"ewma rotation accounting wrong: {dict(ewma_inj.fired)}, "
        f"{em.stats.ewma_decays} vs {eref.stats.ewma_decays}",
    )
    fired_sites |= set(ewma_inj.fired)

    # --------------------- fleet boundaries: barrier + host-loss transients
    # (ISSUE 15) a degenerate 1-host fleet under the chaos plan: the first
    # snapshot-cut barrier entry and the first cross-host fold both fail
    # transiently and retry — both sites fire BEFORE their collective, so a
    # retry re-enters it cleanly and every per-stream result stays
    # bit-identical to a fault-free fleet twin
    fleet_inj = injs["fleet"]
    fleet_snapdir = tempfile.mkdtemp(prefix="metrics_tpu_chaos_fleet_")
    got_f = run_fleet_phase(fleet_inj, fleet_snapdir, trace=rec)
    want_f = run_fleet_phase(None, tempfile.mkdtemp(prefix="metrics_tpu_chaos_fleet_ref_"))
    for sid in want_f:
        for k in want_f[sid]:
            _check(
                np.array_equal(got_f[sid][k], want_f[sid][k], equal_nan=True),
                f"fleet chaos parity: stream {sid} {k} {got_f[sid][k]} != {want_f[sid][k]}",
            )
    _check(
        fleet_inj.fired.get("fleet_barrier", 0) == 1
        and fleet_inj.fired.get("host_loss", 0) == 1,
        f"fleet sites did not fire: {dict(fleet_inj.fired)}",
    )
    fired_sites |= set(fleet_inj.fired)

    # ------------------- stream-sharded paging: spill/fault-in under chaos
    # (ISSUE 9) a resident-capped stream-sharded engine under seeded Zipfian
    # traffic: page_out/page_in transients fire mid-stream and retry against
    # untouched buffers; every per-stream result stays bit-identical to an
    # UNSHARDED UNPAGED oracle; a mid-stream snapshot (taken while rows were
    # spilled) then backs BOTH sides of the stream-shard restore matrix —
    # same-world verbatim, and merged into a single-device engine — each with
    # exact replay from the snapshot cursor.
    sstraffic = stream_shard_traffic()
    oracle = MultiStreamEngine(collection(), SSHARD_STREAMS, EngineConfig(buckets=(8, 32)))
    with oracle:
        for sid, p, t in sstraffic:
            oracle.submit(sid, p, t)
        want_ss = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in oracle.results().items()
        }

    def _ss_parity(tag, got):
        for sid in want_ss:
            for k in want_ss[sid]:
                _check(
                    np.array_equal(got[sid][k], want_ss[sid][k], equal_nan=True),
                    f"{tag}: stream {sid} {k} {got[sid][k]} != {want_ss[sid][k]}",
                )

    page_inj = injs["paging"]
    ss_dir = tempfile.mkdtemp(prefix="metrics_tpu_sshard_")
    paged = MultiStreamEngine(
        collection(), SSHARD_STREAMS,
        stream_shard_engine_config(page_inj, trace=rec, snapshot_dir=ss_dir),
        stream_shard=True, resident_streams=SSHARD_RESIDENT,
    )
    ss_cut = 12
    with paged:
        for sid, p, t in sstraffic[:ss_cut]:
            paged.submit(sid, p, t)
        paged.snapshot()  # mid-stream, with rows spilled: paged rows MUST be covered
        spilled_at_snap = paged._pager.spilled_count()
        for sid, p, t in sstraffic[ss_cut:]:
            paged.submit(sid, p, t)
        got_ss = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in paged.results().items()
        }
    _ss_parity("stream-shard chaos parity", got_ss)
    _check(
        page_inj.fired.get("page_out", 0) == 1 and page_inj.fired.get("page_in", 0) == 1,
        f"paging fault sites did not fire: {dict(page_inj.fired)}",
    )
    _check(paged.stats.retries >= 2, f"paging faults were not retried: {paged.stats.retries}")
    _check(
        paged.stats.page_outs >= 1 and spilled_at_snap >= 1,
        f"the resident cap never bound (page_outs={paged.stats.page_outs}, "
        f"spilled at snapshot={spilled_at_snap})",
    )
    _check(
        {k: tuple(v.shape) for k, v in paged._state.items()}
        == {k: (1, SSHARD_RESIDENT, n) for k, n in paged._layout.buffer_sizes().items()},
        "paged arena buffers are not the (world, resident, n) per-shard form",
    )
    fired_sites |= set(page_inj.fired)

    del paged
    same_world = MultiStreamEngine(
        collection(), SSHARD_STREAMS,
        stream_shard_engine_config(None, snapshot_dir=ss_dir),
        stream_shard=True, resident_streams=SSHARD_RESIDENT,
    )
    meta_ss = same_world.restore()
    _check(
        int(meta_ss["batches_done"]) == ss_cut,
        f"stream-shard snapshot cursor should be {ss_cut}, got {meta_ss['batches_done']}",
    )
    with same_world:
        for sid, p, t in sstraffic[ss_cut:]:
            same_world.submit(sid, p, t)
        got_same = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in same_world.results().items()
        }
    _ss_parity("same-world restore replay past a spill", got_same)

    merged_engine = MultiStreamEngine(
        collection(), SSHARD_STREAMS, EngineConfig(buckets=(8, 32), snapshot_dir=ss_dir)
    )
    merged_engine.restore()
    with merged_engine:
        for sid, p, t in sstraffic[ss_cut:]:
            merged_engine.submit(sid, p, t)
        got_merged = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in merged_engine.results().items()
        }
    _ss_parity("single-device merged restore replay", got_merged)

    # the matrix is EXACT: a non-sharded snapshot has no residency provenance
    # a sharded engine could seat — it must refuse, not guess
    plain_dir = tempfile.mkdtemp(prefix="metrics_tpu_sshard_plain_")
    plain = MultiStreamEngine(
        collection(), SSHARD_STREAMS, EngineConfig(buckets=(8, 32), snapshot_dir=plain_dir)
    )
    with plain:
        plain.submit(*sstraffic[0])
        plain.snapshot()
    refuser = MultiStreamEngine(
        collection(), SSHARD_STREAMS,
        stream_shard_engine_config(None, snapshot_dir=plain_dir),
        stream_shard=True, resident_streams=SSHARD_RESIDENT,
    )
    from metrics_tpu.utils.exceptions import MetricsTPUUserError

    try:
        refuser.restore()
        _check(False, "plain snapshot restored into a stream-sharded engine (must refuse)")
    except MetricsTPUUserError as e:
        # the refusal must be the TYPED, explanatory one — a crash elsewhere
        # in the restore path is a bug, not a refusal
        _check("stream-sharded" in str(e), f"refusal message unhelpful: {e}")

    # --------------------------- dead dispatcher: sticky submit, reset re-arms
    kill_inj = injs["dispatcher_kill"]
    dead = StreamingEngine(Accuracy(), kill_engine_config(kill_inj, trace=rec_kill))
    p, t = np.asarray([0.9, 0.2], np.float32), np.asarray([1, 0], np.int32)
    dead.start()
    dead.submit(p, t)
    deadline = time.monotonic() + 10.0
    sticky = None
    while time.monotonic() < deadline and sticky is None:
        try:
            dead.submit(p, t, timeout=0.2)
        except EngineDispatchError as e:
            sticky = e
        except BackpressureTimeout:
            continue  # the kill has not landed yet; keep probing
    _check(
        sticky is not None and "dispatcher_kill" in str(sticky),
        "submit(timeout=) did not surface the dead dispatcher's sticky error",
    )
    dead.reset()  # drains the dead queue, clears the error, re-arms
    dead.submit(p, t)
    _check(float(dead.result()) == 1.0, "engine did not serve after dispatcher-death reset")
    dead.stop()
    fired_sites |= set(kill_inj.fired)

    # ----------------------- trace-time kernel-dispatch fault: silent fallback
    import jax.numpy as jnp

    from metrics_tpu.ops.kernels import fold_rows_masked, kernel_fault_scope, use_backend

    calls = []

    def hook(kernel):
        calls.append(kernel)
        raise RuntimeError("injected trace-time kernel failure")

    state = jnp.zeros((4,), jnp.float32)
    rows = jnp.asarray(np.random.RandomState(1).randint(0, 65, size=(6, 4)) / 64.0, jnp.float32)
    mask = jnp.asarray([True] * 5 + [False])
    want_fold = np.asarray(fold_rows_masked(state, rows, mask, "sum", backend="xla"))
    with kernel_fault_scope(hook), use_backend("pallas"):
        got_fold = np.asarray(fold_rows_masked(state, rows, mask, "sum"))
    _check(bool(calls), "trace-time kernel fault hook never ran")
    _check(
        np.array_equal(got_fold, want_fold),
        "kernel-dispatch fault did not fall back to the XLA path",
    )

    # ------------------------------------------------------- sweep completeness
    missing = set(FAULT_SITES) - fired_sites
    _check(not missing, f"injection points never fired: {sorted(missing)}")

    # ------------------------------- flight recorder: spans, links, Perfetto
    # every injected firing must ALSO be a span event in the recorded trace,
    # the exported document must be schema-valid Perfetto JSON, and every
    # megabatch span must link exactly the submit spans it absorbed
    span_sites = set(rec.fault_sites()) | set(rec_kill.fault_sites())
    missing_spans = set(FAULT_SITES) - span_sites
    _check(not missing_spans, f"fault sites without span events: {sorted(missing_spans)}")
    _check(rec.dropped == 0, f"trace ring dropped {rec.dropped} spans mid-chaos")
    trace_path = os.path.join(os.path.dirname(out_path) or "out", "trace_chaos.json")
    rec.export(trace_path)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", "tools"))
    import trace_export

    with open(trace_path) as f:
        trace_doc = json.load(f)
    trace_errs = trace_export.validate_chrome_trace(trace_doc) + trace_export.validate_links(
        trace_doc
    )
    _check(not trace_errs, f"chaos trace invalid: {trace_errs[:3]}")

    if _failed:
        return 1
    print(
        "chaos-smoke PASS: "
        f"{len(FAULT_SITES)} injection points fired; chaos result bit-identical "
        f"to fault-free run ({len(clean)} batches; 1 poisoned batch quarantined, "
        f"ledger exact); rollbacks={st.rollbacks}, retries={st.retries}, "
        f"demotions={st.kernel_demotions}, watchdog={st.watchdog_timeouts}; "
        "restore fell back past the corrupted LATEST with exact replay; "
        f"all {len(FAULT_SITES)} sites present as trace span events, Perfetto "
        f"export valid with megabatch->submit links -> {trace_path}; "
        f"telemetry -> {out_path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
