"""Chaos smoke: ``python -m metrics_tpu.engine.chaos_smoke [telemetry.json]``.

The CI-shaped proof of the fault-tolerance contract (ISSUE 6), in seconds on
one CPU device (``make chaos-smoke``): a SEEDED fault sweep fires every
injection point in ``engine/faults.py::FAULT_SITES`` at least once, and the
engine recovers from all of it to a ``result()`` BIT-IDENTICAL to a
fault-free run on the same traffic:

1. **Transactional steps** — injected ingest/compile/step/watchdog faults
   roll back onto the pre-step shadow and retry; the arena is never torn
   (layout integrity asserted after the chaos stream).
2. **Quarantine** — a poisoned NaN batch rides the stream; the screen policy
   dead-letters it (it never reaches a compiled step), the ledger accounts
   for exactly its cursor and rows, and parity holds with the quarantined
   batch excluded by construction (the fault-free oracle never sees it).
3. **Graceful degradation** — a kernel-site fault demotes the engine
   ``pallas_interpret → xla`` mid-stream (bit-exact for this traffic: int
   counters and dyadic float sums); a coalesce fault (rate=1.0, also what
   pins every group to one batch so occurrence schedules are deterministic
   under ANY queue timing) degrades megabatching to singleton groups; a
   trace-time ``kernel_fault_scope`` hook proves the dispatcher's per-call
   silent fallback.
4. **Snapshot integrity** — one periodic snapshot write FAILS (contained:
   serving continues, counted), the LAST snapshot is bit-flipped on disk
   after a successful save, and the post-kill ``restore()`` falls back past
   the corrupt LATEST to the previous generation; replaying from its older
   cursor reproduces the uninterrupted result exactly.
5. **Deferred boundary merge** — on a 1-device mesh in deferred mode an
   injected merge fault retries behind ``result()`` (the merge is a
   non-donated read; the carried state stays consistent).
6. **Dead dispatcher** — a fatal fault kills the dispatcher thread outright;
   ``submit(timeout=)`` surfaces the sticky error instead of deadlocking,
   and ``reset()`` drains the dead queue and re-arms. A transient
   ``snapshot_read`` fault retries inside ``restore()``.

Writes the chaos engine's telemetry JSON (the fault block renders via
``tools/engine_report.py``) and prints one PASS line. Exits nonzero on any
violated claim.
"""
import os
import sys
import tempfile
import time

import numpy as np

_FAILED = []


def _check(ok: bool, what: str) -> None:
    if not ok:
        _FAILED.append(what)
        print(f"FAIL: {what}")


def main(out_path: str = "chaos_telemetry.json") -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import (
        BackpressureTimeout,
        EngineConfig,
        EngineDispatchError,
        FaultInjector,
        FaultSpec,
        ScreenPolicy,
        StreamingEngine,
    )
    from metrics_tpu.engine.faults import FAULT_SITES

    def collection():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    # dyadic-rational traffic: every partial float sum is exactly
    # representable, so parity across ANY grouping/lowering is bit-exact
    rng = np.random.RandomState(0)
    clean = [
        ((rng.randint(0, 65, size=n) / 64.0).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in (5, 17, 8, 32, 3, 12, 32, 9)
    ]
    poison = (np.asarray([np.nan, 0.25], np.float32), np.asarray([1, 0], np.int32))
    traffic = clean[:2] + [poison] + clean[2:]  # poison at stream cursor 2

    # -------------------------------------------------------- fault-free truth
    ref = StreamingEngine(collection(), EngineConfig(buckets=(8, 32)))
    with ref:
        for b in clean:
            ref.submit(*b)
        want = {k: np.asarray(v) for k, v in ref.result().items()}

    fired_sites = set()

    # ------------------------------------------------- chaos run, single device
    snapdir = tempfile.mkdtemp(prefix="metrics_tpu_chaos_")
    inj = FaultInjector(
        seed=7,
        plan={
            # rate=1.0 degrades EVERY group to one batch — which is also what
            # makes every other site's occurrence index deterministic under
            # any producer/dispatcher interleaving
            "coalesce": FaultSpec(rate=1.0),
            "ingest": FaultSpec(schedule=(1,)),
            "compile": FaultSpec(schedule=(1,)),
            "step": FaultSpec(schedule=(3,)),
            "kernel": FaultSpec(schedule=(0,)),
            "watchdog": FaultSpec(schedule=(6,)),
            "snapshot_write": FaultSpec(schedule=(0,)),
            "snapshot_corrupt": FaultSpec(schedule=(2,)),  # the LAST good save
        },
    )
    engine = StreamingEngine(
        collection(),
        EngineConfig(
            buckets=(8, 32),
            coalesce=8,
            kernel_backend="pallas_interpret",  # demotable; xla is the floor
            screen=ScreenPolicy(non_finite="quarantine"),
            snapshot_every=2,
            snapshot_dir=snapdir,
            snapshot_keep=4,
            fault_injector=inj,
        ),
    )
    with engine:
        for b in traffic:
            engine.submit(*b)
        got = {k: np.asarray(v) for k, v in engine.result().items()}
    for k in want:
        _check(np.array_equal(got[k], want[k]), f"chaos parity: {k} {got[k]} != {want[k]}")
    st = engine.stats
    _check(st.rollbacks >= 3, f"expected >=3 pre-step rollbacks, saw {st.rollbacks}")
    _check(st.retries >= 3, f"expected >=3 retries, saw {st.retries}")
    _check(st.kernel_demotions == 1, f"expected 1 kernel demotion, saw {st.kernel_demotions}")
    _check(engine._kernel_backend == "xla", "engine did not demote to the xla backend")
    _check(st.watchdog_timeouts == 1, f"expected 1 watchdog expiry, saw {st.watchdog_timeouts}")
    # the coalesce site is consulted only when the drain limit exceeds 1 —
    # snapshot boundaries cap it to 1 on alternating groups at this cadence
    _check(st.coalesce_degraded >= 3, f"coalesce degradation barely fired: {st.coalesce_degraded}")
    _check(st.snapshot_failures == 1, f"expected 1 contained snapshot failure, saw {st.snapshot_failures}")
    # quarantine ledger accounts for EXACTLY the poisoned batch
    q = engine.quarantine()
    _check(
        st.quarantined_batches == 1 and st.quarantined_rows == 2,
        f"quarantine ledger off: {st.quarantined_batches} batches / {st.quarantined_rows} rows",
    )
    _check(
        len(q) == 1 and q[0].cursor == 2 and q[0].rows == 2 and "non-finite" in q[0].reason,
        f"quarantine record wrong: {[(r.cursor, r.rows, r.reason) for r in q]}",
    )
    # the arena is not torn: the carried buffers still match the layout
    layout = engine.arena_layout
    _check(
        layout is not None and layout.matches(engine._state),
        "carried arena does not match its layout after chaos",
    )
    engine.export_telemetry(out_path)
    fired_sites |= set(inj.fired)

    # --------------------------------- kill + restore past the corrupt LATEST
    del engine
    read_inj = FaultInjector(seed=11, plan={"snapshot_read": FaultSpec(schedule=(0,))})
    resumed = StreamingEngine(
        collection(),
        EngineConfig(
            buckets=(8, 32),
            screen=ScreenPolicy(non_finite="quarantine"),
            snapshot_dir=snapdir,
            fault_injector=read_inj,
        ),
    )
    meta = resumed.restore()
    _check(
        int(meta.get("generations_skipped", 0)) == 1,
        f"restore should skip exactly the corrupted LATEST, skipped {meta.get('generations_skipped')}",
    )
    _check(resumed.stats.snapshot_fallbacks == 1, "snapshot fallback not counted")
    _check(resumed.stats.retries == 1, "transient snapshot_read was not retried")
    # saves fired at cursors 2 (write-failed), 4, 6, 8; the @8 payload was
    # bit-flipped after its save — fallback must land on the @6 generation
    cursor = int(meta["batches_done"])
    _check(cursor == 6, f"fallback generation cursor should be 6, got {cursor}")
    with resumed:
        for b in traffic[cursor:]:
            resumed.submit(*b)
        replayed = {k: np.asarray(v) for k, v in resumed.result().items()}
    for k in want:
        _check(
            np.array_equal(replayed[k], want[k]),
            f"replay-after-fallback parity: {k} {replayed[k]} != {want[k]}",
        )
    fired_sites |= set(read_inj.fired)

    # ------------------------------------- deferred boundary merge, 1-dev mesh
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    merge_inj = FaultInjector(seed=13, plan={"merge": FaultSpec(schedule=(0,))})
    deferred = StreamingEngine(
        collection(),
        EngineConfig(
            buckets=(8, 32), mesh=mesh, axis="dp", mesh_sync="deferred",
            fault_injector=merge_inj,
        ),
    )
    with deferred:
        for b in clean:
            deferred.submit(*b)
        got_d = {k: np.asarray(v) for k, v in deferred.result().items()}
    for k in want:
        _check(
            np.array_equal(got_d[k], want[k]),
            f"deferred merge-retry parity: {k} {got_d[k]} != {want[k]}",
        )
    _check(merge_inj.fired.get("merge", 0) == 1, "merge fault did not fire")
    _check(deferred.stats.retries == 1, "merge fault was not retried")
    fired_sites |= set(merge_inj.fired)

    # --------------------------- dead dispatcher: sticky submit, reset re-arms
    kill_inj = FaultInjector(
        seed=17, plan={"dispatcher_kill": FaultSpec(schedule=(0,), transient=False, fatal=True)}
    )
    dead = StreamingEngine(
        Accuracy(), EngineConfig(buckets=(8,), max_queue=2, fault_injector=kill_inj)
    )
    p, t = np.asarray([0.9, 0.2], np.float32), np.asarray([1, 0], np.int32)
    dead.start()
    dead.submit(p, t)
    deadline = time.monotonic() + 10.0
    sticky = None
    while time.monotonic() < deadline and sticky is None:
        try:
            dead.submit(p, t, timeout=0.2)
        except EngineDispatchError as e:
            sticky = e
        except BackpressureTimeout:
            continue  # the kill has not landed yet; keep probing
    _check(
        sticky is not None and "dispatcher_kill" in str(sticky),
        "submit(timeout=) did not surface the dead dispatcher's sticky error",
    )
    dead.reset()  # drains the dead queue, clears the error, re-arms
    dead.submit(p, t)
    _check(float(dead.result()) == 1.0, "engine did not serve after dispatcher-death reset")
    dead.stop()
    fired_sites |= set(kill_inj.fired)

    # ----------------------- trace-time kernel-dispatch fault: silent fallback
    import jax.numpy as jnp

    from metrics_tpu.ops.kernels import fold_rows_masked, kernel_fault_scope, use_backend

    calls = []

    def hook(kernel):
        calls.append(kernel)
        raise RuntimeError("injected trace-time kernel failure")

    state = jnp.zeros((4,), jnp.float32)
    rows = jnp.asarray(rng.randint(0, 65, size=(6, 4)) / 64.0, jnp.float32)
    mask = jnp.asarray([True] * 5 + [False])
    want_fold = np.asarray(fold_rows_masked(state, rows, mask, "sum", backend="xla"))
    with kernel_fault_scope(hook), use_backend("pallas"):
        got_fold = np.asarray(fold_rows_masked(state, rows, mask, "sum"))
    _check(bool(calls), "trace-time kernel fault hook never ran")
    _check(
        np.array_equal(got_fold, want_fold),
        "kernel-dispatch fault did not fall back to the XLA path",
    )

    # ------------------------------------------------------- sweep completeness
    missing = set(FAULT_SITES) - fired_sites
    _check(not missing, f"injection points never fired: {sorted(missing)}")

    if _FAILED:
        return 1
    print(
        "chaos-smoke PASS: "
        f"{len(FAULT_SITES)} injection points fired; chaos result bit-identical "
        f"to fault-free run ({len(clean)} batches; 1 poisoned batch quarantined, "
        f"ledger exact); rollbacks={st.rollbacks}, retries={st.retries}, "
        f"demotions={st.kernel_demotions}, watchdog={st.watchdog_timeouts}; "
        "restore fell back past the corrupted LATEST with exact replay; "
        f"telemetry -> {out_path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
