"""Ragged serving: group-keyed metric domains through the streaming engine.

The last metric families with no serving story are the ones whose state is a
BAG OF ROWS per logical group — retrieval (documents keyed by query id,
AP/NDCG folds after a per-query rank sort) and detection (boxes keyed by
image id, COCO matching after a score sort). Their eager form is
``dist_reduce_fx=None`` cat-lists, which every engine gate rightly refuses:
list states grow with data and have no masked/segmented/stacked-merge form.
But the GROUPED shape is exactly the multi-tenant shape at a finer grain —
a query id is a micro-scale stream id — so the whole existing machinery
(segmented one-executable step, megabatch coalescing, deferred mesh,
``WindowPolicy`` pane rings, the stream-shard pager that already serves
millions of keys) applies once the state is given a static shape:

* **Capacity buffers** (AUROC's cat-capacity precedent): each group carries
  ``capacity`` rows per payload field plus a ``count``. Rows land at
  ``count + rank`` via one stable lexsort over the batch's group keys and a
  scatter with ``mode="drop"`` — pad rows and over-capacity rows drop in the
  same mechanism, and ``count`` keeps the TRUE total so overflow is loud
  (NaN per-group, a typed refusal at the aggregate read), never a silent
  truncation.
* **Group keys ride the stream machinery**: :class:`RaggedEngine` is a
  ``MultiStreamEngine`` whose submitted items carry a PER-ROW int32 group-id
  array instead of one scalar stream id; the megabatch merge broadcasts
  scalars and concatenates arrays identically, so cross-group coalescing,
  bucketing by row count, routing, and the pager are all unchanged.
* **Sort-at-compute stays at compute**: the per-group read
  (``result(gid)``/``results()``) runs the metric's
  ``grouped_group_value`` — a traced compute over one group's
  ``(capacity, ...)`` buffers — and the aggregate ``result()`` runs as ONE
  device program plus one scalar transfer (ISSUE 18): the per-group read
  batches over the stacked ``(G, capacity, ...)`` buffers and the
  per-group scores fold with the masked row kernels
  (``metrics declaring grouped_aggregate_spec()``; detection's corpus PR
  curve device-matches per image and interpolates host-side). The host
  eager replay (``grouped_finalize`` → unmodified eager ``compute``) is
  kept as the parity ORACLE behind ``aggregate(oracle=True)`` /
  ``aggregate_oracle=True``. Both paths are bit-exact vs the eager
  oracle: every row carries its ingest rank in an engine-owned ``_seq``
  field, and every read re-orders a group's rows by it, so rows that
  compare EQUAL under the compute's sort key still tie-break exactly as
  the eager metric's submission order — whatever merge, pane or shard
  interleaving produced the buffers.

A metric opts in by returning a :class:`~metrics_tpu.metric.GroupedUpdateSpec`
from ``grouped_update_spec()`` (``masked_update_strategy() == "grouped"``);
non-ragged engines then refuse it at construction with a typed message that
points here (``Metric.grouped_refusal_reason``). See docs/serving.md
§ "Ragged serving".
"""
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.aot import AotCache
from metrics_tpu.engine.multistream import MultiStreamEngine
from metrics_tpu.engine.pipeline import EngineConfig
from metrics_tpu.metric import GroupedUpdateSpec, Metric
from metrics_tpu.ops.kernels import (
    MEGASTEP_BACKENDS,
    fold_rows_masked,
    resolve_backend,
    segment_reduce_masked,
)
from metrics_tpu.utils.exceptions import MetricsTPUUserError

# paged aggregate sweep: fixed block row count — ONE block program serves any
# touched-row population (the last block pads with ok=False rows), so repeat
# aggregates never recompile as groups spill in and out
_AGG_BLOCK_ROWS = 1024

# sentinel a corpus plan returns through _aggregate_corpus when the device
# pass declines (class universe past the device budget, empty corpus) — the
# aggregate reroutes to the host oracle
_CORPUS_FALLBACK = object()

__all__ = ["GroupedStateMetric", "RaggedEngine"]


class GroupedStateMetric(Metric):
    """Engine-internal wrapper giving a group-keyed metric a STATIC state.

    One group's state is ``count`` (scalar int32, the TRUE number of rows
    ever ingested — may exceed capacity, which is the overflow signal) plus
    one ``(capacity,) + field.shape`` buffer per spec field. The engine
    stacks a leading group axis over it exactly like any multi-stream state,
    so the whole ragged subsystem reuses the (S, ...)-stacked arena, the
    stream-shard pager's per-row spill/fault, and the windowed pane ring
    without a single new carried form.

    The wrapped user metric is held under a dunder attribute name
    (``__grouped_inner__``) deliberately: ``_child_metrics`` skips dunder
    attrs, so the inner metric's LIST states never leak into this wrapper's
    state registry, while ``metric_fingerprint`` still walks ``__dict__``
    and keys compiled programs on the inner metric's full configuration.
    """

    full_state_update = False

    def __init__(self, metric: Any, capacity: Optional[int] = None) -> None:
        super().__init__()
        spec = metric.grouped_update_spec()
        if spec is None or not isinstance(spec, GroupedUpdateSpec):
            raise MetricsTPUUserError(
                f"{type(metric).__name__} declares no grouped_update_spec(); "
                "only group-keyed metrics (retrieval, detection) serve through "
                "the ragged path"
            )
        cap = int(capacity) if capacity is not None else int(spec.capacity)
        if cap <= 0:
            raise MetricsTPUUserError(
                f"ragged capacity must be a positive int, got {capacity!r}"
            )
        self._capacity = cap
        # the engine-owned "_seq" field rides last: each row's global ingest
        # rank (the submit-side monotone counter), the stable secondary sort
        # key every read re-orders a group's rows by — so rows that compare
        # EQUAL under the compute's own sort key tie-break by submission
        # order no matter how merges/panes/shards interleaved the buffers
        self._user_field_names: Tuple[str, ...] = spec.field_names()
        self._field_names: Tuple[str, ...] = self._user_field_names + ("_seq",)
        self._field_shapes = tuple(
            tuple(int(d) for d in f.shape) for f in spec.fields
        ) + ((),)
        self._field_dtypes = tuple(
            str(jnp.dtype(f.dtype)) for f in spec.fields
        ) + ("int32",)
        # count declares fx=None deliberately: the boundary merge needs the
        # PER-REPLICA counts (they are the buffers' validity) so every leaf
        # rides the stacked u32 carrier — sync_states gathers, then
        # merge_stacked_states sums counts and compacts rows locally. A
        # "sum" declaration would promise a psum the merge never issues
        # (the quantized-sync-policy audit reads this declaration).
        self.add_state("count", default=jnp.zeros((), jnp.int32), dist_reduce_fx=None)
        for name, shape, dtype in zip(
            self._field_names, self._field_shapes, self._field_dtypes
        ):
            self.add_state(
                "buf_" + name,
                default=jnp.zeros((cap,) + shape, jnp.dtype(dtype)),
                dist_reduce_fx=None,
            )
        self.__dict__["__grouped_inner__"] = metric

    # --------------------------------------------------------------- eager facade

    def _inner(self) -> Any:
        return self.__dict__["__grouped_inner__"]

    @property
    def capacity(self) -> int:
        return self._capacity

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise MetricsTPUUserError(
            "GroupedStateMetric ingests through the ragged engine's segmented "
            "step only; call the wrapped metric's update() for eager use"
        )

    def compute(self) -> Any:
        """ONE group's value from its capacity buffers — the per-group read
        the engine's compiled ``result(gid)``/``results()`` programs run.
        Rows present in INGEST order (the ``_seq`` sort), so equal-sort-key
        rows tie-break exactly as the eager metric's submission order."""
        tree = {"count": jnp.asarray(self.count)[None]}
        for name in self._field_names:
            tree["buf_" + name] = jnp.asarray(getattr(self, "buf_" + name))[None]
        fields = {k: v[0] for k, v in self.seq_ordered_fields(tree).items()}
        return self._inner().grouped_group_value(fields, self.count, self._capacity)

    def seq_ordered_fields(self, tree: Dict[str, Any]) -> Dict[str, Any]:
        """User-named field buffers with every group's valid rows gathered
        into ingest (``_seq``) order — the row view ALL reads share (traced;
        ``tree`` leaves carry a leading group axis: ``count`` ``(G,)``,
        buffers ``(G, capacity, ...)``).

        Valid rows hold globally unique seq values so the gather is a
        permutation of the valid prefix; invalid slots key to int32 max and
        sink to the tail (their values are unread — every consumer masks by
        ``count``)."""
        cap = self._capacity
        counts = jnp.asarray(tree["count"], jnp.int32)
        seq = jnp.asarray(tree["buf__seq"], jnp.int32)
        valid = jnp.arange(cap)[None, :] < jnp.minimum(counts, cap)[:, None]
        key = jnp.where(valid, seq, jnp.iinfo(jnp.int32).max)
        order = jnp.argsort(key, axis=1)
        out: Dict[str, Any] = {}
        for name in self._user_field_names:
            v = jnp.asarray(tree["buf_" + name])
            idx = jnp.reshape(order, order.shape + (1,) * (v.ndim - 2))
            out[name] = jnp.take_along_axis(v, idx, axis=1)
        return out

    # ------------------------------------------------------------ engine contract

    def segmented_update_unsupported_reason(self) -> Optional[str]:
        return None

    def stacked_merge_unsupported_reason(self) -> Optional[str]:
        return None

    def update_state_segmented(
        self,
        state: Dict[str, Any],
        *args: Any,
        mask: Any,
        segment_ids: Any,
        num_segments: int,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """The grouped capacity write: one stable lexsort + one scatter per
        field, fully static.

        Masked rows get the sentinel key ``num_segments`` and over-capacity
        rows a column index ``>= capacity`` — both drop out of the scatter
        via ``mode="drop"``, while ``count`` keeps the true per-group total
        (overflow stays observable). Within one batch a group's rows land in
        batch order (stable sort + in-run rank), so every strict sort at
        compute time sees exactly the rows the eager metric would.
        """
        if kwargs:
            raise MetricsTPUUserError(
                f"grouped ingestion takes positional field rows only; got kwargs {sorted(kwargs)}"
            )
        if len(args) != len(self._field_names):
            raise MetricsTPUUserError(
                f"grouped ingestion expects {len(self._field_names)} field arrays "
                f"({', '.join(self._field_names)}), got {len(args)}"
            )
        mask = jnp.asarray(mask, bool)
        ids = jnp.asarray(segment_ids, jnp.int32)
        n = mask.shape[0]
        cap = self._capacity
        count = jnp.asarray(state["count"])

        seg_key = jnp.where(mask, ids, num_segments)
        # stable group sort: the arange tie-break pins submission order inside
        # each group's run (jnp.lexsort sorts by the LAST key first)
        order = jnp.lexsort((jnp.arange(n), seg_key))
        sseg = seg_key[order]
        smask = mask[order]
        pos = jnp.arange(n)
        run_start = jnp.concatenate([jnp.ones((1,), bool), sseg[1:] != sseg[:-1]])
        seg_start = jax.lax.cummax(jnp.where(run_start, pos, 0))
        rank = pos - seg_start  # 0-based offset within this batch's group run
        safe = jnp.minimum(sseg, num_segments - 1)
        base = count[safe]
        write_pos = jnp.where(smask, base + rank, cap)

        out = dict(state)
        out["count"] = count.at[sseg].add(
            smask.astype(count.dtype), mode="drop"
        )
        for i, name in enumerate(self._field_names):
            k = "buf_" + name
            buf = jnp.asarray(state[k])
            rows = jnp.asarray(args[i])[order].astype(buf.dtype)
            out[k] = buf.at[sseg, write_pos].set(rows, mode="drop")
        return out

    def sync_states(self, state: Dict[str, Any], axis_name: Any) -> Dict[str, Any]:
        """Deferred boundary merge over a mesh axis: every leaf (count AND
        buffers) rides ONE fused u32-carrier all_gather stacked ``(world, ...)``,
        then the compaction fold (:meth:`merge_stacked_states`) runs locally on
        every shard — replicated output, exactly the per-leaf ``sync_states``
        contract. The default per-leaf path can't serve grouped state: a psum'd
        count with world-stacked buffers is not a logical state."""
        from metrics_tpu.parallel.collectives import fused_axis_sync, in_mapped_context

        if axis_name is None or not in_mapped_context(axis_name):
            return state
        keys = sorted(state)
        gathered = fused_axis_sync([(None, state[k]) for k in keys], axis_name)
        return self.merge_stacked_states(dict(zip(keys, gathered)))

    def merge_stacked_states(self, stacked: Dict[str, Any]) -> Dict[str, Any]:
        """Fold a leading stack axis of grouped states: counts SUM; buffers
        COMPACT — each group's valid rows from all P replicas pack to the
        front of one fresh capacity buffer, replica-major (replica order ==
        shard/pane order, the same order a cat-state merge concatenates in).

        Handles every stacked form the engine produces: ``(P,)`` leading over
        per-group rows (one stream's pane ring), ``(P, S)`` over the stacked
        state (deferred boundary merge, sliding-window folds) — any middle
        axes ``mid`` between the stack axis and the capacity axis.
        """
        cap = self._capacity
        count = jnp.asarray(stacked["count"])
        P = count.shape[0]
        mid = count.shape[1:]
        out: Dict[str, Any] = {"count": jnp.sum(count, axis=0)}
        cflat = jnp.reshape(count, (P, -1))  # (P, G)
        G = cflat.shape[1]
        filled = jnp.minimum(cflat, cap)
        slot = jnp.arange(cap)
        valid = slot[None, None, :] < filled[:, :, None]  # (P, G, cap)
        vflat = jnp.reshape(jnp.transpose(valid, (1, 0, 2)), (G, P * cap))
        # stable argsort of ~valid: per group, the indices of valid slots in
        # (replica, slot) order come first — the compaction gather map
        take = jnp.argsort(~vflat, axis=1)[:, :cap]  # (G, cap)
        for name in self._field_names:
            k = "buf_" + name
            v = jnp.asarray(stacked[k])  # (P,)+mid+(cap,)+suffix
            suffix = v.shape[1 + len(mid) + 1:]
            rows = jnp.reshape(v, (P, G, cap) + suffix)
            rows = jnp.reshape(jnp.moveaxis(rows, 0, 1), (G, P * cap) + suffix)
            idx = jnp.reshape(take, (G, cap) + (1,) * len(suffix))
            gathered = jnp.take_along_axis(rows, idx, axis=1)
            out[k] = jnp.reshape(gathered, mid + (cap,) + suffix)
        return out


class RaggedEngine(MultiStreamEngine):
    """Serve a group-keyed metric: ``num_groups`` logical groups (query ids,
    image ids), per-row group keys, capacity-buffer state, the aggregate
    eager-oracle read.

    Args:
        metric: a metric declaring ``grouped_update_spec()`` (``RetrievalMAP``,
            ``RetrievalNormalizedDCG``, detection ``MeanAveragePrecision``).
        num_groups: the group-key universe — keys are ``0 <= gid < num_groups``.
        config: engine config; composes with deferred mesh and ``WindowPolicy``.
        aot_cache: optional shared AOT cache.
        capacity: per-group row budget (defaults to the metric's spec).
        group_shard: shard the group axis over the mesh + page cold groups
            (the stream-shard machinery at group grain).
        resident_groups: per-shard paged-arena slot count under
            ``group_shard`` (see ``resident_streams``).
        aggregate_oracle: pin the aggregate ``result()`` to the host
            eager-replay oracle path (``grouped_finalize`` + eager
            ``compute``) instead of the compiled device aggregate — the
            parity flag; per-call override via ``aggregate(oracle=...)``.

    ``submit(group_ids, *fields)`` takes one scalar group id for a
    single-group batch or a per-row int32 array for a mixed-group batch;
    ``submit_update(*eager_args)`` accepts the metric's own eager update
    signature and routes it through ``grouped_encode``. ``result(gid)`` /
    ``results()`` are the per-group reads; ``result()`` with no argument is
    the aggregate value, bit-exact vs the eager oracle.
    """

    def __init__(
        self,
        metric: Any,
        num_groups: int,
        config: Optional[EngineConfig] = None,
        aot_cache: Optional[AotCache] = None,
        capacity: Optional[int] = None,
        group_shard: bool = False,
        resident_groups: Optional[int] = None,
        aggregate_oracle: bool = False,
    ):
        spec = getattr(metric, "grouped_update_spec", lambda: None)()
        if spec is None:
            raise MetricsTPUUserError(
                f"RaggedEngine serves group-keyed metrics only: "
                f"{type(metric).__name__} declares no grouped_update_spec() "
                "(built-in retrieval metrics with a segment kind and detection "
                "MeanAveragePrecision do)"
            )
        if config is not None and config.kernel_backend in MEGASTEP_BACKENDS:
            raise MetricsTPUUserError(
                "ragged serving has no megastep form: the grouped capacity "
                "write (the INGEST scatter) is a 2-d scatter outside the "
                "per-column opcode grid; the AGGREGATE path is kernel-"
                "eligible and honors the configured backend — use "
                "kernel_backend='xla' or 'pallas_interpret'"
            )
        self._user_metric = metric
        wrapped = GroupedStateMetric(metric, capacity=capacity)
        self._capacity = wrapped.capacity
        self._n_fields = len(spec.fields)
        super().__init__(
            wrapped,
            num_streams=num_groups,
            config=config,
            aot_cache=aot_cache,
            stream_shard=group_shard,
            resident_streams=resident_groups,
        )
        self._stats.ragged_groups = int(num_groups)
        self._stats.ragged_capacity = int(self._capacity)
        # the grouped capacity write (the INGEST scatter) is a 2-d scatter
        # with no per-column kernel form — kernel-ineligible by design (the
        # megastep tiers refuse above). Pin the RESOLVED backend of the
        # ingest/step programs to the XLA reference lowering so program
        # keys, the kernel scope, and the scatter audit (no-scatter-under-
        # pallas's ineligibility clause) all agree. The AGGREGATE path is
        # kernel-eligible (its folds are the masked row kernels), so it
        # keeps the user's configured backend separately.
        self._agg_backend = config.kernel_backend if config is not None else "auto"
        self._kernel_backend = "xla"
        self._aggregate_oracle = bool(aggregate_oracle)
        # global ingest-rank counter backing the engine-owned "_seq" field;
        # snapshotted/restored so kill/resume keeps replayed rows ordered
        # AFTER every row the snapshot already carries
        self._ingest_seq = 0
        self._seq_lock = threading.Lock()

    # ------------------------------------------------------------------ properties

    @property
    def num_groups(self) -> int:
        return self._num_streams

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def user_metric(self) -> Any:
        return self._user_metric

    # ------------------------------------------------------------------- producers

    def _check_group_ids(self, group_ids: Any, fields: Tuple[Any, ...]) -> Tuple[Any, int]:
        if len(fields) != self._n_fields:
            raise MetricsTPUUserError(
                f"ragged submit expects {self._n_fields} field arrays "
                f"({', '.join(self._metric._user_field_names)}), got {len(fields)}"
            )
        n = int(np.shape(fields[0])[0]) if np.ndim(fields[0]) else 0
        for f in fields[1:]:
            if int(np.shape(f)[0]) != n:
                raise MetricsTPUUserError(
                    "ragged submit field arrays must share their leading (row) dim"
                )
        if np.ndim(group_ids) == 0:
            return self._check_stream(group_ids), n
        gids = np.asarray(group_ids)
        if gids.ndim != 1 or gids.shape[0] != n:
            raise MetricsTPUUserError(
                f"group_ids must be a scalar or a 1-d array of length {n} "
                f"(one key per row), got shape {gids.shape}"
            )
        if gids.size and (int(gids.min()) < 0 or int(gids.max()) >= self._num_streams):
            raise MetricsTPUUserError(
                f"group_ids out of range [0, {self._num_streams}): "
                f"min={int(gids.min())}, max={int(gids.max())}"
            )
        return gids.astype(np.int32), n

    def submit(
        self, group_ids: Any, *fields: Any, timeout: Optional[float] = None, **kwargs: Any
    ) -> None:
        """Enqueue rows for one group (scalar id) or many (per-row id array)."""
        gids, n = self._check_group_ids(group_ids, fields)
        if n == 0:
            return
        self._raise_if_failed()
        self.start()
        # stamp each row's global ingest rank — the "_seq" field (stable
        # secondary sort key of every read). Allocated under its own small
        # lock so concurrent producers get disjoint, submission-ordered runs.
        with self._seq_lock:
            seq0 = self._ingest_seq
            self._ingest_seq = seq0 + n
        fields = tuple(fields) + (np.arange(seq0, seq0 + n, dtype=np.int32),)
        n_groups = 1 if np.ndim(gids) == 0 else int(np.unique(gids).size)
        self._stats.record_ragged_submit(rows=n, groups=n_groups)
        item = (gids, fields, kwargs)
        if self._admission is not None:
            # per-group admission classes: a mixed-group batch is admitted
            # under its FIRST row's group (one batch, one verdict)
            admit = int(gids) if np.ndim(gids) == 0 else int(np.asarray(gids)[0])
            self._admitted_submit(admit, item, (fields, kwargs), timeout)
        else:
            self._submit_item(item, timeout)

    def submit_update(self, *args: Any, timeout: Optional[float] = None, **kwargs: Any) -> None:
        """Submit in the metric's own eager ``update`` signature: the
        metric's ``grouped_encode`` validates exactly like ``update`` and
        flattens the call to ``(group_ids, *field_rows)``."""
        encoded = self._user_metric.grouped_encode(*args, **kwargs)
        self.submit(encoded[0], *encoded[1:], timeout=timeout)

    # --------------------------------------------------------------- fault context

    def _item_context(self, item: Any) -> Dict[str, Any]:
        gids = item[0]
        if np.ndim(gids) == 0:
            return {"stream_id": int(gids)}
        u = np.unique(np.asarray(gids))
        return {"group_ids": [int(x) for x in u[:32]]}

    def _group_context(self, group: List[Any]) -> Dict[str, Any]:
        ids: set = set()
        for it in group:
            if isinstance(it, tuple) and len(it) == 3:
                ids.update(int(x) for x in np.atleast_1d(np.asarray(it[0])).ravel())
        return {"group_ids": sorted(ids)[:64]} if ids else {}

    # --------------------------------------------------------------------- readers

    def result(self, group_id: Optional[int] = None) -> Any:  # type: ignore[override]
        """``result(gid)`` is the per-group value (the wrapped metric's
        ``grouped_group_value`` through the shared compiled program);
        ``result()`` is the AGGREGATE: one compiled device program batches
        the per-group read over the stacked buffers and folds the scores
        with the masked row kernels — one scalar bundle crosses to host
        (under ``group_shard``, resident + spilled groups sweep through the
        same program in capacity-sized blocks). The host eager replay stays
        available as the parity oracle (``aggregate(oracle=True)``); both
        paths are bit-exact vs the eager oracle."""
        if group_id is None:
            return self.aggregate()
        return super().result(group_id)

    # ----------------------------------------------------------- aggregate read

    def aggregate_path(self) -> Tuple[str, str]:
        """Which path ``aggregate()`` takes and why: ``("device", reason)``
        or ``("oracle", reason)`` — introspection for tests/smokes, no work
        performed."""
        if self._aggregate_oracle:
            return ("oracle", "aggregate_oracle=True pinned at construction")
        spec = getattr(self._user_metric, "grouped_aggregate_spec", lambda: None)()
        if spec is None:
            return (
                "oracle",
                f"{type(self._user_metric).__name__} declares no "
                "grouped_aggregate_spec()",
            )
        if spec.kind == "fold":
            if self._stream_shard and self._pane_rows > 1 and self._window.kind == "sliding":
                return (
                    "oracle",
                    "group_shard + sliding panes: the pane ring folds through "
                    "the host row universe",
                )
            if self._stream_shard:
                return ("device", "batched fold over a capacity-blocked paged sweep")
            return ("device", "batched fold over the stacked buffers")
        if spec.kind == "corpus":
            if self._stream_shard:
                return (
                    "oracle",
                    "corpus aggregates need every group in one device pass; "
                    "group_shard pages groups out",
                )
            return ("device", "corpus device bundle + host curve interpolation")
        return ("oracle", f"unknown aggregate kind {spec.kind!r}")

    def aggregate(self, oracle: Optional[bool] = None) -> Any:
        """The corpus-level value. Device path by default (see
        :meth:`aggregate_path`); ``oracle=True`` forces the host eager
        replay for this one call (``None`` defers to the construction
        flag)."""
        self.flush()
        use_oracle = self._aggregate_oracle if oracle is None else bool(oracle)
        if not use_oracle:
            path, _ = self.aggregate_path()
            use_oracle = path != "device"
        if use_oracle:
            self._stats.record_ragged_aggregate("oracle")
            return self._aggregate_oracle_value()
        spec = self._user_metric.grouped_aggregate_spec()
        if spec.kind == "fold":
            if self._stream_shard:
                return self._aggregate_fold_paged()
            return self._aggregate_fold()
        value = self._aggregate_corpus()
        if value is _CORPUS_FALLBACK:
            self._stats.record_ragged_aggregate("oracle")
            return self._aggregate_oracle_value()
        return value

    def _aggregate_oracle_value(self) -> Any:
        """The host eager replay (the parity oracle): reconstruct every
        group's rows host-side in ingest order, rebuild the metric's eager
        list states via ``grouped_finalize``, run the unmodified eager
        ``compute``."""
        counts, fields = self._gather_groups()
        self._check_overflow(counts)
        gids = np.arange(self._num_streams, dtype=np.int64)
        state = self._user_metric.grouped_finalize(counts, fields, gids)
        return self._user_metric.compute_from(state)

    def _check_overflow(self, counts: np.ndarray) -> None:
        """The typed overflow raise both aggregate paths share — fires
        host-side off the ``(G,)`` count vector."""
        over = np.flatnonzero(counts > self._capacity)
        if over.size:
            self._stats.record_ragged_overflow(int(over.size))
            shown = ", ".join(
                f"{int(g)} ({int(counts[g])} rows)" for g in over[:8]
            )
            raise MetricsTPUUserError(
                f"ragged capacity overflow: {over.size} group(s) exceeded "
                f"capacity={self._capacity} — {shown}"
                f"{', ...' if over.size > 8 else ''}; rebuild the engine with a "
                "larger capacity= (rows past capacity were dropped, counts kept)"
            )

    # ------------------------------------------------------- fold device path

    def _aggregate_traced_from_tree(self, tree: Dict[str, Any]) -> Any:
        """The fold aggregate's traced tail from a logical ``(G, ...)`` tree:
        batched per-group scores, then masked kernel folds to the ``(4,)``
        scalar bundle ``[value, kept, flagged, overflow]`` — the ONE
        transfer the device aggregate makes."""
        cap = self._capacity
        kb = self._agg_backend
        counts = jnp.asarray(tree["count"], jnp.int32)
        fields = self._metric.seq_ordered_fields(tree)
        out = self._user_metric.grouped_batch_scores(counts, fields, cap)
        value = jnp.asarray(out["value"], jnp.float32)
        keep = jnp.asarray(out["keep"], bool)
        flag = jnp.asarray(out["flag"], bool)
        zero = jnp.zeros((), jnp.float32)
        ones = jnp.ones_like(value)
        total = fold_rows_masked(zero, value, keep, "sum", backend=kb)
        kept = fold_rows_masked(zero, ones, keep, "sum", backend=kb)
        flagged = fold_rows_masked(zero, ones, flag, "sum", backend=kb)
        overflow = fold_rows_masked(zero, ones, counts > cap, "sum", backend=kb)
        result = jnp.where(kept > 0, total / jnp.maximum(kept, 1.0), 0.0)
        return jnp.stack([result, kept, flagged, overflow])

    def _aggregate_traced(self, state: Any, *extra: Any) -> Any:
        tree = self._window_fold_traced(self._compute_tree(state), *extra)
        return self._aggregate_traced_from_tree(tree)

    def _aggregate_program(self):
        key = self._aot.program_key(
            f"aggregate_ragged+k.{resolve_backend(self._agg_backend)}"
            f"+w.{self._window_tag()}",
            self._metric_fp,
            arg_tree=(self._compute_input_abstract(),) + self._compute_extra_abs(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )

        def build():
            with self._kernel_scope():
                return (
                    jax.jit(self._aggregate_traced)
                    .lower(self._compute_input_abstract(), *self._compute_extra_abs())
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _aggregate_finish_fold(self, bundle: Any) -> Any:
        """Host finish of a fold bundle: fetch the 4 scalars in ONE
        transfer, fire the overflow raise off the count vector if any group
        overflowed, hand the folded mean to the metric's finish hook."""
        fetched = np.asarray(jax.device_get(bundle), np.float32)
        value, kept, flagged, overflow = (float(x) for x in fetched)
        if overflow:
            with self._state_lock:
                counts = np.asarray(
                    jax.device_get(self._logical_tree_locked()["count"])
                )
            self._check_overflow(counts)
        return self._user_metric.grouped_aggregate_finish(
            value, int(kept), int(flagged)
        )

    def _aggregate_fold(self) -> Any:
        """Unsharded fold aggregate: ONE compiled program over the logical
        state (deferred boundary merge / window fold inside the same trace)
        + one scalar-bundle transfer."""
        with self._state_lock:
            state = self._merged_state() if self._deferred else self._state
            bundle = self._aggregate_program()(state, *self._compute_extra())
            self._stats.result_device_calls += 1
        value = self._aggregate_finish_fold(bundle)
        self._stats.record_ragged_aggregate("device")
        return value

    # ------------------------------------------------------ paged fold sweep

    def _aggregate_block_program(self):
        """The paged sweep's block program: ``_AGG_BLOCK_ROWS`` packed group
        rows (+ their gids and an ok mask) score through the SAME batched
        fold body, then segment-scatter into the ``(G, 3)`` accumulator
        (``[value, kept, flagged]`` columns; each touched gid owns exactly
        one swept row, so the scatter-sum is an assignment and the final
        ``(G,)`` vectors are bit-identical to the unsharded batch)."""
        B = _AGG_BLOCK_ROWS
        G = self._num_streams
        rows_abs = {
            k: jax.ShapeDtypeStruct((B, n), jnp.dtype(k))
            for k, n in self._layout.buffer_sizes().items()
        }
        acc_abs = jax.ShapeDtypeStruct((G, 3), jnp.float32)
        gid_abs = jax.ShapeDtypeStruct((B,), jnp.int32)
        ok_abs = jax.ShapeDtypeStruct((B,), bool)
        key = self._aot.program_key(
            f"aggregate_ragged_block+k.{resolve_backend(self._agg_backend)}",
            self._metric_fp,
            arg_tree=(acc_abs, rows_abs, gid_abs, ok_abs), mesh=None,
            donate=False, sync=self._sync_tag(), precision=self._precision_tag,
        )
        metric, user, layout = self._metric, self._user_metric, self._layout
        cap, kb = self._capacity, self._agg_backend

        def build():
            def block(acc, rows, gids, ok):
                tree = layout.unpack_stacked(rows)
                counts = jnp.asarray(tree["count"], jnp.int32)
                fields = metric.seq_ordered_fields(tree)
                out = user.grouped_batch_scores(counts, fields, cap)
                value = jnp.asarray(out["value"], jnp.float32)
                keep = jnp.asarray(out["keep"], bool) & ok
                flag = jnp.asarray(out["flag"], bool) & ok
                over = (counts > cap) & ok
                cols = jnp.stack(
                    [
                        jnp.where(keep, value, 0.0),
                        keep.astype(jnp.float32),
                        flag.astype(jnp.float32),
                    ],
                    axis=1,
                )
                mask = keep | flag | over
                new_acc = segment_reduce_masked(
                    acc, cols, mask, gids, G, "sum", backend=kb
                )
                n_over = fold_rows_masked(
                    jnp.zeros((), jnp.float32), jnp.ones_like(value), over,
                    "sum", backend=kb,
                )
                return new_acc, n_over

            with self._kernel_scope():
                return (
                    jax.jit(block)
                    .lower(acc_abs, rows_abs, gid_abs, ok_abs)
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _aggregate_fold_final_program(self):
        """The sweep's closing fold: the ``(G, 3)`` accumulator to the same
        ``(4,)`` scalar bundle the unsharded path emits. The accumulated
        value column already reads ``where(keep, value, 0)`` per group —
        the identical dense vector the unsharded fold sums — so the result
        is bit-exact across both layouts."""
        G = self._num_streams
        acc_abs = jax.ShapeDtypeStruct((G, 3), jnp.float32)
        over_abs = jax.ShapeDtypeStruct((), jnp.float32)
        key = self._aot.program_key(
            f"aggregate_ragged_final+k.{resolve_backend(self._agg_backend)}",
            self._metric_fp,
            arg_tree=(acc_abs, over_abs), mesh=None, donate=False,
            sync=self._sync_tag(), precision=self._precision_tag,
        )
        kb = self._agg_backend

        def build():
            def final(acc, n_over):
                zero = jnp.zeros((), jnp.float32)
                keep = acc[:, 1] > 0
                ones = jnp.ones((acc.shape[0],), jnp.float32)
                total = fold_rows_masked(zero, acc[:, 0], keep, "sum", backend=kb)
                kept = fold_rows_masked(zero, ones, keep, "sum", backend=kb)
                flagged = fold_rows_masked(
                    zero, ones, acc[:, 2] > 0, "sum", backend=kb
                )
                result = jnp.where(kept > 0, total / jnp.maximum(kept, 1.0), 0.0)
                return jnp.stack([result, kept, flagged, n_over])

            with self._kernel_scope():
                return jax.jit(final).lower(acc_abs, over_abs).compile()

        return self._aot.get_or_compile(key, build)

    def _swept_rows_locked(self) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """The paged sweep's work list (state lock held): ``(gids (M,),
        {dtype: (M, n)})`` packed rows of every TOUCHED group — resident
        slots out of the device arena, spilled groups out of the pager's
        host store — never the ``(G, n)`` dense universe. Untouched groups
        carry count 0 and contribute nothing to the fold, exactly as in the
        eager corpus. A group both resident and spilled keeps the spill copy
        (the row-reassembly precedence)."""
        arena = {k: np.asarray(jax.device_get(v)) for k, v in self._state.items()}
        payload = self._decoded_pager_payload(self._pager.snapshot_payload())
        world, num = self._world, self._num_streams
        parts_g: List[np.ndarray] = []
        parts_r: Dict[str, List[np.ndarray]] = {k: [] for k in arena}
        slots = np.asarray(payload["slots"])
        w_idx, j_idx = np.nonzero(slots >= 0)
        if w_idx.size:
            ext = slots[w_idx, j_idx].astype(np.int64) * world + w_idx
            sid, pane = self._ext_to_sid_pane(ext)
            keep = (sid < num) & self._pane_open(pane)
            parts_g.append(sid[keep])
            for k in arena:
                parts_r[k].append(arena[k][w_idx[keep], j_idx[keep]])
        coords = np.asarray(
            payload.get("spill_coords", np.zeros((0, 2), np.int64))
        ).reshape(-1, 2)
        if coords.size:
            ext = coords[:, 1].astype(np.int64) * world + coords[:, 0].astype(np.int64)
            sid, pane = self._ext_to_sid_pane(ext)
            keep = (sid < num) & self._pane_open(pane)
            parts_g.append(sid[keep])
            for k in arena:
                parts_r[k].append(np.asarray(payload[f"spill_{k}"])[keep])
        if not parts_g:
            return np.zeros((0,), np.int64), {
                k: np.zeros((0, v.shape[-1]), v.dtype) for k, v in arena.items()
            }
        gids = np.concatenate(parts_g)
        rows = {k: np.concatenate(parts_r[k], axis=0) for k in arena}
        # keep the LAST copy of a duplicated gid (spill wins over resident)
        _, last = np.unique(gids[::-1], return_index=True)
        sel = np.sort(gids.size - 1 - last)
        return gids[sel], {k: v[sel] for k, v in rows.items()}

    def _ext_to_sid_pane(self, ext: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Invert ``_ext_id``: extended (stream, pane) row ids back to
        ``(sid, pane)`` — identity panes on unwindowed engines."""
        if self._pane_rows == 1:
            return ext, np.zeros_like(ext)
        w = ext % self._world
        q = ext // self._world
        pane = q % self._pane_rows
        sid = (q // self._pane_rows) * self._world + w
        return sid, pane

    def _pane_open(self, pane: np.ndarray) -> np.ndarray:
        """Rows belonging to the aggregate's pane view: everything on
        unwindowed engines, the open pane on tumbling rings (sliding +
        group_shard routes to the oracle in :meth:`aggregate_path`)."""
        if self._pane_rows == 1:
            return np.ones_like(pane, dtype=bool)
        return pane == self._pane_cursor

    def _aggregate_fold_paged(self) -> Any:
        """``group_shard`` fold aggregate: page every touched group's packed
        row through the block program in ``_AGG_BLOCK_ROWS``-sized sweeps
        (O(touched / block) dispatches — never one per group), accumulate
        per-group columns on device, close with one fold program + one
        scalar-bundle transfer."""
        B = _AGG_BLOCK_ROWS
        with self._state_lock:
            gids, rows = self._swept_rows_locked()
            block = self._aggregate_block_program()
            final = self._aggregate_fold_final_program()
            acc = jnp.zeros((self._num_streams, 3), jnp.float32)
            n_over = jnp.zeros((), jnp.float32)
            M = int(gids.shape[0])
            n_blocks = max(1, -(-M // B))
            for b in range(n_blocks):
                lo = b * B
                blk_g = np.full((B,), 0, np.int32)
                blk_ok = np.zeros((B,), bool)
                m = max(0, min(B, M - lo))
                if m:
                    blk_g[:m] = gids[lo:lo + m].astype(np.int32)
                    blk_ok[:m] = True
                blk_rows = {}
                for k, v in rows.items():
                    pad = np.zeros((B, v.shape[-1]), v.dtype)
                    if m:
                        pad[:m] = v[lo:lo + m]
                    blk_rows[k] = jnp.asarray(pad)
                acc, over_b = block(acc, blk_rows, jnp.asarray(blk_g), jnp.asarray(blk_ok))
                n_over = n_over + over_b
                self._stats.result_device_calls += 1
            bundle = final(acc, n_over)
            self._stats.result_device_calls += 1
        value = self._aggregate_finish_fold(bundle)
        self._stats.record_ragged_aggregate("device", blocks=n_blocks)
        return value

    # ----------------------------------------------------- corpus device path

    def _aggregate_corpus(self) -> Any:
        """Detection-style corpus aggregate: the metric plans the device
        pass off the count + scan-field vectors (host), one compiled program
        produces the corpus match bundle (per-group greedy matches batched
        on device), and the metric's host finish interpolates the final
        curve. Returns ``_CORPUS_FALLBACK`` when the plan declines (class
        universe too large for the device budget / empty corpus) — the
        caller reroutes to the oracle."""
        user = self._user_metric
        with self._state_lock:
            tree = self._logical_tree_locked()
            counts = np.asarray(jax.device_get(tree["count"]))
            scan_names = tuple(user.grouped_corpus_scan_fields())
            scan = {
                name: np.asarray(jax.device_get(tree["buf_" + name]))
                for name in scan_names
            }
        self._check_overflow(counts)
        plan = user.grouped_corpus_plan(counts, scan)
        if plan is None:
            return _CORPUS_FALLBACK
        classes = np.asarray(plan["classes_padded"], np.int32)
        cls_valid = np.arange(classes.shape[0]) < int(plan["n_classes"])
        with self._state_lock:
            state = self._merged_state() if self._deferred else self._state
            bundle = self._corpus_program(int(classes.shape[0]))(
                state,
                jnp.asarray(classes),
                jnp.asarray(cls_valid),
                *self._compute_extra(),
            )
            self._stats.result_device_calls += 1
        fetched = jax.tree.map(lambda x: np.asarray(x), jax.device_get(bundle))
        self._stats.record_ragged_aggregate("device")
        return user.grouped_corpus_finish(fetched, plan)

    def _corpus_program(self, c_pad: int):
        """ONE compiled corpus-bundle program per padded-class-count bucket
        (the plan pads the class list so nearby corpora share programs; the
        live class count rides a validity mask, not the trace)."""
        cls_abs = jax.ShapeDtypeStruct((c_pad,), jnp.int32)
        valid_abs = jax.ShapeDtypeStruct((c_pad,), bool)
        key = self._aot.program_key(
            f"aggregate_ragged_corpus+k.{resolve_backend(self._agg_backend)}"
            f"+c{c_pad}+w.{self._window_tag()}",
            self._metric_fp,
            arg_tree=(self._compute_input_abstract(), cls_abs, valid_abs)
            + self._compute_extra_abs(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric, user, cap = self._metric, self._user_metric, self._capacity

        def build():
            def corpus(state, classes, cls_valid, *extra):
                tree = self._window_fold_traced(self._compute_tree(state), *extra)
                counts = jnp.asarray(tree["count"], jnp.int32)
                fields = metric.seq_ordered_fields(tree)
                return user.grouped_corpus_device(
                    counts, fields, classes, cls_valid, cap
                )

            with self._kernel_scope():
                return (
                    jax.jit(corpus)
                    .lower(
                        self._compute_input_abstract(), cls_abs, valid_abs,
                        *self._compute_extra_abs(),
                    )
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    # --------------------------------------------------------- analysis hooks

    def _aggregate_audit_jaxprs(self) -> List[Tuple[str, Any]]:
        """``(label, jaxpr)`` pairs of the device-aggregate programs,
        re-traced FRESH on every call (so a monkeypatched metric hook is
        seen) — what ``EngineAnalysis.check()`` audits. Empty when the
        aggregate runs on the oracle path."""
        path, _ = self.aggregate_path()
        if path != "device":
            return []
        spec = self._user_metric.grouped_aggregate_spec()
        out: List[Tuple[str, Any]] = []
        if spec.kind == "fold":
            if self._stream_shard:
                B, G = _AGG_BLOCK_ROWS, self._num_streams
                rows_abs = {
                    k: jax.ShapeDtypeStruct((B, n), jnp.dtype(k))
                    for k, n in self._layout.buffer_sizes().items()
                }
                metric, user, layout = self._metric, self._user_metric, self._layout
                cap, kb = self._capacity, self._agg_backend

                def block(acc, rows, gids, ok):
                    tree = layout.unpack_stacked(rows)
                    counts = jnp.asarray(tree["count"], jnp.int32)
                    fields = metric.seq_ordered_fields(tree)
                    res = user.grouped_batch_scores(counts, fields, cap)
                    keep = jnp.asarray(res["keep"], bool) & ok
                    cols = jnp.stack(
                        [
                            jnp.where(keep, jnp.asarray(res["value"], jnp.float32), 0.0),
                            keep.astype(jnp.float32),
                            jnp.asarray(res["flag"], bool).astype(jnp.float32),
                        ],
                        axis=1,
                    )
                    return segment_reduce_masked(
                        acc, cols, keep, gids, G, "sum", backend=kb
                    )

                out.append(
                    (
                        "aggregate/block",
                        jax.make_jaxpr(block)(
                            jax.ShapeDtypeStruct((G, 3), jnp.float32),
                            rows_abs,
                            jax.ShapeDtypeStruct((B,), jnp.int32),
                            jax.ShapeDtypeStruct((B,), bool),
                        ),
                    )
                )
            else:
                out.append(
                    (
                        "aggregate/fold",
                        jax.make_jaxpr(self._aggregate_traced)(
                            self._compute_input_abstract(), *self._compute_extra_abs()
                        ),
                    )
                )
        else:  # corpus: audit the bundle program at a nominal class bucket
            user, metric, cap = self._user_metric, self._metric, self._capacity
            c_pad = int(getattr(user, "grouped_corpus_audit_classes", lambda: 4)())

            def corpus(state, classes, cls_valid, *extra):
                tree = self._window_fold_traced(self._compute_tree(state), *extra)
                counts = jnp.asarray(tree["count"], jnp.int32)
                fields = metric.seq_ordered_fields(tree)
                return user.grouped_corpus_device(
                    counts, fields, classes, cls_valid, cap
                )

            out.append(
                (
                    "aggregate/corpus",
                    jax.make_jaxpr(corpus)(
                        self._compute_input_abstract(),
                        jax.ShapeDtypeStruct((c_pad,), jnp.int32),
                        jax.ShapeDtypeStruct((c_pad,), bool),
                        *self._compute_extra_abs(),
                    ),
                )
            )
        return out

    def _aggregate_program_cap(self) -> int:
        """Extra compiled-program allowance the device aggregate owns (the
        analysis compile-cap accounting): the fold program (unsharded), the
        block + final pair (paged sweep), or the per-class-bucket corpus
        allowance."""
        path, _ = self.aggregate_path()
        if path != "device":
            return 0
        spec = self._user_metric.grouped_aggregate_spec()
        if spec.kind == "fold":
            return 2 if self._stream_shard else 1
        return 4

    def _gather_groups(self) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Host numpy ``(counts (G,), {field: (G, capacity, ...)})`` of the
        logical per-group state, window panes folded (tumbling reads the open
        pane, sliding folds the ring through the wrapper's compaction merge).
        Each group's valid rows come back in INGEST order (the ``_seq``
        sort); the engine-owned ``_seq`` field itself is not returned."""
        with self._state_lock:
            tree = self._logical_tree_locked()
            counts = np.asarray(jax.device_get(tree["count"]))
            raw = {
                name: np.asarray(jax.device_get(tree["buf_" + name]))
                for name in self._metric._field_names
            }
        cap = self._capacity
        seq = raw.pop("_seq")
        filled = np.minimum(counts, cap)
        key = np.where(
            np.arange(cap)[None, :] < filled[:, None], seq, np.iinfo(np.int32).max
        )
        order = np.argsort(key, axis=1, kind="stable")
        fields = {}
        for name, v in raw.items():
            idx = order.reshape(order.shape + (1,) * (v.ndim - 2))
            fields[name] = np.take_along_axis(v, idx, axis=1)
        return counts, fields

    def _logical_tree_locked(self) -> Dict[str, Any]:
        if self._stream_shard:
            rows = self._global_rows_host()
            if self._pane_rows == 1:
                return self._layout.unpack_stacked(
                    {k: jnp.asarray(v) for k, v in rows.items()}
                )
            if self._window.kind == "tumbling":
                idx = self._ext_ids([self._pane_cursor])[0]
                return self._layout.unpack_stacked(
                    {k: jnp.asarray(np.asarray(v)[idx]) for k, v in rows.items()}
                )
            idx = self._ext_ids(range(self._pane_rows))
            stacked = self._layout.unpack_stacked(
                {k: jnp.asarray(np.asarray(v)[idx]) for k, v in rows.items()}, lead=2
            )
            return self._metric.merge_stacked_states(stacked)
        tree = self._merged_state() if self._deferred else self._unpack(self._state)
        if self._win_stacked:
            if self._window.kind == "tumbling":
                return jax.tree.map(lambda x: x[self._pane_cursor], tree)
            return self._metric.merge_stacked_states(tree)
        return tree

    # --------------------------------------------------------- snapshot provenance

    def _snapshot_meta_extra(self) -> Dict[str, Any]:
        extra = super()._snapshot_meta_extra()
        extra.update(
            ragged=1,
            ragged_capacity=self._capacity,
            ragged_groups=self._num_streams,
            # the ingest-rank counter: restored rows keep their original seq
            # values (all < this), replayed/new rows allocate from here on —
            # so kill/resume preserves relative ingest order exactly
            ragged_seq=int(self._ingest_seq),
        )
        return extra

    def _restore_commit(self, state: Any, meta: Dict[str, Any]) -> None:
        if not bool(int(meta.get("ragged", 0) or 0)):
            raise MetricsTPUUserError(
                "snapshot was not written by a ragged engine: plain stream "
                "rows carry no group-key provenance a RaggedEngine could seat "
                "— restore it into the engine kind that wrote it"
            )
        cap = int(meta.get("ragged_capacity", 0) or 0)
        if cap != self._capacity:
            raise MetricsTPUUserError(
                f"ragged snapshot was written at capacity={cap}, this engine "
                f"serves capacity={self._capacity}; per-group buffer columns "
                "only mean row slots under the capacity that wrote them — "
                "restore with a matching capacity= engine"
            )
        g = int(meta.get("ragged_groups", 0) or 0)
        if g != self._num_streams:
            raise MetricsTPUUserError(
                f"ragged snapshot serves {g} groups, this engine {self._num_streams}"
            )
        with self._seq_lock:
            self._ingest_seq = max(
                self._ingest_seq, int(meta.get("ragged_seq", 0) or 0)
            )
        super()._restore_commit(state, meta)
