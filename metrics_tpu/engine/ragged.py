"""Ragged serving: group-keyed metric domains through the streaming engine.

The last metric families with no serving story are the ones whose state is a
BAG OF ROWS per logical group — retrieval (documents keyed by query id,
AP/NDCG folds after a per-query rank sort) and detection (boxes keyed by
image id, COCO matching after a score sort). Their eager form is
``dist_reduce_fx=None`` cat-lists, which every engine gate rightly refuses:
list states grow with data and have no masked/segmented/stacked-merge form.
But the GROUPED shape is exactly the multi-tenant shape at a finer grain —
a query id is a micro-scale stream id — so the whole existing machinery
(segmented one-executable step, megabatch coalescing, deferred mesh,
``WindowPolicy`` pane rings, the stream-shard pager that already serves
millions of keys) applies once the state is given a static shape:

* **Capacity buffers** (AUROC's cat-capacity precedent): each group carries
  ``capacity`` rows per payload field plus a ``count``. Rows land at
  ``count + rank`` via one stable lexsort over the batch's group keys and a
  scatter with ``mode="drop"`` — pad rows and over-capacity rows drop in the
  same mechanism, and ``count`` keeps the TRUE total so overflow is loud
  (NaN per-group, a typed refusal at the aggregate read), never a silent
  truncation.
* **Group keys ride the stream machinery**: :class:`RaggedEngine` is a
  ``MultiStreamEngine`` whose submitted items carry a PER-ROW int32 group-id
  array instead of one scalar stream id; the megabatch merge broadcasts
  scalars and concatenates arrays identically, so cross-group coalescing,
  bucketing by row count, routing, and the pager are all unchanged.
* **Sort-at-compute stays at compute**: the per-group read
  (``result(gid)``/``results()``) runs the metric's
  ``grouped_group_value`` — a traced compute over one group's
  ``(capacity, ...)`` buffers — while the aggregate ``result()``
  reconstructs every group's rows host-side, rebuilds the metric's EAGER
  list states via ``grouped_finalize``, and runs the unmodified eager
  ``compute`` — bit-exact vs the eager oracle by construction (the one
  caveat: rows that compare EQUAL under the compute's sort key may permute
  across groups'/shards' interleavings; every strict ordering is exact).

A metric opts in by returning a :class:`~metrics_tpu.metric.GroupedUpdateSpec`
from ``grouped_update_spec()`` (``masked_update_strategy() == "grouped"``);
non-ragged engines then refuse it at construction with a typed message that
points here (``Metric.grouped_refusal_reason``). See docs/serving.md
§ "Ragged serving".
"""
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.aot import AotCache
from metrics_tpu.engine.multistream import MultiStreamEngine
from metrics_tpu.engine.pipeline import EngineConfig
from metrics_tpu.metric import GroupedUpdateSpec, Metric
from metrics_tpu.ops.kernels import MEGASTEP_BACKENDS
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["GroupedStateMetric", "RaggedEngine"]


class GroupedStateMetric(Metric):
    """Engine-internal wrapper giving a group-keyed metric a STATIC state.

    One group's state is ``count`` (scalar int32, the TRUE number of rows
    ever ingested — may exceed capacity, which is the overflow signal) plus
    one ``(capacity,) + field.shape`` buffer per spec field. The engine
    stacks a leading group axis over it exactly like any multi-stream state,
    so the whole ragged subsystem reuses the (S, ...)-stacked arena, the
    stream-shard pager's per-row spill/fault, and the windowed pane ring
    without a single new carried form.

    The wrapped user metric is held under a dunder attribute name
    (``__grouped_inner__``) deliberately: ``_child_metrics`` skips dunder
    attrs, so the inner metric's LIST states never leak into this wrapper's
    state registry, while ``metric_fingerprint`` still walks ``__dict__``
    and keys compiled programs on the inner metric's full configuration.
    """

    full_state_update = False

    def __init__(self, metric: Any, capacity: Optional[int] = None) -> None:
        super().__init__()
        spec = metric.grouped_update_spec()
        if spec is None or not isinstance(spec, GroupedUpdateSpec):
            raise MetricsTPUUserError(
                f"{type(metric).__name__} declares no grouped_update_spec(); "
                "only group-keyed metrics (retrieval, detection) serve through "
                "the ragged path"
            )
        cap = int(capacity) if capacity is not None else int(spec.capacity)
        if cap <= 0:
            raise MetricsTPUUserError(
                f"ragged capacity must be a positive int, got {capacity!r}"
            )
        self._capacity = cap
        self._field_names: Tuple[str, ...] = spec.field_names()
        self._field_shapes = tuple(tuple(int(d) for d in f.shape) for f in spec.fields)
        self._field_dtypes = tuple(str(jnp.dtype(f.dtype)) for f in spec.fields)
        # count declares fx=None deliberately: the boundary merge needs the
        # PER-REPLICA counts (they are the buffers' validity) so every leaf
        # rides the stacked u32 carrier — sync_states gathers, then
        # merge_stacked_states sums counts and compacts rows locally. A
        # "sum" declaration would promise a psum the merge never issues
        # (the quantized-sync-policy audit reads this declaration).
        self.add_state("count", default=jnp.zeros((), jnp.int32), dist_reduce_fx=None)
        for name, shape, dtype in zip(
            self._field_names, self._field_shapes, self._field_dtypes
        ):
            self.add_state(
                "buf_" + name,
                default=jnp.zeros((cap,) + shape, jnp.dtype(dtype)),
                dist_reduce_fx=None,
            )
        self.__dict__["__grouped_inner__"] = metric

    # --------------------------------------------------------------- eager facade

    def _inner(self) -> Any:
        return self.__dict__["__grouped_inner__"]

    @property
    def capacity(self) -> int:
        return self._capacity

    def update(self, *args: Any, **kwargs: Any) -> None:
        raise MetricsTPUUserError(
            "GroupedStateMetric ingests through the ragged engine's segmented "
            "step only; call the wrapped metric's update() for eager use"
        )

    def compute(self) -> Any:
        """ONE group's value from its capacity buffers — the per-group read
        the engine's compiled ``result(gid)``/``results()`` programs run."""
        fields = {name: getattr(self, "buf_" + name) for name in self._field_names}
        return self._inner().grouped_group_value(fields, self.count, self._capacity)

    # ------------------------------------------------------------ engine contract

    def segmented_update_unsupported_reason(self) -> Optional[str]:
        return None

    def stacked_merge_unsupported_reason(self) -> Optional[str]:
        return None

    def update_state_segmented(
        self,
        state: Dict[str, Any],
        *args: Any,
        mask: Any,
        segment_ids: Any,
        num_segments: int,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """The grouped capacity write: one stable lexsort + one scatter per
        field, fully static.

        Masked rows get the sentinel key ``num_segments`` and over-capacity
        rows a column index ``>= capacity`` — both drop out of the scatter
        via ``mode="drop"``, while ``count`` keeps the true per-group total
        (overflow stays observable). Within one batch a group's rows land in
        batch order (stable sort + in-run rank), so every strict sort at
        compute time sees exactly the rows the eager metric would.
        """
        if kwargs:
            raise MetricsTPUUserError(
                f"grouped ingestion takes positional field rows only; got kwargs {sorted(kwargs)}"
            )
        if len(args) != len(self._field_names):
            raise MetricsTPUUserError(
                f"grouped ingestion expects {len(self._field_names)} field arrays "
                f"({', '.join(self._field_names)}), got {len(args)}"
            )
        mask = jnp.asarray(mask, bool)
        ids = jnp.asarray(segment_ids, jnp.int32)
        n = mask.shape[0]
        cap = self._capacity
        count = jnp.asarray(state["count"])

        seg_key = jnp.where(mask, ids, num_segments)
        # stable group sort: the arange tie-break pins submission order inside
        # each group's run (jnp.lexsort sorts by the LAST key first)
        order = jnp.lexsort((jnp.arange(n), seg_key))
        sseg = seg_key[order]
        smask = mask[order]
        pos = jnp.arange(n)
        run_start = jnp.concatenate([jnp.ones((1,), bool), sseg[1:] != sseg[:-1]])
        seg_start = jax.lax.cummax(jnp.where(run_start, pos, 0))
        rank = pos - seg_start  # 0-based offset within this batch's group run
        safe = jnp.minimum(sseg, num_segments - 1)
        base = count[safe]
        write_pos = jnp.where(smask, base + rank, cap)

        out = dict(state)
        out["count"] = count.at[sseg].add(
            smask.astype(count.dtype), mode="drop"
        )
        for i, name in enumerate(self._field_names):
            k = "buf_" + name
            buf = jnp.asarray(state[k])
            rows = jnp.asarray(args[i])[order].astype(buf.dtype)
            out[k] = buf.at[sseg, write_pos].set(rows, mode="drop")
        return out

    def sync_states(self, state: Dict[str, Any], axis_name: Any) -> Dict[str, Any]:
        """Deferred boundary merge over a mesh axis: every leaf (count AND
        buffers) rides ONE fused u32-carrier all_gather stacked ``(world, ...)``,
        then the compaction fold (:meth:`merge_stacked_states`) runs locally on
        every shard — replicated output, exactly the per-leaf ``sync_states``
        contract. The default per-leaf path can't serve grouped state: a psum'd
        count with world-stacked buffers is not a logical state."""
        from metrics_tpu.parallel.collectives import fused_axis_sync, in_mapped_context

        if axis_name is None or not in_mapped_context(axis_name):
            return state
        keys = sorted(state)
        gathered = fused_axis_sync([(None, state[k]) for k in keys], axis_name)
        return self.merge_stacked_states(dict(zip(keys, gathered)))

    def merge_stacked_states(self, stacked: Dict[str, Any]) -> Dict[str, Any]:
        """Fold a leading stack axis of grouped states: counts SUM; buffers
        COMPACT — each group's valid rows from all P replicas pack to the
        front of one fresh capacity buffer, replica-major (replica order ==
        shard/pane order, the same order a cat-state merge concatenates in).

        Handles every stacked form the engine produces: ``(P,)`` leading over
        per-group rows (one stream's pane ring), ``(P, S)`` over the stacked
        state (deferred boundary merge, sliding-window folds) — any middle
        axes ``mid`` between the stack axis and the capacity axis.
        """
        cap = self._capacity
        count = jnp.asarray(stacked["count"])
        P = count.shape[0]
        mid = count.shape[1:]
        out: Dict[str, Any] = {"count": jnp.sum(count, axis=0)}
        cflat = jnp.reshape(count, (P, -1))  # (P, G)
        G = cflat.shape[1]
        filled = jnp.minimum(cflat, cap)
        slot = jnp.arange(cap)
        valid = slot[None, None, :] < filled[:, :, None]  # (P, G, cap)
        vflat = jnp.reshape(jnp.transpose(valid, (1, 0, 2)), (G, P * cap))
        # stable argsort of ~valid: per group, the indices of valid slots in
        # (replica, slot) order come first — the compaction gather map
        take = jnp.argsort(~vflat, axis=1)[:, :cap]  # (G, cap)
        for name in self._field_names:
            k = "buf_" + name
            v = jnp.asarray(stacked[k])  # (P,)+mid+(cap,)+suffix
            suffix = v.shape[1 + len(mid) + 1:]
            rows = jnp.reshape(v, (P, G, cap) + suffix)
            rows = jnp.reshape(jnp.moveaxis(rows, 0, 1), (G, P * cap) + suffix)
            idx = jnp.reshape(take, (G, cap) + (1,) * len(suffix))
            gathered = jnp.take_along_axis(rows, idx, axis=1)
            out[k] = jnp.reshape(gathered, mid + (cap,) + suffix)
        return out


class RaggedEngine(MultiStreamEngine):
    """Serve a group-keyed metric: ``num_groups`` logical groups (query ids,
    image ids), per-row group keys, capacity-buffer state, the aggregate
    eager-oracle read.

    Args:
        metric: a metric declaring ``grouped_update_spec()`` (``RetrievalMAP``,
            ``RetrievalNormalizedDCG``, detection ``MeanAveragePrecision``).
        num_groups: the group-key universe — keys are ``0 <= gid < num_groups``.
        config: engine config; composes with deferred mesh and ``WindowPolicy``.
        aot_cache: optional shared AOT cache.
        capacity: per-group row budget (defaults to the metric's spec).
        group_shard: shard the group axis over the mesh + page cold groups
            (the stream-shard machinery at group grain).
        resident_groups: per-shard paged-arena slot count under
            ``group_shard`` (see ``resident_streams``).

    ``submit(group_ids, *fields)`` takes one scalar group id for a
    single-group batch or a per-row int32 array for a mixed-group batch;
    ``submit_update(*eager_args)`` accepts the metric's own eager update
    signature and routes it through ``grouped_encode``. ``result(gid)`` /
    ``results()`` are the per-group reads; ``result()`` with no argument is
    the aggregate value, bit-exact vs the eager oracle.
    """

    def __init__(
        self,
        metric: Any,
        num_groups: int,
        config: Optional[EngineConfig] = None,
        aot_cache: Optional[AotCache] = None,
        capacity: Optional[int] = None,
        group_shard: bool = False,
        resident_groups: Optional[int] = None,
    ):
        spec = getattr(metric, "grouped_update_spec", lambda: None)()
        if spec is None:
            raise MetricsTPUUserError(
                f"RaggedEngine serves group-keyed metrics only: "
                f"{type(metric).__name__} declares no grouped_update_spec() "
                "(built-in retrieval metrics with a segment kind and detection "
                "MeanAveragePrecision do)"
            )
        if config is not None and config.kernel_backend in MEGASTEP_BACKENDS:
            raise MetricsTPUUserError(
                "ragged serving has no megastep form: the grouped capacity "
                "write is a 2-d scatter outside the per-column opcode grid — "
                "use kernel_backend='xla' or 'pallas_interpret'"
            )
        self._user_metric = metric
        wrapped = GroupedStateMetric(metric, capacity=capacity)
        self._capacity = wrapped.capacity
        self._n_fields = len(spec.fields)
        super().__init__(
            wrapped,
            num_streams=num_groups,
            config=config,
            aot_cache=aot_cache,
            stream_shard=group_shard,
            resident_streams=resident_groups,
        )
        self._stats.ragged_groups = int(num_groups)
        self._stats.ragged_capacity = int(self._capacity)
        # the grouped capacity write is a 2-d scatter with no per-column
        # kernel form — kernel-ineligible by design (the megastep tiers
        # refuse above). Pin the RESOLVED backend to the XLA reference
        # lowering so program keys, the kernel scope, and the scatter audit
        # (no-scatter-under-pallas's ineligibility clause) all agree.
        self._kernel_backend = "xla"

    # ------------------------------------------------------------------ properties

    @property
    def num_groups(self) -> int:
        return self._num_streams

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def user_metric(self) -> Any:
        return self._user_metric

    # ------------------------------------------------------------------- producers

    def _check_group_ids(self, group_ids: Any, fields: Tuple[Any, ...]) -> Tuple[Any, int]:
        if len(fields) != self._n_fields:
            raise MetricsTPUUserError(
                f"ragged submit expects {self._n_fields} field arrays "
                f"({', '.join(self._metric._field_names)}), got {len(fields)}"
            )
        n = int(np.shape(fields[0])[0]) if np.ndim(fields[0]) else 0
        for f in fields[1:]:
            if int(np.shape(f)[0]) != n:
                raise MetricsTPUUserError(
                    "ragged submit field arrays must share their leading (row) dim"
                )
        if np.ndim(group_ids) == 0:
            return self._check_stream(group_ids), n
        gids = np.asarray(group_ids)
        if gids.ndim != 1 or gids.shape[0] != n:
            raise MetricsTPUUserError(
                f"group_ids must be a scalar or a 1-d array of length {n} "
                f"(one key per row), got shape {gids.shape}"
            )
        if gids.size and (int(gids.min()) < 0 or int(gids.max()) >= self._num_streams):
            raise MetricsTPUUserError(
                f"group_ids out of range [0, {self._num_streams}): "
                f"min={int(gids.min())}, max={int(gids.max())}"
            )
        return gids.astype(np.int32), n

    def submit(
        self, group_ids: Any, *fields: Any, timeout: Optional[float] = None, **kwargs: Any
    ) -> None:
        """Enqueue rows for one group (scalar id) or many (per-row id array)."""
        gids, n = self._check_group_ids(group_ids, fields)
        if n == 0:
            return
        self._raise_if_failed()
        self.start()
        n_groups = 1 if np.ndim(gids) == 0 else int(np.unique(gids).size)
        self._stats.record_ragged_submit(rows=n, groups=n_groups)
        item = (gids, fields, kwargs)
        if self._admission is not None:
            # per-group admission classes: a mixed-group batch is admitted
            # under its FIRST row's group (one batch, one verdict)
            admit = int(gids) if np.ndim(gids) == 0 else int(np.asarray(gids)[0])
            self._admitted_submit(admit, item, (fields, kwargs), timeout)
        else:
            self._submit_item(item, timeout)

    def submit_update(self, *args: Any, timeout: Optional[float] = None, **kwargs: Any) -> None:
        """Submit in the metric's own eager ``update`` signature: the
        metric's ``grouped_encode`` validates exactly like ``update`` and
        flattens the call to ``(group_ids, *field_rows)``."""
        encoded = self._user_metric.grouped_encode(*args, **kwargs)
        self.submit(encoded[0], *encoded[1:], timeout=timeout)

    # --------------------------------------------------------------- fault context

    def _item_context(self, item: Any) -> Dict[str, Any]:
        gids = item[0]
        if np.ndim(gids) == 0:
            return {"stream_id": int(gids)}
        u = np.unique(np.asarray(gids))
        return {"group_ids": [int(x) for x in u[:32]]}

    def _group_context(self, group: List[Any]) -> Dict[str, Any]:
        ids: set = set()
        for it in group:
            if isinstance(it, tuple) and len(it) == 3:
                ids.update(int(x) for x in np.atleast_1d(np.asarray(it[0])).ravel())
        return {"group_ids": sorted(ids)[:64]} if ids else {}

    # --------------------------------------------------------------------- readers

    def result(self, group_id: Optional[int] = None) -> Any:  # type: ignore[override]
        """``result(gid)`` is the per-group value (the wrapped metric's
        ``grouped_group_value`` through the shared compiled program);
        ``result()`` is the AGGREGATE: every group's rows reconstruct
        host-side, ``grouped_finalize`` rebuilds the metric's eager list
        states in group-id order, and the unmodified eager ``compute`` runs —
        bit-exact vs the eager oracle."""
        if group_id is None:
            return self.aggregate()
        return super().result(group_id)

    def aggregate(self) -> Any:
        self.flush()
        counts, fields = self._gather_groups()
        over = np.flatnonzero(counts > self._capacity)
        if over.size:
            self._stats.record_ragged_overflow(int(over.size))
            shown = ", ".join(
                f"{int(g)} ({int(counts[g])} rows)" for g in over[:8]
            )
            raise MetricsTPUUserError(
                f"ragged capacity overflow: {over.size} group(s) exceeded "
                f"capacity={self._capacity} — {shown}"
                f"{', ...' if over.size > 8 else ''}; rebuild the engine with a "
                "larger capacity= (rows past capacity were dropped, counts kept)"
            )
        gids = np.arange(self._num_streams, dtype=np.int64)
        state = self._user_metric.grouped_finalize(counts, fields, gids)
        return self._user_metric.compute_from(state)

    def _gather_groups(self) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Host numpy ``(counts (G,), {field: (G, capacity, ...)})`` of the
        logical per-group state, window panes folded (tumbling reads the open
        pane, sliding folds the ring through the wrapper's compaction merge)."""
        with self._state_lock:
            tree = self._logical_tree_locked()
            counts = np.asarray(jax.device_get(tree["count"]))
            fields = {
                name: np.asarray(jax.device_get(tree["buf_" + name]))
                for name in self._metric._field_names
            }
        return counts, fields

    def _logical_tree_locked(self) -> Dict[str, Any]:
        if self._stream_shard:
            rows = self._global_rows_host()
            if self._pane_rows == 1:
                return self._layout.unpack_stacked(
                    {k: jnp.asarray(v) for k, v in rows.items()}
                )
            if self._window.kind == "tumbling":
                idx = self._ext_ids([self._pane_cursor])[0]
                return self._layout.unpack_stacked(
                    {k: jnp.asarray(np.asarray(v)[idx]) for k, v in rows.items()}
                )
            idx = self._ext_ids(range(self._pane_rows))
            stacked = self._layout.unpack_stacked(
                {k: jnp.asarray(np.asarray(v)[idx]) for k, v in rows.items()}, lead=2
            )
            return self._metric.merge_stacked_states(stacked)
        tree = self._merged_state() if self._deferred else self._unpack(self._state)
        if self._win_stacked:
            if self._window.kind == "tumbling":
                return jax.tree.map(lambda x: x[self._pane_cursor], tree)
            return self._metric.merge_stacked_states(tree)
        return tree

    # --------------------------------------------------------- snapshot provenance

    def _snapshot_meta_extra(self) -> Dict[str, Any]:
        extra = super()._snapshot_meta_extra()
        extra.update(
            ragged=1,
            ragged_capacity=self._capacity,
            ragged_groups=self._num_streams,
        )
        return extra

    def _restore_commit(self, state: Any, meta: Dict[str, Any]) -> None:
        if not bool(int(meta.get("ragged", 0) or 0)):
            raise MetricsTPUUserError(
                "snapshot was not written by a ragged engine: plain stream "
                "rows carry no group-key provenance a RaggedEngine could seat "
                "— restore it into the engine kind that wrote it"
            )
        cap = int(meta.get("ragged_capacity", 0) or 0)
        if cap != self._capacity:
            raise MetricsTPUUserError(
                f"ragged snapshot was written at capacity={cap}, this engine "
                f"serves capacity={self._capacity}; per-group buffer columns "
                "only mean row slots under the capacity that wrote them — "
                "restore with a matching capacity= engine"
            )
        g = int(meta.get("ragged_groups", 0) or 0)
        if g != self._num_streams:
            raise MetricsTPUUserError(
                f"ragged snapshot serves {g} groups, this engine {self._num_streams}"
            )
        super()._restore_commit(state, meta)
