"""Ring-buffer telemetry for the streaming engine.

Serving observability without unbounded host memory: a fixed-capacity ring of
per-step records plus monotonic counters. Exported as one JSON document
(``tools/engine_report.py`` pretty-prints it; the bench's
``engine_steady_state`` entry embeds the summary). Records deliberately carry
HOST-side observables only — queue depth at dispatch, padding waste, ingest
time, and the sync latency of the steps that actually blocked (double
buffering means most steps don't) — because device-side step time on a
timeshared virtual mesh is host noise, not signal (docs/benchmarking.md,
"the four hazards").
"""
import json
import math
from typing import Any, Dict, List, Optional

from metrics_tpu.engine.bucketing import BucketPolicy

__all__ = ["EngineStats"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    k = (len(sorted_vals) - 1) * q
    lo, hi = math.floor(k), math.ceil(k)
    if lo == hi:
        return sorted_vals[lo]
    return sorted_vals[lo] * (hi - k) + sorted_vals[hi] * (k - lo)


class EngineStats:
    """Fixed-capacity per-step telemetry ring + lifetime counters."""

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError(f"telemetry capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self.steps = 0
        self.batches_submitted = 0
        self.rows_in = 0
        self.rows_padded = 0
        self.snapshots = 0
        self.resumes = 0

    def record_step(
        self,
        *,
        bucket: int,
        valid: int,
        queue_depth: int,
        ingest_us: float,
        sync_us: Optional[float] = None,
    ) -> None:
        rec = {
            "step": self.steps,
            "bucket": bucket,
            "valid": valid,
            "queue_depth": queue_depth,
            "ingest_us": round(ingest_us, 1),
        }
        if sync_us is not None:
            rec["sync_us"] = round(sync_us, 1)
        self._ring[self.steps % self.capacity] = rec
        self.steps += 1
        self.rows_in += valid
        self.rows_padded += bucket

    def recent(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first."""
        n = min(self.steps, self.capacity)
        start = self.steps % self.capacity if self.steps > self.capacity else 0
        out = []
        for i in range(n):
            rec = self._ring[(start + i) % self.capacity]
            if rec is not None:
                out.append(rec)
        return out

    def summary(self, aot_stats: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        recent = self.recent()
        ingest = sorted(r["ingest_us"] for r in recent)
        syncs = sorted(r["sync_us"] for r in recent if "sync_us" in r)
        depths = [r["queue_depth"] for r in recent]
        out: Dict[str, Any] = {
            "steps": self.steps,
            "batches_submitted": self.batches_submitted,
            "rows_in": self.rows_in,
            "rows_padded": self.rows_padded,
            "padding_waste_fraction": round(
                BucketPolicy.waste_fraction(self.rows_in, self.rows_padded), 4
            ),
            "snapshots": self.snapshots,
            "resumes": self.resumes,
            "queue_depth_max": max(depths) if depths else 0,
            "ingest_us": {
                "p50": round(_percentile(ingest, 0.5), 1) if ingest else None,
                "p95": round(_percentile(ingest, 0.95), 1) if ingest else None,
            },
            "blocked_sync_us": {
                "count": len(syncs),
                "p50": round(_percentile(syncs, 0.5), 1) if syncs else None,
                "p95": round(_percentile(syncs, 0.95), 1) if syncs else None,
            },
        }
        if aot_stats is not None:
            out["compile_cache"] = aot_stats
        return out

    def to_json(self, aot_stats: Optional[Dict[str, Any]] = None) -> str:
        return json.dumps({"summary": self.summary(aot_stats), "recent_steps": self.recent()}, indent=2)

    def export(self, path: str, aot_stats: Optional[Dict[str, Any]] = None) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(aot_stats))
