"""Ring-buffer telemetry for the streaming engine.

Serving observability without unbounded host memory: a fixed-capacity ring of
per-step records plus monotonic counters. Exported as one JSON document
(``tools/engine_report.py`` pretty-prints it; the bench's
``engine_steady_state`` entry embeds the summary). Records deliberately carry
HOST-side observables only — queue depth at dispatch, padding waste, ingest
time, and the sync latency of the steps that actually blocked (double
buffering means most steps don't) — because device-side step time on a
timeshared virtual mesh is host noise, not signal (docs/benchmarking.md,
"the four hazards").
"""
import json
import math
import os
import threading
from typing import Any, Dict, List, Optional

from metrics_tpu.engine.bucketing import BucketPolicy

__all__ = ["EngineStats"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    k = (len(sorted_vals) - 1) * q
    lo, hi = math.floor(k), math.ceil(k)
    if lo == hi:
        return sorted_vals[lo]
    return sorted_vals[lo] * (hi - k) + sorted_vals[hi] * (k - lo)


class EngineStats:
    """Fixed-capacity per-step telemetry ring + lifetime counters."""

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError(f"telemetry capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self.steps = 0
        self.batches_submitted = 0
        self.batches_coalesced = 0  # submitted batches folded into a shared step
        self.megasteps = 0          # steps that carried > 1 submitted batch
        self.rows_in = 0
        self.rows_padded = 0
        self.snapshots = 0
        self.resumes = 0
        # mesh sync accounting: "step" engines pay a collective inside every
        # step (its latency shows up as the per-step sync_us when the
        # dispatcher blocks); "deferred" engines pay collectives only at
        # explicit merge boundaries, recorded here. None = no mesh. The
        # *_us_total counters are LIFETIME sums (unlike the bounded ring), so
        # collective_share compares like with like on runs longer than the
        # ring window.
        self.mesh_sync: Optional[str] = None
        self.merges = 0
        self.merge_us_total = 0.0
        self.wall_us_total = 0.0
        self.sync_us_total = 0.0
        # quantized-sync payload accounting (ISSUE 10): bytes one shard
        # contributed to the fused sync's collectives, split by rider —
        # exact (f32 psum bundle / digit riders / verbatim carrier) vs
        # quantized (block-scaled int8 codes + scales). Counted per boundary
        # merge under deferred sync, per step under step sync; analytic from
        # the state signature (parallel/collectives.py::fused_sync_plan), so
        # the counters cost no device work. Rendered as the OpenMetrics
        # sync_payload_bytes{kind=...} counters.
        self.sync_payload_exact_bytes = 0
        self.sync_payload_quant_bytes = 0
        # fault-tolerance accounting (ISSUE 6): injected faults by site, and
        # every recovery action the engine took — retries with backoff,
        # pre-step rollbacks, pallas→xla kernel demotions, coalesce
        # degradations/shrinks, watchdog expiries, quarantined (dead-
        # lettered) batches, snapshot write failures and restore fallbacks.
        # All lifetime counters; rendered by tools/engine_report.py.
        self.faults_injected: Dict[str, int] = {}
        # kernel-dispatch fallbacks by reason (ISSUE 16): every time the
        # engine's megastep plan (or the per-leaf dispatcher on its behalf)
        # declined the fused path, keyed by WHY — ``engine:<reason>`` for
        # whole-engine ineligibility (no arena, replicated mesh, stacked
        # multistream layout), ``dtype.<key>:<reason>`` for a single arena
        # dtype that fell back per-leaf (strategy/dtype/vmem). Construction-
        # time plan verdicts count ONCE (the plan is static), so the counter
        # reads as "how much of this engine's state runs off the fused path",
        # not a per-step rate. Rendered as the OpenMetrics
        # ``kernel_fallbacks_total{reason=...}`` counter.
        self.kernel_fallbacks: Dict[str, int] = {}
        self.retries = 0
        self.rollbacks = 0
        self.kernel_demotions = 0
        self.coalesce_degraded = 0
        self.coalesce_shrinks = 0
        self.watchdog_timeouts = 0
        self.quarantined_batches = 0
        self.quarantined_rows = 0
        self.snapshot_failures = 0
        self.snapshot_fallbacks = 0
        # stream-sharded serving (ISSUE 9): host-side routing + LRU paging.
        # page_hits = submitted rows' streams already resident; page_faults =
        # streams faulted into an arena slot (from host spill or init);
        # page_ins/page_outs = row movements between HBM and host RAM. The
        # *_streams values are point-in-time gauges the engine refreshes at
        # scrape boundaries (resident = occupied arena slots across shards,
        # spilled = rows currently living in host RAM).
        self.routed_steps = 0
        # device computations issued by MultiStreamEngine.result()/results():
        # the dispatch-count observable — results() must add exactly ONE per
        # call, for any S (the batched all-streams program)
        self.result_device_calls = 0
        self.page_hits = 0
        self.page_faults = 0
        self.page_ins = 0
        self.page_outs = 0
        self.resident_streams = 0
        self.spilled_streams = 0
        # host-RAM bytes of the spill store at the last gauge refresh — the
        # footprint compress_payloads quantizes (ISSUE 10)
        self.spilled_bytes = 0
        # cross-thread counter lock (ISSUE 11, widened by ISSUE 14): every
        # counter that PRODUCER threads bump concurrently with the dispatcher
        # — admission outcomes by priority class, retries, deferred reads,
        # submitted batches, fault firings — goes through a record_* method
        # under this lock: a bare `+=`/`dict[k] += 1` is a read-modify-write
        # the GIL does not make atomic (counter semantics pinned under
        # concurrent submits in tests/engine/test_admission.py and
        # tests/engine/test_stats_edges.py; the guarded set is DECLARED in
        # analysis/rules/locks.py and checked by `make analyze`).
        self._counter_lock = threading.Lock()
        self.admission_admitted: Dict[int, int] = {}
        self.admission_rejected: Dict[int, int] = {}
        self.admission_shed: Dict[int, int] = {}
        # ladder_level is a gauge (current rung count engaged); transitions a
        # lifetime counter; deferred_reads counts result() calls served from
        # the stale-read cache while the defer_cold_reads rung was engaged.
        self.ladder_level = 0
        self.ladder_transitions = 0
        self.deferred_reads = 0
        # live elastic resharding: count + the last transition's coordinates
        # (from/to world, replay cursor) — what engine_report surfaces
        self.reshards = 0
        self.reshard_last: Optional[Dict[str, Any]] = None
        # windowed semantics (ISSUE 13): pane-ring rotation accounting.
        # window_policy is the canonical policy tag (set at engine
        # construction, None for cumulative engines — their telemetry
        # documents stay byte-stable); live_panes/pane_cursor are gauges
        # refreshed at each rotation, the counters are lifetime totals.
        self.window_policy: Optional[str] = None
        self.pane_rotations = 0
        self.ewma_decays = 0
        self.live_panes = 0
        self.pane_cursor = 0
        self.drift_evals = 0
        self.drift_alarms = 0
        # fleet serving (ISSUE 15): host-topology gauges + per-host boundary
        # counters. fleet_hosts None = not fleet-managed (every pre-fleet
        # telemetry document stays byte-stable). Counters move on the fleet
        # caller's thread only (ingest/result/snapshot are per-host
        # single-threaded boundaries), but ride the counter lock anyway —
        # the lock cost is one boundary op, not a hot-path step.
        self.fleet_hosts: Optional[int] = None
        self.fleet_process_id = 0
        self.fleet_streams_owned = 0
        self.fleet_ingested = 0   # plan batches homed here and submitted
        self.fleet_skipped = 0    # plan batches homed on another host
        self.fleet_merges = 0     # cross-host boundary folds (result/results)
        self.fleet_merge_us_total = 0.0
        self.fleet_barriers = 0   # snapshot-cut barrier entries
        self.fleet_cuts = 0       # globally consistent snapshot cuts written
        # the CROSS-HOST fold's own payload accounting — deliberately NOT
        # the shared sync_payload_* counters: a fleet host with a local
        # deferred mesh also pays a host-local boundary merge per fold
        # (recorded there), and summing the two surfaces would double-count
        # what actually crossed hosts
        self.fleet_payload_exact_bytes = 0
        self.fleet_payload_quant_bytes = 0
        # fleet tenancy (ISSUE 20): the hierarchical fold's INTRA-host leg
        # (bytes the host-local exact merge folds per boundary — scales with
        # this host's stream residency) vs the cross legs above (scale with
        # hosts), plus the stream pager's spill gauges — per-host device
        # residency stays flat while spilled tenants grow host RAM only
        self.fleet_payload_intra_bytes = 0
        self.fleet_spill_rows = 0
        self.fleet_spill_bytes = 0
        self.fleet_resident_rows = 0
        # ragged serving (ISSUE 17): group-keyed ingestion. ragged_groups
        # None = not a ragged engine (every prior telemetry document stays
        # byte-stable); capacity is the per-group row budget gauge. The
        # counters ride the counter lock — submits come from producer
        # threads, the overflow counter from reader threads (aggregate()).
        self.ragged_groups: Optional[int] = None
        self.ragged_capacity = 0
        self.ragged_batches = 0
        self.ragged_rows = 0
        self.ragged_groups_touched = 0
        self.ragged_overflows = 0
        # aggregate reads by path (ISSUE 18): device = the compiled fold /
        # corpus-bundle path, oracle = the host eager replay.  agg_blocks
        # counts paged-sweep block dispatches — G-independent for a fixed
        # touched population, the O(1)-dispatch observable the smoke pins.
        self.ragged_agg_device_reads = 0
        self.ragged_agg_oracle_reads = 0
        self.ragged_agg_blocks = 0

    def record_admission(self, outcome: str, priority: int) -> None:
        """One admission verdict (``"admitted"``/``"rejected"``/``"shed"``)
        for a submit in ``priority`` class — called from producer threads,
        so the bump is serialized under the admission lock."""
        target = {
            "admitted": self.admission_admitted,
            "rejected": self.admission_rejected,
            "shed": self.admission_shed,
        }[outcome]
        with self._counter_lock:
            target[int(priority)] = target.get(int(priority), 0) + 1

    def record_submitted(self) -> None:
        """One accepted submit. Locked: producers submit CONCURRENTLY, and a
        bare ``batches_submitted += 1`` on their threads loses increments —
        the same RMW class the admission counters were locked for in PR 11
        (found by the concurrency plane's lockset rule, ISSUE 14)."""
        with self._counter_lock:
            self.batches_submitted += 1

    def record_retry(self) -> None:
        """One bounded-retry attempt. Locked: since ISSUE 11 admission-site
        retries come from PRODUCER threads concurrently with the
        dispatcher's step/merge retries — a bare ``+=`` can lose one."""
        with self._counter_lock:
            self.retries += 1

    def record_deferred_read(self) -> None:
        """One stale read served by the defer_cold_reads rung — reader
        threads call ``result()`` concurrently, so the bump locks."""
        with self._counter_lock:
            self.deferred_reads += 1

    def record_reshard(self, from_world: int, to_world: int, cursor: int, auto: bool) -> None:
        """One live reshard transition (manual or shard-loss-triggered)."""
        self.reshards += 1
        self.reshard_last = {
            "from_world": int(from_world),
            "to_world": int(to_world),
            "cursor": int(cursor),
            "auto": bool(auto),
        }

    def admission_summary(self) -> Optional[Dict[str, Any]]:
        """The admission/ladder block for :meth:`summary` — None when the
        engine ran with neither an admission policy nor a ladder (every
        pre-ISSUE-11 engine: its telemetry document is unchanged). Priority
        keys stringify for JSON round-trip stability."""
        with self._counter_lock:
            admitted = dict(self.admission_admitted)
            rejected = dict(self.admission_rejected)
            shed = dict(self.admission_shed)
        if (
            not (admitted or rejected or shed)
            and not self.ladder_transitions
            and not self.ladder_level
            and not self.deferred_reads
        ):
            return None
        return {
            "admitted_by_priority": {str(k): v for k, v in sorted(admitted.items())},
            "rejected_by_priority": {str(k): v for k, v in sorted(rejected.items())},
            "shed_by_priority": {str(k): v for k, v in sorted(shed.items())},
            "ladder_level": self.ladder_level,
            "ladder_transitions": self.ladder_transitions,
            "deferred_reads": self.deferred_reads,
        }

    def record_rotation(self, cursor: int, live: int, ewma: bool) -> None:
        """One committed pane rotation (dispatcher thread only)."""
        self.pane_rotations += 1
        if ewma:
            self.ewma_decays += 1
        self.pane_cursor = int(cursor)
        self.live_panes = int(live)

    def windows_summary(self) -> Optional[Dict[str, Any]]:
        """The windowed-semantics block for :meth:`summary` — None for
        cumulative engines (no window policy was ever set), so every
        pre-window telemetry document is unchanged."""
        if self.window_policy is None:
            return None
        out: Dict[str, Any] = {
            "policy": self.window_policy,
            "pane_rotations": self.pane_rotations,
            "live_panes": self.live_panes,
            "pane_cursor": self.pane_cursor,
        }
        if self.ewma_decays:
            out["ewma_decays"] = self.ewma_decays
        if self.drift_evals or self.drift_alarms:
            out["drift"] = {
                "evals": self.drift_evals,
                "alarms": self.drift_alarms,
            }
        return out

    def record_ragged_submit(self, rows: int, groups: int) -> None:
        """One accepted ragged submit: ``rows`` payload rows spanning
        ``groups`` distinct group keys. Locked — ragged producers submit
        concurrently like any stream producers."""
        with self._counter_lock:
            self.ragged_batches += 1
            self.ragged_rows += int(rows)
            self.ragged_groups_touched += int(groups)

    def record_ragged_overflow(self, groups: int) -> None:
        """One aggregate read refused because ``groups`` group(s) exceeded
        capacity. Locked — reader threads call ``aggregate()`` concurrently
        with producers."""
        with self._counter_lock:
            self.ragged_overflows += int(groups)

    def record_ragged_aggregate(self, path: str, blocks: int = 0) -> None:
        """One aggregate ``result()`` served: ``path`` is ``"device"`` (the
        compiled fold / corpus bundle) or ``"oracle"`` (the host eager
        replay); ``blocks`` counts the paged sweep's block dispatches (0 off
        ``group_shard``). Locked — readers aggregate concurrently with
        producers."""
        with self._counter_lock:
            if path == "device":
                self.ragged_agg_device_reads += 1
            else:
                self.ragged_agg_oracle_reads += 1
            self.ragged_agg_blocks += int(blocks)

    def ragged_summary(self) -> Optional[Dict[str, Any]]:
        """The ragged-serving block for :meth:`summary` — None for engines
        that never declared a group universe (every non-ragged telemetry
        document stays byte-stable)."""
        if self.ragged_groups is None:
            return None
        with self._counter_lock:
            return {
                "groups": self.ragged_groups,
                "capacity": self.ragged_capacity,
                "batches": self.ragged_batches,
                "rows": self.ragged_rows,
                "groups_touched": self.ragged_groups_touched,
                "overflows": self.ragged_overflows,
                "agg_device_reads": self.ragged_agg_device_reads,
                "agg_oracle_reads": self.ragged_agg_oracle_reads,
                "agg_blocks": self.ragged_agg_blocks,
            }

    def record_fleet_ingest(self, owned: bool) -> None:
        """One plan batch seen by the fleet ingest path: ``owned`` batches
        were homed here (and submitted), the rest belong to another host."""
        with self._counter_lock:
            if owned:
                self.fleet_ingested += 1
            else:
                self.fleet_skipped += 1

    def record_fleet_merge(
        self,
        merge_us: float,
        exact_bytes: int = 0,
        quant_bytes: int = 0,
        intra_bytes: int = 0,
    ) -> None:
        """One cross-host boundary fold (the fleet ``result()``/``results()``
        collective), with the bytes THIS host contributed to it —
        ``intra_bytes`` is the hierarchical fold's host-LOCAL exact leg (the
        logical state this host folds before anything crosses the wire),
        exact/quant are the cross-host legs."""
        with self._counter_lock:
            self.fleet_merges += 1
            self.fleet_merge_us_total += float(merge_us)
            self.fleet_payload_exact_bytes += int(exact_bytes)
            self.fleet_payload_quant_bytes += int(quant_bytes)
            self.fleet_payload_intra_bytes += int(intra_bytes)

    def record_fleet_tenancy(
        self, resident_rows: int, spill_rows: int, spill_bytes: int
    ) -> None:
        """Refresh the per-host tenancy gauges from the stream pager (device-
        resident rows stay FLAT as the stream universe grows; spilled tenants
        cost host RAM only)."""
        with self._counter_lock:
            self.fleet_resident_rows = int(resident_rows)
            self.fleet_spill_rows = int(spill_rows)
            self.fleet_spill_bytes = int(spill_bytes)

    def record_fleet_barrier(self) -> None:
        """One snapshot-cut barrier entered (and agreed) by this host."""
        with self._counter_lock:
            self.fleet_barriers += 1

    def record_fleet_cut(self) -> None:
        """One globally consistent snapshot cut written by this host."""
        with self._counter_lock:
            self.fleet_cuts += 1

    def fleet_summary(self) -> Optional[Dict[str, Any]]:
        """The fleet block for :meth:`summary` — None unless the engine is
        fleet-managed (``FleetEngine`` set ``fleet_hosts``), so every
        single-process telemetry document stays byte-stable."""
        if self.fleet_hosts is None:
            return None
        return {
            "num_hosts": int(self.fleet_hosts),
            "process_id": int(self.fleet_process_id),
            "streams_owned": int(self.fleet_streams_owned),
            "ingested": self.fleet_ingested,
            "skipped": self.fleet_skipped,
            "merges": self.fleet_merges,
            "merge_us_total": self.fleet_merge_us_total,
            "barriers": self.fleet_barriers,
            "cuts": self.fleet_cuts,
            # the cross-host fold's OWN bytes (lifetime totals) — host-local
            # mesh merges keep the ordinary sync_payload counters, so the
            # two surfaces never double-count one boundary
            "sync_payload_bytes": {
                "exact": self.fleet_payload_exact_bytes,
                "quantized": self.fleet_payload_quant_bytes,
            },
            # hierarchical-fold legs + tenancy gauges (ISSUE 20): intra is
            # the host-local exact leg's lifetime bytes; the gauges mirror
            # the stream pager so capacity scaling is observable per host
            "payload_intra_bytes": self.fleet_payload_intra_bytes,
            "tenancy": {
                "resident_rows": self.fleet_resident_rows,
                "spill_rows": self.fleet_spill_rows,
                "spill_bytes": self.fleet_spill_bytes,
            },
        }

    def reshard_summary(self) -> Optional[Dict[str, Any]]:
        """The elastic-reshard block — None until the engine resharded."""
        if not self.reshards:
            return None
        out: Dict[str, Any] = {"reshards": self.reshards}
        if self.reshard_last is not None:
            out["last"] = dict(self.reshard_last)
        return out

    def record_fault(self, site: str) -> None:
        """One injected fault fired at ``site`` (chaos harness accounting).
        Locked: since ISSUE 11 the ``admission`` site fires on PRODUCER
        threads concurrently with the dispatcher's sites — an unlocked
        ``dict[site] += 1`` can lose a firing and break the chaos smokes'
        every-site-fired accounting (found by the lockset rule, ISSUE 14)."""
        with self._counter_lock:
            self.faults_injected[site] = self.faults_injected.get(site, 0) + 1

    def record_kernel_fallback(self, reason: str) -> None:
        """One kernel-dispatch fallback verdict under ``reason``. Locked for
        the same RMW class as :meth:`record_fault` — engines are built (and
        their plans judged) on whatever thread constructs them, concurrently
        with a dispatcher scraping another engine's shared stats object."""
        with self._counter_lock:
            self.kernel_fallbacks[str(reason)] = self.kernel_fallbacks.get(str(reason), 0) + 1

    def kernel_fallbacks_by_reason(self) -> Dict[str, int]:
        """One consistent snapshot of the per-reason fallback counts."""
        with self._counter_lock:
            return dict(self.kernel_fallbacks)

    def kernels_summary(self) -> Optional[Dict[str, Any]]:
        """The kernel-dispatch block for :meth:`summary` — None when no
        fallback was ever recorded (every fully-fused or non-megastep engine:
        its telemetry document stays byte-stable)."""
        fallbacks = self.kernel_fallbacks_by_reason()
        if not fallbacks:
            return None
        return {"fallbacks_by_reason": {k: fallbacks[k] for k in sorted(fallbacks)}}

    def faults_by_site(self) -> Dict[str, int]:
        """One consistent snapshot of the per-site fault counts. Locked: the
        admission site fires on producer threads, and an unlocked
        ``dict(...)`` copy can see the dict resize mid-iteration."""
        with self._counter_lock:
            return dict(self.faults_injected)

    def fault_summary(self) -> Optional[Dict[str, Any]]:
        """The fault/recovery block for :meth:`summary` — None when this
        engine saw no fault activity at all (the common case keeps its
        telemetry document unchanged)."""
        counters = {
            "retries": self.retries,
            "rollbacks": self.rollbacks,
            "kernel_demotions": self.kernel_demotions,
            "coalesce_degraded": self.coalesce_degraded,
            "coalesce_shrinks": self.coalesce_shrinks,
            "watchdog_timeouts": self.watchdog_timeouts,
            "quarantined_batches": self.quarantined_batches,
            "quarantined_rows": self.quarantined_rows,
            "snapshot_failures": self.snapshot_failures,
            "snapshot_fallbacks": self.snapshot_fallbacks,
        }
        injected = self.faults_by_site()
        if not injected and not any(counters.values()):
            return None
        return {"injected": injected, **counters}

    def paging_summary(self) -> Optional[Dict[str, Any]]:
        """The stream-sharding/paging block for :meth:`summary` — None for
        engines with no routing OR residency activity (every non-sharded
        engine: only stream-sharded code paths touch these fields), so their
        telemetry documents are unchanged. The gauge clause matters for a
        freshly RESTORED sharded engine: it has seated slots (and possibly
        spilled rows) before its first routed step, and its scrape must say
        so."""
        if (
            not self.routed_steps
            and not (self.page_hits or self.page_faults)
            and not (self.resident_streams or self.spilled_streams)
        ):
            return None
        total = self.page_hits + self.page_faults
        return {
            "routed_steps": self.routed_steps,
            "page_hits": self.page_hits,
            "page_faults": self.page_faults,
            "page_hit_rate": round(self.page_hits / total, 4) if total else None,
            "page_ins": self.page_ins,
            "page_outs": self.page_outs,
            "resident_streams": self.resident_streams,
            "spilled_streams": self.spilled_streams,
            "spilled_bytes": self.spilled_bytes,
        }

    def record_merge(self, merge_us: float) -> None:
        """One deferred-sync boundary merge (result()/snapshot/restore): the
        fused collective bundle's host-observed latency."""
        self.merges += 1
        self.merge_us_total += float(merge_us)

    def record_sync_payload(self, exact_bytes: int, quant_bytes: int) -> None:
        """One fused sync's per-shard payload, split by rider kind."""
        self.sync_payload_exact_bytes += int(exact_bytes)
        self.sync_payload_quant_bytes += int(quant_bytes)

    def record_step(
        self,
        *,
        bucket: int,
        valid: int,
        queue_depth: int,
        ingest_us: float,
        sync_us: Optional[float] = None,
        pad_us: Optional[float] = None,
        queue_wait_us: Optional[float] = None,
        wall_us: Optional[float] = None,
        coalesced: Optional[int] = None,
    ) -> None:
        rec = {
            "step": self.steps,
            "bucket": bucket,
            "valid": valid,
            "queue_depth": queue_depth,
            "ingest_us": round(ingest_us, 1),
        }
        if sync_us is not None:
            rec["sync_us"] = round(sync_us, 1)
            self.sync_us_total += float(sync_us)
        if pad_us is not None:
            rec["pad_us"] = round(pad_us, 1)
        if queue_wait_us is not None:
            rec["queue_wait_us"] = round(queue_wait_us, 1)
        if wall_us is not None:
            rec["wall_us"] = round(wall_us, 1)
            self.wall_us_total += float(wall_us) + float(queue_wait_us or 0.0)
        if coalesced is not None:
            rec["coalesced"] = int(coalesced)
            if coalesced > 1:
                self.megasteps += 1
                self.batches_coalesced += coalesced
        self._ring[self.steps % self.capacity] = rec
        self.steps += 1
        self.rows_in += valid
        self.rows_padded += bucket

    def recent(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first."""
        n = min(self.steps, self.capacity)
        start = self.steps % self.capacity if self.steps > self.capacity else 0
        out = []
        for i in range(n):
            rec = self._ring[(start + i) % self.capacity]
            if rec is not None:
                out.append(rec)
        return out

    def summary(self, aot_stats: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        recent = self.recent()
        ingest = sorted(r["ingest_us"] for r in recent)
        syncs = sorted(r["sync_us"] for r in recent if "sync_us" in r)
        depths = [r["queue_depth"] for r in recent]
        out: Dict[str, Any] = {
            "steps": self.steps,
            "batches_submitted": self.batches_submitted,
            "rows_in": self.rows_in,
            "rows_padded": self.rows_padded,
            "padding_waste_fraction": round(
                BucketPolicy.waste_fraction(self.rows_in, self.rows_padded), 4
            ),
            "snapshots": self.snapshots,
            "resumes": self.resumes,
            "queue_depth_max": max(depths) if depths else 0,
            "ingest_us": {
                "p50": round(_percentile(ingest, 0.5), 1) if ingest else None,
                "p95": round(_percentile(ingest, 0.95), 1) if ingest else None,
            },
            "blocked_sync_us": {
                "count": len(syncs),
                "p50": round(_percentile(syncs, 0.5), 1) if syncs else None,
                "p95": round(_percentile(syncs, 0.95), 1) if syncs else None,
            },
            "coalesce": {
                "megasteps": self.megasteps,
                "batches_coalesced": self.batches_coalesced,
                "batches_per_step_mean": round(
                    self.batches_submitted / self.steps, 3
                ) if self.steps else None,
            },
        }
        shares = self._host_time_shares(recent, self.mesh_sync)
        if shares is not None:
            out["host_time_shares"] = shares
        paging = self.paging_summary()
        if paging is not None:
            out["paging"] = paging
        admission = self.admission_summary()
        if admission is not None:
            out["admission"] = admission
        windows = self.windows_summary()
        if windows is not None:
            out["windows"] = windows
        reshard = self.reshard_summary()
        if reshard is not None:
            out["reshard"] = reshard
        fleet = self.fleet_summary()
        if fleet is not None:
            out["fleet"] = fleet
        ragged = self.ragged_summary()
        if ragged is not None:
            out["ragged"] = ragged
        faults = self.fault_summary()
        if faults is not None:
            out["faults"] = faults
        kernels = self.kernels_summary()
        if kernels is not None:
            out["kernels"] = kernels
        if self.mesh_sync is not None:
            out["mesh_sync"] = self._mesh_sync_summary()
        if aot_stats is not None:
            out["compile_cache"] = aot_stats
        return out

    def _mesh_sync_summary(self) -> Dict[str, Any]:
        """Where this mesh engine's collective time lives: inside blocked
        steps (``step`` mode) or at explicit merge boundaries (``deferred``
        mode). ``collective_share`` uses LIFETIME totals in both modes
        (merges are boundary events the bounded step ring never sees — mixing
        a lifetime merge sum with a windowed wall would inflate the share
        without bound on long runs) — the step-vs-deferred comparison
        ``tools/engine_report.py`` renders.

        The step-mode share is an UPPER BOUND (flagged in the summary): the
        blocked wait covers the whole in-step program — masked-update compute
        AND the collective bundle — because the host cannot observe where
        device time went inside one executable. A compute-heavy metric can
        dominate that wait with update math; before attributing it to the
        collective, A/B the same stream against ``mesh_sync="deferred"`` (or
        the ``engine_mesh_dispatch`` step-latency isolate) — only the delta
        is the collective."""
        out: Dict[str, Any] = {
            "mode": self.mesh_sync,
            "merges": self.merges,
            "merge_us_total": round(self.merge_us_total, 1),
        }
        if self.sync_payload_exact_bytes or self.sync_payload_quant_bytes:
            out["sync_payload_bytes"] = {
                "exact": self.sync_payload_exact_bytes,
                "quantized": self.sync_payload_quant_bytes,
            }
        if self.mesh_sync in ("deferred", "stream_shard"):
            # stream_shard engines route host-side and carry NO steady-state
            # collectives either — boundary merges (deferred) or per-read row
            # gathers (stream_shard) are the only cross-shard traffic, so the
            # deferred-style share math applies to both
            denom = self.wall_us_total + self.merge_us_total
            out["collective_share"] = (
                round(self.merge_us_total / denom, 4) if denom > 0 else None
            )
        else:
            out["collective_share"] = (
                round(self.sync_us_total / self.wall_us_total, 4)
                if self.wall_us_total > 0
                else None
            )
            out["collective_share_is_upper_bound"] = True
        return out

    @staticmethod
    def _host_time_shares(
        recent: List[Dict[str, Any]], mesh_sync: Optional[str] = None
    ) -> Optional[Dict[str, Any]]:
        """Attribute the dispatcher's wall time over the ring window: padding,
        queue wait (idle, producer-bound), blocked device sync, and the
        residual dispatch overhead (program-call + upload — the share the
        arena/coalescing optimizations exist to amortize). The ``regime``
        label is what ``tools/engine_report.py`` surfaces: a step loop is
        *dispatch-bound* when the residual dominates, *pad-bound* when host
        padding/concat does, *starved* when the queue wait does. A dominant
        blocked-sync share reads *device-bound* off-mesh and under deferred
        sync, but *sync-bound* for a step-sync mesh engine — blocked there
        means waiting on SYNCHRONIZED steps, which bundle the cross-chip
        collective WITH the update compute (the host cannot split device
        time inside one executable): treat it as "the per-step sync
        discipline is the bottleneck, up to its compute content" and confirm
        with a ``mesh_sync="deferred"`` A/B before concluding a faster
        device wouldn't help (see ``_mesh_sync_summary``)."""
        timed = [r for r in recent if "wall_us" in r]
        if not timed:
            return None
        wall = sum(r["wall_us"] for r in timed)
        wait = sum(r.get("queue_wait_us", 0.0) for r in timed)
        pad = sum(r.get("pad_us", 0.0) for r in timed)
        sync = sum(r.get("sync_us", 0.0) for r in timed)
        total = wall + wait
        if total <= 0:
            return None
        dispatch = max(0.0, wall - pad - sync)
        shares = {
            "pad": round(pad / total, 4),
            "queue_wait": round(wait / total, 4),
            "blocked_sync": round(sync / total, 4),
            "dispatch": round(dispatch / total, 4),
        }
        regime = max(("dispatch", "pad", "queue_wait", "blocked_sync"), key=lambda k: shares[k])
        shares["regime"] = {
            "dispatch": "dispatch-bound",
            "pad": "pad-bound",
            "queue_wait": "starved",
            "blocked_sync": "sync-bound" if mesh_sync == "step" else "device-bound",
        }[regime]
        shares["window_steps"] = len(timed)
        return shares

    def to_json(
        self,
        aot_stats: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """The exported telemetry document. ``extra`` merges additional
        top-level sections (the engine adds ``trace`` — the flight recorder's
        SLO summary — when one is attached)."""
        doc: Dict[str, Any] = {"summary": self.summary(aot_stats), "recent_steps": self.recent()}
        if extra:
            doc.update(extra)
        return json.dumps(doc, indent=2)

    def export(
        self,
        path: str,
        aot_stats: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> None:
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json(aot_stats, extra=extra))
