"""Ring-buffer telemetry for the streaming engine.

Serving observability without unbounded host memory: a fixed-capacity ring of
per-step records plus monotonic counters. Exported as one JSON document
(``tools/engine_report.py`` pretty-prints it; the bench's
``engine_steady_state`` entry embeds the summary). Records deliberately carry
HOST-side observables only — queue depth at dispatch, padding waste, ingest
time, and the sync latency of the steps that actually blocked (double
buffering means most steps don't) — because device-side step time on a
timeshared virtual mesh is host noise, not signal (docs/benchmarking.md,
"the four hazards").
"""
import json
import math
from typing import Any, Dict, List, Optional

from metrics_tpu.engine.bucketing import BucketPolicy

__all__ = ["EngineStats"]


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    k = (len(sorted_vals) - 1) * q
    lo, hi = math.floor(k), math.ceil(k)
    if lo == hi:
        return sorted_vals[lo]
    return sorted_vals[lo] * (hi - k) + sorted_vals[hi] * (k - lo)


class EngineStats:
    """Fixed-capacity per-step telemetry ring + lifetime counters."""

    def __init__(self, capacity: int = 1024):
        if capacity <= 0:
            raise ValueError(f"telemetry capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.capacity
        self.steps = 0
        self.batches_submitted = 0
        self.batches_coalesced = 0  # submitted batches folded into a shared step
        self.megasteps = 0          # steps that carried > 1 submitted batch
        self.rows_in = 0
        self.rows_padded = 0
        self.snapshots = 0
        self.resumes = 0

    def record_step(
        self,
        *,
        bucket: int,
        valid: int,
        queue_depth: int,
        ingest_us: float,
        sync_us: Optional[float] = None,
        pad_us: Optional[float] = None,
        queue_wait_us: Optional[float] = None,
        wall_us: Optional[float] = None,
        coalesced: Optional[int] = None,
    ) -> None:
        rec = {
            "step": self.steps,
            "bucket": bucket,
            "valid": valid,
            "queue_depth": queue_depth,
            "ingest_us": round(ingest_us, 1),
        }
        if sync_us is not None:
            rec["sync_us"] = round(sync_us, 1)
        if pad_us is not None:
            rec["pad_us"] = round(pad_us, 1)
        if queue_wait_us is not None:
            rec["queue_wait_us"] = round(queue_wait_us, 1)
        if wall_us is not None:
            rec["wall_us"] = round(wall_us, 1)
        if coalesced is not None:
            rec["coalesced"] = int(coalesced)
            if coalesced > 1:
                self.megasteps += 1
                self.batches_coalesced += coalesced
        self._ring[self.steps % self.capacity] = rec
        self.steps += 1
        self.rows_in += valid
        self.rows_padded += bucket

    def recent(self) -> List[Dict[str, Any]]:
        """Ring contents, oldest first."""
        n = min(self.steps, self.capacity)
        start = self.steps % self.capacity if self.steps > self.capacity else 0
        out = []
        for i in range(n):
            rec = self._ring[(start + i) % self.capacity]
            if rec is not None:
                out.append(rec)
        return out

    def summary(self, aot_stats: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        recent = self.recent()
        ingest = sorted(r["ingest_us"] for r in recent)
        syncs = sorted(r["sync_us"] for r in recent if "sync_us" in r)
        depths = [r["queue_depth"] for r in recent]
        out: Dict[str, Any] = {
            "steps": self.steps,
            "batches_submitted": self.batches_submitted,
            "rows_in": self.rows_in,
            "rows_padded": self.rows_padded,
            "padding_waste_fraction": round(
                BucketPolicy.waste_fraction(self.rows_in, self.rows_padded), 4
            ),
            "snapshots": self.snapshots,
            "resumes": self.resumes,
            "queue_depth_max": max(depths) if depths else 0,
            "ingest_us": {
                "p50": round(_percentile(ingest, 0.5), 1) if ingest else None,
                "p95": round(_percentile(ingest, 0.95), 1) if ingest else None,
            },
            "blocked_sync_us": {
                "count": len(syncs),
                "p50": round(_percentile(syncs, 0.5), 1) if syncs else None,
                "p95": round(_percentile(syncs, 0.95), 1) if syncs else None,
            },
            "coalesce": {
                "megasteps": self.megasteps,
                "batches_coalesced": self.batches_coalesced,
                "batches_per_step_mean": round(
                    self.batches_submitted / self.steps, 3
                ) if self.steps else None,
            },
        }
        shares = self._host_time_shares(recent)
        if shares is not None:
            out["host_time_shares"] = shares
        if aot_stats is not None:
            out["compile_cache"] = aot_stats
        return out

    @staticmethod
    def _host_time_shares(recent: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
        """Attribute the dispatcher's wall time over the ring window: padding,
        queue wait (idle, producer-bound), blocked device sync (device-bound),
        and the residual dispatch overhead (program-call + upload — the share
        the arena/coalescing optimizations exist to amortize). The ``regime``
        label is what ``tools/engine_report.py`` surfaces: a step loop is
        *dispatch-bound* when the residual dominates, *pad-bound* when host
        padding/concat does, *device-bound* when blocked sync does, *starved*
        when the queue wait does."""
        timed = [r for r in recent if "wall_us" in r]
        if not timed:
            return None
        wall = sum(r["wall_us"] for r in timed)
        wait = sum(r.get("queue_wait_us", 0.0) for r in timed)
        pad = sum(r.get("pad_us", 0.0) for r in timed)
        sync = sum(r.get("sync_us", 0.0) for r in timed)
        total = wall + wait
        if total <= 0:
            return None
        dispatch = max(0.0, wall - pad - sync)
        shares = {
            "pad": round(pad / total, 4),
            "queue_wait": round(wait / total, 4),
            "blocked_sync": round(sync / total, 4),
            "dispatch": round(dispatch / total, 4),
        }
        regime = max(("dispatch", "pad", "queue_wait", "blocked_sync"), key=lambda k: shares[k])
        shares["regime"] = {
            "dispatch": "dispatch-bound",
            "pad": "pad-bound",
            "queue_wait": "starved",
            "blocked_sync": "device-bound",
        }[regime]
        shares["window_steps"] = len(timed)
        return shares

    def to_json(self, aot_stats: Optional[Dict[str, Any]] = None) -> str:
        return json.dumps({"summary": self.summary(aot_stats), "recent_steps": self.recent()}, indent=2)

    def export(self, path: str, aot_stats: Optional[Dict[str, Any]] = None) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(aot_stats))
