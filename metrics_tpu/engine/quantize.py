"""Block-scaled int8 codec for state at REST: compressed snapshot payloads
and compressed pager rows (ISSUE 10).

The wire codec (``parallel/collectives.py``: ``Q8_BLOCK`` absmax blocks, int8
codes, f32 scales) extended to stored state, so host RAM and snapshot disk
scale with the QUANTIZED footprint — the same ``sync_precision`` policy
decides what compresses: float ``sum`` accumulators a metric declared
``"q8_block"`` for; counts, cat buffers and min/max states stay verbatim
(their restore is a bit-exactness contract). Error model: one encode→decode
round-trip per element, ``|err| <= block_absmax / 254`` (plus the denormal
flush floor) — the SAME per-element bound the quantized collective rider
declares, checked by the same oracle (``q8_sum_error_bound`` on a 1-row
stack).

Two storage forms, matching the two state-at-rest layouts in the engine:

* **Tree form** (``encode_state_tree``/``decode_state_tree``): the logical
  (possibly shard-stacked) state pytree of a snapshot. A quantized leaf is
  replaced by a SELF-DESCRIBING dict (marker, codes, scales, shape, dtype) —
  decode needs no layout, so any engine in the restore matrix can unwrap it.
  The snapshot's sha256 integrity sidecar hashes the payload AS SAVED, i.e.
  over the compressed bytes.
* **Buffer form** (:class:`ArenaRowCodec`): the per-dtype arena vectors the
  stream pager spills (``engine/paging.py``) and the stream-sharded
  ``(world, resident, n)`` snapshot arenas. The codec is built from the
  metric's :class:`~metrics_tpu.engine.arena.ArenaLayout` + policy: the
  quantized leaves' element positions within each dtype buffer split into a
  coded section (``<dtype>#q8c`` + ``<dtype>#q8s``) and a verbatim remainder
  (``<dtype>#ex``). Buffer form is NOT self-describing (the positions come
  from the layout), so snapshot meta carries ``codec_fp`` — the metric's
  ``sync_precision_tag()`` — and restore refuses a tag mismatch instead of
  unscrambling rows with the wrong plan.

Both forms are pure host-numpy functions of their input — the engine's
``quant_encode``/``quant_decode`` chaos sites can retry them without ever
double-applying scales.
"""
from typing import Any, Dict, List, Optional

import numpy as np

from metrics_tpu.parallel.collectives import Q8_BLOCK, Q8_FLUSH

__all__ = [
    "ArenaRowCodec",
    "CODEC_ID",
    "decode_state_tree",
    "encode_state_tree",
    "is_q8_leaf",
    "q8_decode_array",
    "q8_encode_array",
]

#: the codec id snapshot meta carries (``meta["codec"]``) — names the scheme
#: AND the block size, so a future block-size change is a different codec.
CODEC_ID = f"q8b{Q8_BLOCK}"

_MARKER = "__q8b__"


def _encode_blocks(flat: np.ndarray, block: int) -> "tuple[np.ndarray, np.ndarray]":
    """Rows of a ``(rows, n)`` f32 matrix -> (codes int8 (rows, nb*block),
    scales f32 (rows, nb)) with per-row per-block absmax scales."""
    rows, n = flat.shape
    nb = -(-n // block)
    padded = np.zeros((rows, nb * block), np.float32)
    padded[:, :n] = flat
    blocks = padded.reshape(rows, nb, block)
    absmax = np.abs(blocks).max(axis=2)
    scales = np.where(absmax >= Q8_FLUSH, absmax / 127.0, 0.0).astype(np.float32)
    inv = np.zeros_like(scales)
    np.divide(1.0, scales, out=inv, where=scales > 0)
    codes = np.clip(np.rint(blocks * inv[:, :, None]), -127, 127).astype(np.int8)
    return codes.reshape(rows, nb * block), scales


def _decode_blocks(codes: np.ndarray, scales: np.ndarray, n: int, block: int) -> np.ndarray:
    """Inverse of :func:`_encode_blocks`: ``(rows, n)`` f32."""
    rows = codes.shape[0]
    nb = scales.shape[1]
    vals = codes.astype(np.float32).reshape(rows, nb, block) * scales[:, :, None]
    return vals.reshape(rows, nb * block)[:, :n]


def q8_encode_array(arr: Any, block: int = Q8_BLOCK) -> Dict[str, Any]:
    """One array -> its self-describing compressed leaf dict."""
    a = np.asarray(arr)
    codes, scales = _encode_blocks(a.astype(np.float32).reshape(1, -1), block)
    return {
        # plain python int: numpy scalars round-trip through orbax as python
        # ints, which would change the integrity digest across save/load
        _MARKER: int(block),
        "codes": codes[0],
        "scales": scales[0],
        "shape": np.asarray(a.shape, np.int64),
        "dtype": str(a.dtype),
    }


def q8_decode_array(leaf: Dict[str, Any]) -> np.ndarray:
    """Inverse of :func:`q8_encode_array` (accepts jax-array members — a
    loaded snapshot hands them back as device arrays)."""
    block = int(np.asarray(leaf[_MARKER]))
    shape = tuple(int(d) for d in np.asarray(leaf["shape"]))
    n = 1
    for d in shape:
        n *= d
    codes = np.asarray(leaf["codes"]).reshape(1, -1)
    scales = np.asarray(leaf["scales"]).reshape(1, -1)
    flat = _decode_blocks(codes, scales, n, block)[0]
    return flat.reshape(shape).astype(np.dtype(str(leaf["dtype"])))


def is_q8_leaf(x: Any) -> bool:
    return isinstance(x, dict) and _MARKER in x


def encode_state_tree(metric: Any, state: Any) -> Any:
    """Wrap the quantized-policy leaves of a logical (or shard-stacked)
    state pytree in compressed leaf dicts; everything else passes verbatim.
    ``metric`` supplies the policy (Metric or MetricCollection)."""
    if not isinstance(state, dict):
        return state
    if hasattr(metric, "items") and not hasattr(metric, "_defaults"):
        return {
            k: encode_state_tree(m, state.get(k, {})) for k, m in metric.items(keep_base=True)
        }
    out: Dict[str, Any] = {}
    children = metric._child_metrics()
    for k, v in state.items():
        if k == metric._CHILD_KEY:
            sub: Dict[str, Any] = {}
            for name, child_state in v.items():
                child = children.get(name)
                if child is None:
                    sub[name] = child_state
                elif isinstance(child, list):
                    sub[name] = [
                        encode_state_tree(c, cs) for c, cs in zip(child, child_state)
                    ]
                else:
                    sub[name] = encode_state_tree(child, child_state)
            out[k] = sub
        elif metric._sync_precision.get(k, "exact") == "q8_block" and not isinstance(v, list):
            out[k] = q8_encode_array(v)
        else:
            out[k] = v
    return out


def decode_state_tree(tree: Any) -> Any:
    """Unwrap every compressed leaf anywhere in a pytree (self-describing —
    no metric or layout needed; the restore matrix's host paths call this
    before merging/embedding the state)."""
    if is_q8_leaf(tree):
        return q8_decode_array(tree)
    if isinstance(tree, dict):
        return {k: decode_state_tree(v) for k, v in tree.items()}
    if isinstance(tree, list):
        return [decode_state_tree(v) for v in tree]
    if isinstance(tree, tuple):
        return tuple(decode_state_tree(v) for v in tree)
    return tree


class ArenaRowCodec:
    """Buffer-form codec over a metric's per-dtype arena vectors.

    Built from the per-stream/engine :class:`ArenaLayout` and the metric's
    ``sync_precision`` policy: for each dtype buffer, the element positions
    of quantized leaves form the coded section, the rest stays verbatim.
    Operates on any leading shape — a single spilled row ``(n,)``, a stacked
    spill matrix ``(K, n)``, a paged snapshot arena ``(world, resident, n)``.
    """

    CODES = "#q8c"
    SCALES = "#q8s"
    EXACT = "#ex"

    def __init__(self, q_mask: Dict[str, np.ndarray], block: int = Q8_BLOCK):
        #: dtype key -> boolean element mask of the quantized section
        self._q_mask = {k: np.asarray(v, bool) for k, v in q_mask.items()}
        self._block = int(block)

    @classmethod
    def for_metric(cls, metric: Any, block: int = Q8_BLOCK) -> Optional["ArenaRowCodec"]:
        """The codec for ``metric``'s per-stream arena layout, or None when
        the policy quantizes nothing (compression is then a no-op)."""
        from metrics_tpu.engine.arena import ArenaLayout

        precisions = _flat_precisions(metric)
        if not any(p == "q8_block" for p in precisions):
            return None
        layout = ArenaLayout.for_state(metric.abstract_state())
        specs = layout._specs
        if len(specs) != len(precisions):  # pragma: no cover - same flatten order
            raise ValueError(
                f"precision list ({len(precisions)}) does not align with the arena "
                f"layout ({len(specs)} leaves)"
            )
        masks = {k: np.zeros((n,), bool) for k, n in layout.buffer_sizes().items()}
        for spec, prec in zip(specs, precisions):
            if prec == "q8_block":
                masks[spec.key][spec.offset : spec.offset + spec.size] = True
        return cls({k: m for k, m in masks.items() if m.any()}, block)

    def is_encoded(self, bufs: Dict[str, Any]) -> bool:
        return any(str(k).endswith(self.CODES) for k in bufs)

    def encode_buffers(self, bufs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Per-dtype buffers (any leading shape, elements on the LAST axis)
        -> their compressed form. Buffers without quantized elements pass
        through under their own key; an all-quantized buffer omits its
        ``#ex`` entry (zero-size arrays break the orbax save path)."""
        out: Dict[str, np.ndarray] = {}
        for k, buf in bufs.items():
            mask = self._q_mask.get(k)
            arr = np.asarray(buf)
            if mask is None:
                out[k] = arr
                continue
            lead = arr.shape[:-1]
            flat = arr.reshape(-1, arr.shape[-1]).astype(np.float32)
            codes, scales = _encode_blocks(flat[:, mask], self._block)
            out[k + self.CODES] = codes.reshape(lead + (codes.shape[-1],))
            out[k + self.SCALES] = scales.reshape(lead + (scales.shape[-1],))
            exact = arr.reshape(-1, arr.shape[-1])[:, ~mask]
            if exact.shape[-1]:
                out[k + self.EXACT] = exact.reshape(lead + (exact.shape[-1],))
        return out

    def stage_buffers(
        self, enc: Dict[str, Any], keys: Any
    ) -> "tuple[Dict[str, np.ndarray], Dict[str, tuple]]":
        """Split an encoded buffer dict for DEVICE-side decode of ``keys``'s
        quantized sections (the megastep q8-resident path, ISSUE 16).

        Returns ``(seed, stage)``: ``seed`` is :meth:`decode_buffers`' output
        except each staged key's quantized columns are left ZERO (the exact
        remainder and every other buffer decode verbatim) — the form the
        engine seats in the arena; ``stage[key] = (codes_elem, scales_elem)``
        are per-ELEMENT ``(..., n)`` int8/f32 expansions aligned to the
        buffer columns (zero outside the quantized mask), so
        ``(codes_elem.astype(f32) * scales_elem).astype(dtype)`` over the
        mask reproduces :meth:`decode_buffers` bit-for-bit — the same
        int8→f32 convert, one f32 multiply, one cast the kernel seed runs.
        """
        keys = tuple(keys)
        sub = dict(enc)
        stage: Dict[str, tuple] = {}
        for k in keys:
            mask = self._q_mask[k]
            codes = np.asarray(sub.pop(k + self.CODES))
            scales = np.asarray(sub.pop(k + self.SCALES), np.float32)
            lead = codes.shape[:-1]
            nq = int(mask.sum())
            n = mask.size
            codes_elem = np.zeros(lead + (n,), np.int8)
            scales_elem = np.zeros(lead + (n,), np.float32)
            codes_elem[..., mask] = codes[..., :nq]
            scales_elem[..., mask] = np.repeat(scales, self._block, axis=-1)[..., :nq]
            stage[k] = (codes_elem, scales_elem)
        seed = self.decode_buffers(sub)
        for k in keys:
            mask = self._q_mask[k]
            lead = stage[k][0].shape[:-1]
            n = mask.size
            full = np.zeros(lead + (n,), np.dtype(k))
            ek = k + self.EXACT
            if ek in enc:
                full[..., ~mask] = np.asarray(enc[ek]).reshape(lead + (n - int(mask.sum()),))
            seed[k] = full
        return seed, stage

    def decode_buffers(self, enc: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Inverse of :meth:`encode_buffers` — reassembles each dtype buffer
        from its coded section + verbatim remainder."""
        out: Dict[str, np.ndarray] = {}
        for k, v in enc.items():
            key = str(k)
            if key.endswith((self.CODES, self.SCALES, self.EXACT)):
                continue
            out[key] = np.asarray(v)
        for k, mask in self._q_mask.items():
            ck, sk, ek = k + self.CODES, k + self.SCALES, k + self.EXACT
            if ck not in enc:
                continue
            codes = np.asarray(enc[ck])
            scales = np.asarray(enc[sk])
            lead = codes.shape[:-1]
            nq = int(mask.sum())
            vals = _decode_blocks(
                codes.reshape(-1, codes.shape[-1]),
                scales.reshape(-1, scales.shape[-1]),
                nq,
                self._block,
            )
            n = mask.size
            full = np.zeros((vals.shape[0], n), np.dtype(k))
            full[:, mask] = vals.astype(np.dtype(k))
            if ek in enc:
                full[:, ~mask] = np.asarray(enc[ek]).reshape(-1, n - nq)
            out[k] = full.reshape(lead + (n,))
        return out


def _flat_precisions(metric: Any) -> List[str]:
    """Per-leaf precision strings in ``abstract_state`` tree-flatten order
    (sorted-dict nesting mirrors the state tree exactly)."""
    import jax

    def ptree(m: Any) -> Any:
        if hasattr(m, "items") and not hasattr(m, "_defaults"):
            return {k: ptree(mm) for k, mm in m.items(keep_base=True)}
        out: Dict[str, Any] = {k: m._sync_precision.get(k, "exact") for k in m._defaults}
        children = m._child_metrics()
        if children:
            out[m._CHILD_KEY] = {
                name: ([ptree(c) for c in child] if isinstance(child, list) else ptree(child))
                for name, child in children.items()
            }
        return out

    return [str(p) for p in jax.tree_util.tree_leaves(ptree(metric))]
