"""Stream-sharding smoke: ``python -m metrics_tpu.engine.streams_smoke``.

The CI-shaped proof of the stream-sharded MultiStreamEngine (ISSUE 9) on the
8-device virtual CPU mesh (bootstraps itself via
``--xla_force_host_platform_device_count``, the ``mesh_smoke`` recipe):

1. **Parity past the resident cap** — S=64 streams behind resident=2 slots
   per shard (resident capacity 16 ≪ S) under seeded Zipfian traffic
   (``engine/traffic.py``): every per-stream result is BIT-IDENTICAL to an
   unsharded, unpaged single-device oracle on the same stream (dyadic
   values), with the pager demonstrably working (spills AND fault-ins
   happened).
2. **Per-shard residency** — the carried arena buffers are exactly
   ``(world, resident, n)`` per dtype: per-shard resident state is the
   working-set cap, not S.
3. **Zero steady compiles** — replaying the same traffic after warmup
   compiles NOTHING (the routed program set is closed), and ``results()``
   issues ONE device computation for all 64 streams.
4. **Kill/resume past a spill** — a mid-stream snapshot taken while rows
   were spilled restores into a same-world engine; replaying the remaining
   batches reproduces the uninterrupted per-stream results exactly.
5. **Collective placement** — every compiled routed step's HLO carries ZERO
   cross-shard collectives (the named ``no-collectives-in-deferred-step``
   rule; the jaxpr-level pin rides ``make analyze``'s bootstrap matrix).

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys
import tempfile

NUM_DEVICES = 8
S = 64
RESIDENT = 2  # per-shard slots: capacity 16 ≪ S=64, so the Zipf run MUST page
BUCKETS = (32, 64)


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.streams_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.analysis import check_no_collectives
    from metrics_tpu.engine import AotCache, EngineConfig, MultiStreamEngine
    from metrics_tpu.engine.chaos_smoke import make_checker
    from metrics_tpu.engine.traffic import zipf_traffic

    check, failed = make_checker()
    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))

    def col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    traffic = zipf_traffic(S, 120, alpha=1.1, seed=41)

    def run_all(engine):
        for sid, p, t in traffic:
            engine.submit(sid, p, t)
        return {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in engine.results().items()
        }

    def parity(tag, got, want):
        for sid in want:
            for k in want[sid]:
                check(
                    np.array_equal(got[sid][k], want[sid][k], equal_nan=True),
                    f"{tag}: stream {sid} {k} {got[sid][k]} != {want[sid][k]}",
                )

    # unsharded, unpaged single-device oracle
    oracle = MultiStreamEngine(col(), S, EngineConfig(buckets=BUCKETS))
    with oracle:
        want = run_all(oracle)

    cache = AotCache()
    snapdir = tempfile.mkdtemp(prefix="metrics_tpu_streams_smoke_")
    cfg = EngineConfig(
        buckets=BUCKETS, mesh=mesh, axis="dp", mesh_sync="deferred",
        snapshot_dir=snapdir,
    )
    engine = MultiStreamEngine(
        col(), S, cfg, aot_cache=cache, stream_shard=True, resident_streams=RESIDENT
    )
    with engine:
        got = run_all(engine)
        warm = cache.misses
        calls_before = engine.stats.result_device_calls
        engine.reset()
        got2 = run_all(engine)
        steady = cache.misses - warm
    parity("sharded+paged vs oracle", got, want)
    parity("warm repeat", got2, want)
    check(steady == 0, f"repeat stream compiled {steady} programs (expected 0)")
    check(
        engine.stats.result_device_calls == calls_before + 1,
        "results() issued more than one device computation",
    )
    st = engine.stats
    check(
        st.page_outs > 0 and st.page_ins > 0,
        f"Zipf run never paged (outs={st.page_outs}, ins={st.page_ins}) — resident cap not binding",
    )
    sizes = engine._layout.buffer_sizes()
    shapes = {k: tuple(v.shape) for k, v in engine._state.items()}
    check(
        shapes == {k: (NUM_DEVICES, RESIDENT, n) for k, n in sizes.items()},
        f"arena buffers are {shapes}, expected (world, resident, n) per dtype",
    )
    for prog in engine._program_memo.values():
        findings = check_no_collectives(hlo_text=prog.as_text(), where="streams-smoke/routed-step")
        check(not findings, f"routed step HLO carries collectives: {[f.render() for f in findings[:2]]}")

    # kill/resume past a spill: snapshot mid-stream while rows are spilled
    cut = 60
    eng2 = MultiStreamEngine(
        col(), S, cfg, aot_cache=cache, stream_shard=True, resident_streams=RESIDENT
    )
    with eng2:
        for sid, p, t in traffic[:cut]:
            eng2.submit(sid, p, t)
        eng2.flush()
        spilled = eng2._pager.spilled_count()
        eng2.snapshot()
    check(spilled > 0, "snapshot was taken with nothing spilled — the claim needs a spill")
    del eng2
    resumed = MultiStreamEngine(
        col(), S, cfg, aot_cache=cache, stream_shard=True, resident_streams=RESIDENT
    )
    meta = resumed.restore()
    check(int(meta["batches_done"]) == cut, f"cursor {meta['batches_done']} != {cut}")
    check(str(meta.get("mesh_sync")) == "stream_shard", f"provenance mesh_sync={meta.get('mesh_sync')}")
    check(int(meta.get("world", 0)) == NUM_DEVICES and int(meta.get("resident", 0)) == RESIDENT,
          "snapshot meta lacks the stream-shard topology")
    with resumed:
        for sid, p, t in traffic[cut:]:
            resumed.submit(sid, p, t)
        got3 = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in resumed.results().items()
        }
    parity("kill/resume past a spill", got3, want)

    if failed:
        return 1
    print(
        "streams-smoke PASS: "
        f"S={S} streams sharded over {NUM_DEVICES} shards at resident={RESIDENT} "
        f"(capacity {NUM_DEVICES * RESIDENT} ≪ S) == unsharded unpaged oracle bit-exactly "
        f"on {len(traffic)} Zipfian batches; page_outs={st.page_outs} page_ins={st.page_ins} "
        f"hit_rate={st.page_hits}/{st.page_hits + st.page_faults}; per-shard arena = "
        f"(world, resident, n) exactly; repeat stream compiled 0; results() = 1 device "
        f"computation; routed-step HLO collective-free; kill/resume past a spill replayed exactly"
    )
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
