"""Fault layer for the streaming engine: deterministic injection, input
screening, and the typed error model.

Production serving is defined by what happens when things break (ROADMAP
item 3 — multi-host, where preemption and partial failure are the steady
state). This module supplies the three pieces every recovery path in
``engine/pipeline.py`` stands on:

* :class:`FaultInjector` — a SEEDED, occurrence-deterministic chaos harness.
  Every fault boundary in the engine (ingestion, coalesce, compile, device
  step, kernel dispatch, watchdog, snapshot write/read/corrupt, deferred
  boundary merge, dispatcher kill) calls ``injector.check(site)``; whether
  the Nth call at a site fires depends only on the seed and N — never on
  wall time or thread interleaving — so every recovery path is replayable
  on CPU CI (``make chaos-smoke``).
* :class:`ScreenPolicy` — pre-dispatch input screening with a
  QUARANTINE/dead-letter path. The action vocabulary extends
  ``aggregation.py``'s ``nan_strategy`` set (``"error"``/``"warn"``/
  ``"ignore"``) with ``"quarantine"``: the batch is rejected BEFORE it can
  reach a compiled step, recorded in the engine's quarantine ledger with its
  replay cursor, and the stream keeps serving. One poisoned batch must never
  invalidate accumulated state (PAPER.md's update/compute/reset contract).
* The typed error model (table in docs/serving.md, "Failure semantics"):
  :class:`InjectedFault`, :class:`EngineDispatchError` (sticky dispatcher
  failures, now carrying the failing batch cursor/bucket/stream ids),
  :class:`SnapshotCorruptError` (truncated/bit-flipped payloads, naming path
  and generation), :class:`StepTimeoutError` (watchdog),
  :class:`BackpressureTimeout` (``submit(timeout=)``), and
  :class:`BoundaryMergeError` (deferred merge, carrying mesh topology).

Deliberately dependency-free within the engine package (no imports from
``pipeline``/``snapshot``), so every engine module can import it.
"""
import hashlib
import threading
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "BackpressureTimeout",
    "BoundaryMergeError",
    "EngineDispatchError",
    "FAULT_SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "QuarantineRecord",
    "ScreenPolicy",
    "SnapshotCorruptError",
    "StepTimeoutError",
    "corrupt_snapshot",
    "is_transient",
    "wait_with_timeout",
]

# Every injection boundary the engine exposes. ``make chaos-smoke`` asserts a
# seeded sweep fires each of these at least once and the engine recovers to a
# bit-identical result.
FAULT_SITES = (
    "admission",        # admission-control check on the submit path
    "ingest",           # dispatcher picked up a group, nothing folded yet
    "coalesce",         # megabatch drain — degrades to singleton groups
    "compile",          # AOT program build
    "step",             # device step completed, host commit pending
    "kernel",           # kernel backend failure -> pallas→xla demotion
    "shard_loss",       # a mesh shard dies mid-step -> elastic reshard (ISSUE 11)
    "watchdog",         # per-step watchdog expiry (simulated stuck device)
    "merge",            # deferred-sync boundary merge
    "page_out",         # stream-paging spill: arena row -> host RAM
    "page_in",          # stream-paging fault-in: host RAM/init -> arena row
    "quant_encode",     # q8 state-at-rest encode (snapshot payload / spill row)
    "quant_decode",     # q8 state-at-rest decode (restore / fault-in / read)
    "reshard_snapshot", # live reshard: in-memory topology snapshot capture
    "reshard_restore",  # live reshard: restore into the target topology
    "pane_rotate",      # window pane rotation: plan phase, before any commit
    "drift_eval",       # closing-pane drift evaluation (pure read, retried)
    "host_loss",        # a fleet host dies at a boundary (ISSUE 15): transient
                        # = suspected loss, retried; sticky = FleetHostLostError
    "fleet_barrier",    # fleet snapshot-cut barrier entry (pure, pre-collective)
    "snapshot_write",   # snapshot save fails before any bytes are durable
    "snapshot_corrupt", # snapshot saved, then payload bytes rot on disk
    "snapshot_read",    # transient restore-time read failure
    "dispatcher_kill",  # dispatcher thread dies outright (fatal)
)

_SCREEN_ACTIONS = ("error", "warn", "ignore", "quarantine")


# ----------------------------------------------------------------- error model


class InjectedFault(RuntimeError):
    """A fault fired by :class:`FaultInjector`.

    ``transient`` marks it retryable (the engine's bounded-backoff retry
    loop); ``fatal`` kills the dispatcher thread outright (the
    ``dispatcher_kill`` site — models a hard host/runtime death rather than
    a per-step error).
    """

    def __init__(self, site: str, occurrence: int, transient: bool = True, fatal: bool = False):
        self.site = site
        self.occurrence = occurrence
        self.transient = transient
        self.fatal = fatal
        super().__init__(
            f"injected fault at site {site!r} (occurrence {occurrence}, "
            f"{'transient' if transient else 'sticky'}{', fatal' if fatal else ''})"
        )


class EngineDispatchError(RuntimeError):
    """The sticky dispatcher failure, surfaced to producers/readers.

    Chains the original exception (``raise ... from cause``) and carries the
    failure context the dispatcher recorded — ``cursor`` (the replay cursor
    of the failing batch: operators re-submit or inspect exactly that batch),
    ``step``, ``bucket``, and ``stream_ids`` for multi-stream engines.
    """

    def __init__(self, message: str, context: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.context = dict(context or {})
        self.cursor = self.context.get("cursor")
        self.bucket = self.context.get("bucket")
        self.stream_ids = self.context.get("stream_ids")


class SnapshotCorruptError(RuntimeError):
    """A snapshot payload failed integrity verification or deserialization.

    Names the snapshot ``path`` and its ``generation`` (the step-stamped
    directory name) so operators know exactly which generation rotted;
    ``load_snapshot(..., fallback=True)`` walks past it to the newest valid
    generation.
    """

    def __init__(self, path: str, generation: str, reason: str):
        self.path = path
        self.generation = generation
        self.reason = reason
        super().__init__(
            f"snapshot payload corrupt: generation {generation!r} at {path} ({reason})"
        )


class StepTimeoutError(RuntimeError):
    """Per-step watchdog expiry: the device step did not complete within
    ``EngineConfig.step_timeout_s`` — a stuck pipeline, not a poison batch.
    Transient for the retry loop (rollback + re-dispatch); sticky once the
    retry budget is exhausted."""


class BackpressureTimeout(TimeoutError):
    """``submit(timeout=...)`` gave up: the bounded queue stayed full for the
    whole window. Raised only when no sticky dispatcher error exists (that
    error is surfaced instead — a dead dispatcher behind a full queue must
    never read as mere backpressure)."""


class BoundaryMergeError(RuntimeError):
    """A deferred-sync boundary merge failed (chained). The carried
    shard-local state is untouched — the merge is a non-donated read — so
    ``result()`` keeps serving the last consistent state on the next call."""


# -------------------------------------------------------------- fault injector


@dataclass
class FaultSpec:
    """Per-site firing plan.

    ``schedule`` fires at exactly those occurrence indices (0-based count of
    ``check``/``fire`` calls at the site); ``rate`` fires each remaining
    occurrence with the given probability drawn from the site's own seeded
    stream. Both are deterministic in (seed, site, occurrence index).
    """

    schedule: Tuple[int, ...] = ()
    rate: float = 0.0
    transient: bool = True
    fatal: bool = False
    max_fires: Optional[int] = None  # None = unbounded


class FaultInjector:
    """Deterministic, seeded fault injection across the engine's boundaries.

    Usage::

        inj = FaultInjector(seed=7, plan={
            "step": FaultSpec(schedule=(2,)),        # 3rd step attempt fails
            "compile": FaultSpec(rate=0.25),          # 25% of builds fail
            "snapshot_corrupt": FaultSpec(schedule=(1,)),
        })
        EngineConfig(fault_injector=inj, ...)

    Determinism contract: whether the Nth call at a site fires depends only
    on (seed, site, N). Counters are thread-safe; per-site RNG streams are
    independent (site-hashed seeds), so adding calls at one site never shifts
    another site's firing pattern.
    """

    def __init__(self, seed: int = 0, plan: Optional[Dict[str, FaultSpec]] = None):
        self.seed = int(seed)
        self.plan: Dict[str, FaultSpec] = dict(plan or {})
        for site in self.plan:
            if site not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {site!r}; expected one of {FAULT_SITES}"
                )
        self._lock = threading.Lock()
        self.calls: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}
        self._rngs: Dict[str, np.random.RandomState] = {}

    def _rng(self, site: str) -> np.random.RandomState:
        rng = self._rngs.get(site)
        if rng is None:
            digest = hashlib.sha256(f"{self.seed}:{site}".encode()).digest()
            rng = self._rngs[site] = np.random.RandomState(
                int.from_bytes(digest[:4], "little")
            )
        return rng

    def has_site(self, site: str) -> bool:
        """Whether the plan can ever fire at ``site`` (the engine uses this to
        arm site-specific machinery, e.g. the watchdog, deterministically)."""
        spec = self.plan.get(site)
        return spec is not None and (bool(spec.schedule) or spec.rate > 0.0)

    def fire(self, site: str) -> bool:
        """Count one occurrence at ``site``; True when the plan says it fails."""
        with self._lock:
            spec = self.plan.get(site)
            n = self.calls.get(site, 0)
            self.calls[site] = n + 1
            if spec is None:
                return False
            if spec.max_fires is not None and self.fired.get(site, 0) >= spec.max_fires:
                return False
            hit = n in spec.schedule
            if not hit and spec.rate > 0.0:
                # one draw per occurrence keeps the (seed, site, N) contract
                hit = bool(self._rng(site).rand() < spec.rate)
            elif spec.rate > 0.0:
                self._rng(site).rand()  # burn the draw: schedules must not shift the stream
            if hit:
                self.fired[site] = self.fired.get(site, 0) + 1
            return hit

    def check(self, site: str, **context: Any) -> None:
        """Raise :class:`InjectedFault` (or :class:`StepTimeoutError` for the
        watchdog site) when the plan fires at this occurrence."""
        if not self.fire(site):
            return
        spec = self.plan[site]
        occurrence = self.calls[site] - 1
        if site == "watchdog":
            raise StepTimeoutError(
                f"injected watchdog expiry (occurrence {occurrence}): device step "
                "did not complete within the configured step_timeout_s"
            )
        raise InjectedFault(site, occurrence=occurrence, transient=spec.transient, fatal=spec.fatal)

    def snapshot_rng(self) -> np.random.RandomState:
        """The seeded stream snapshot corruption draws from (byte offsets)."""
        return self._rng("snapshot_corrupt")

    def summary(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {"calls": dict(self.calls), "fired": dict(self.fired)}


# ------------------------------------------------------------- classification


def is_transient(exc: BaseException) -> bool:
    """Is this failure worth a bounded retry (vs sticky)?

    Transient: injected faults marked so, watchdog expiries, and runtime
    errors whose status text matches the jaxlib/grpc transient family.
    Everything else — shape mismatches, trace errors, user errors — is a
    deterministic property of the input and retrying it would only repeat
    the failure.
    """
    if isinstance(exc, InjectedFault):
        return exc.transient
    if isinstance(exc, StepTimeoutError):
        return True
    msg = str(exc)
    return any(
        code in msg
        for code in ("RESOURCE_EXHAUSTED", "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED")
    )


def wait_with_timeout(fn: Callable[[], Any], timeout_s: float) -> Any:
    """Run blocking ``fn`` under a watchdog; raise :class:`StepTimeoutError`
    after ``timeout_s``. The underlying call cannot be cancelled (a hung
    device op keeps its buffers) — the waiter thread is abandoned as a
    daemon and the caller rolls back to its pre-step shadow instead.

    Cost model: one short-lived thread per invocation. The engine only
    routes through here when ``step_timeout_s`` is armed — a mode that
    already syncs every step (the containment trade), so the thread setup
    is marginal against the sync itself. Abandoned threads are bounded:
    each chunk leaks at most ``max_retries + 1`` waiters before the failure
    goes sticky and the dispatcher stops stepping."""
    done = threading.Event()
    box: Dict[str, Any] = {}

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - relayed to the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, name="metrics-tpu-watchdog-wait", daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise StepTimeoutError(
            f"device step did not complete within the {timeout_s:.3f}s watchdog"
        )
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ----------------------------------------------------------- input screening


@dataclass
class ScreenPolicy:
    """Pre-dispatch batch screening policy.

    Action vocabulary per check — the ``nan_strategy`` set from
    ``aggregation.py`` (``"error"``, ``"warn"``, ``"ignore"``) extended with
    ``"quarantine"`` (reject into the engine's dead-letter ledger; the
    stream keeps serving and the replay cursor still advances past the
    batch, so kill/resume replay re-screens it identically):

    * ``non_finite`` — NaN/Inf anywhere in a floating batch argument.
      (A float *fill* belongs to the aggregator's own ``nan_strategy``; the
      engine screens whole batches, it does not rewrite rows.)
    * ``id_range=(lo, hi)`` — integer batch-carried leaves (labels/ids) must
      lie in ``[lo, hi]`` inclusive; action ``id_range_action``.
    * ``uniform_batch`` — every array argument must be batch-carried (leading
      dim == the batch size). Opt-in shape screening for metrics whose update
      takes only batch arrays: catches the ragged preds-vs-target mismatch
      BEFORE it becomes a trace error; action ``uniform_batch_action``.
    """

    non_finite: str = "quarantine"
    id_range: Optional[Tuple[int, int]] = None
    id_range_action: str = "quarantine"
    uniform_batch: bool = False
    uniform_batch_action: str = "quarantine"

    def __post_init__(self):
        for name in ("non_finite", "id_range_action", "uniform_batch_action"):
            v = getattr(self, name)
            if v not in _SCREEN_ACTIONS:
                raise ValueError(
                    f"ScreenPolicy.{name} must be one of {_SCREEN_ACTIONS}, got {v!r}"
                )

    def screen(self, payload: Any, n_rows: int) -> Optional[Tuple[str, str]]:
        """Screen one host-side ``(args, kwargs)`` payload of ``n_rows``.

        Returns ``(action, reason)`` for a rejection, or None to accept.
        ``"warn"`` warns and accepts; ``"ignore"`` skips the check entirely.
        Runs on the dispatcher thread against host numpy BEFORE any upload —
        one O(rows) pass per enabled check.
        """
        import jax

        from metrics_tpu.utils.data import is_batch_leaf

        leaves = jax.tree_util.tree_leaves(payload)
        for leaf in leaves:
            arr = leaf if isinstance(leaf, np.ndarray) else None
            if arr is None:
                shape = getattr(leaf, "shape", None)
                if shape is None:
                    continue
                arr = np.asarray(leaf)
            if self.non_finite != "ignore" and arr.dtype.kind == "f" and arr.size:
                if not bool(np.isfinite(arr).all()):
                    verdict = self._verdict(
                        self.non_finite,
                        f"non-finite values in float argument (shape {arr.shape})",
                    )
                    if verdict is not None:
                        return verdict
            if (
                self.id_range is not None
                and self.id_range_action != "ignore"
                and arr.dtype.kind in "iu"
                and arr.size
                and is_batch_leaf(arr, n_rows)
            ):
                lo, hi = self.id_range
                mn, mx = int(arr.min()), int(arr.max())
                if mn < lo or mx > hi:
                    verdict = self._verdict(
                        self.id_range_action,
                        f"id/label out of range [{lo}, {hi}]: observed [{mn}, {mx}]",
                    )
                    if verdict is not None:
                        return verdict
            if (
                self.uniform_batch
                and self.uniform_batch_action != "ignore"
                and arr.ndim >= 1
                and not is_batch_leaf(arr, n_rows)
            ):
                verdict = self._verdict(
                    self.uniform_batch_action,
                    f"argument shape {arr.shape} is not batch-carried "
                    f"(expected leading dim {n_rows})",
                )
                if verdict is not None:
                    return verdict
        return None

    @staticmethod
    def _verdict(action: str, reason: str) -> Optional[Tuple[str, str]]:
        if action == "warn":
            warnings.warn(f"screened batch accepted with warning: {reason}", stacklevel=3)
            return None
        return (action, reason)


@dataclass
class QuarantineRecord:
    """One dead-lettered batch: enough for an operator to find and replay it.

    ``cursor`` is the batch's replay-cursor index (its position in the
    submitted stream — the same coordinate ``restore()`` meta uses), so the
    rejected input can be located in the upstream log exactly."""

    cursor: int
    rows: int
    reason: str
    stream_id: Optional[int] = None
    payload: Optional[Any] = None  # host payload, retained up to the ledger cap
    wall_time: float = field(default_factory=time.time)


# -------------------------------------------------------- snapshot corruption


def corrupt_snapshot(path: str, rng: np.random.RandomState, flips: int = 8) -> int:
    """Flip ``flips`` bytes of a snapshot payload in place (chaos harness for
    the restore fallback). ``path`` is a snapshot file or orbax directory;
    the largest payload file is targeted (deterministic choice), byte
    offsets come from the seeded ``rng``. Returns the number of bytes
    flipped (0 when nothing writable was found)."""
    import os

    target = path
    if os.path.isdir(path):
        best, best_size = None, -1
        for root, _, files in sorted(os.walk(path)):
            for name in sorted(files):
                full = os.path.join(root, name)
                size = os.path.getsize(full)
                if size > best_size:
                    best, best_size = full, size
        if best is None:
            return 0
        target = best
    size = os.path.getsize(target)
    if size == 0:
        return 0
    flipped = 0
    with open(target, "r+b") as f:
        for _ in range(int(flips)):
            off = int(rng.randint(0, size))
            f.seek(off)
            b = f.read(1)
            if not b:
                continue
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
            flipped += 1
    return flipped
