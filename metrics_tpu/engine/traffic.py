"""Shared traffic generators for the serving gates and benches.

Uniform stream ids cannot exercise an LRU: every stream is equally cold, the
working set IS the tenant count, and a pager either thrashes or never fires.
Real multi-tenant traffic is skewed — a few hot tenants dominate while a long
tail trickles — so the stream-sharding/paging bench, the chaos plan, the
elastic-overload gate, and the paging tests all draw stream ids from ONE
seeded Zipfian sampler defined here. Sharing the sampler is what keeps the
gates honest about the same workload: a plan change moves bench, chaos,
elastic, and tests in lockstep.

The HOT-SPOT SHIFT mode (ISSUE 11) models the overload scenario the
degradation ladder exists for: at a given batch index the hot set moves —
the rank→stream permutation rotates (head rotation) and/or the Zipf exponent
changes — so a pager sized for the old working set suddenly faults on every
batch. With ``shift_at=None`` the sequence is BIT-IDENTICAL to the
pre-ISSUE-11 generator (same draws, same order), so the existing smokes'
seeded workloads are unchanged.

Values are dyadic rationals (multiples of 1/64), the repo-wide convention
that makes float accumulation exact under ANY grouping, routing, or paging
order — bit-identical parity claims quantify over exactly this traffic.
Batch values and row counts draw from a stream-id-independent RNG, so the
shift moves WHICH stream a batch lands on, never its contents: a shifted and
an unshifted run stay row-for-row comparable.
"""
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["zipf_stream_ids", "zipf_traffic"]


def zipf_stream_ids(
    num_streams: int,
    n: int,
    alpha: float = 1.1,
    seed: int = 0,
    shift_at: Optional[int] = None,
    shift_rotation: Optional[int] = None,
    shift_alpha: Optional[float] = None,
) -> np.ndarray:
    """``n`` stream ids in ``[0, num_streams)`` drawn from a bounded Zipf.

    Rank ``r`` (0-based) has probability proportional to ``1/(r+1)^alpha``;
    rank maps to stream id through a seeded permutation, so the hot set is
    spread across the id space (and therefore across shards under the
    ``sid % world`` routing rule) instead of clustering on shard 0.

    ``shift_at`` arms the hot-spot shift: draws at indices >= ``shift_at``
    use a ROTATED rank→id permutation (``shift_rotation`` positions, default
    ``num_streams // 2`` — the head moves to previously-cold ids) and, when
    ``shift_alpha`` is given, a different Zipf exponent (a flatter/steeper
    tail). The rank STREAM itself is unchanged — one draw sequence, two
    mappings — so the pre-shift prefix of a shifted call equals the
    unshifted call exactly. Deterministic in every argument.
    """
    if num_streams <= 0 or n < 0:
        raise ValueError(f"need num_streams > 0 and n >= 0, got {num_streams}, {n}")
    if shift_at is not None and not (0 <= shift_at):
        raise ValueError(f"shift_at must be >= 0, got {shift_at}")
    rng = np.random.RandomState(seed)

    def _weights(a: float) -> np.ndarray:
        w = 1.0 / np.power(np.arange(1, num_streams + 1, dtype=np.float64), float(a))
        return w / w.sum()

    perm = np.random.RandomState(seed ^ 0x5A1F).permutation(num_streams)
    if shift_at is None or shift_at >= n:
        ranks = rng.choice(num_streams, size=int(n), p=_weights(alpha))
        return perm[ranks].astype(np.int32)
    head = rng.choice(num_streams, size=int(shift_at), p=_weights(alpha))
    tail = rng.choice(
        num_streams,
        size=int(n - shift_at),
        p=_weights(alpha if shift_alpha is None else shift_alpha),
    )
    rot = num_streams // 2 if shift_rotation is None else int(shift_rotation)
    perm_shifted = np.roll(perm, rot)
    return np.concatenate(
        [perm[head], perm_shifted[tail]]
    ).astype(np.int32)


def zipf_traffic(
    num_streams: int,
    n_batches: int,
    alpha: float = 1.1,
    seed: int = 0,
    max_rows: int = 24,
    shift_at: Optional[int] = None,
    shift_rotation: Optional[int] = None,
    shift_alpha: Optional[float] = None,
) -> List[Tuple[int, np.ndarray, np.ndarray]]:
    """``(stream_id, preds, target)`` batches under the Zipfian stream law:
    ragged dyadic-float preds and 0/1 int targets (the Accuracy/MSE input
    shape every serving gate drives). One batch carries one stream's rows —
    cross-stream mixing happens in the engine's coalescer, same as
    production ingest. ``shift_at``/``shift_rotation``/``shift_alpha`` pass
    through to :func:`zipf_stream_ids` (batch CONTENTS draw from an
    id-independent RNG, so the shift reroutes batches without changing
    their rows)."""
    rng = np.random.RandomState(seed ^ 0x7AFF)
    sids = zipf_stream_ids(
        num_streams, n_batches, alpha=alpha, seed=seed,
        shift_at=shift_at, shift_rotation=shift_rotation, shift_alpha=shift_alpha,
    )
    out: List[Tuple[int, np.ndarray, np.ndarray]] = []
    for sid in sids:
        rows = int(rng.randint(1, max(2, max_rows + 1)))  # inclusive max_rows
        preds = (rng.randint(0, 65, size=rows) / 64.0).astype(np.float32)
        target = (rng.rand(rows) > 0.5).astype(np.int32)
        out.append((int(sid), preds, target))
    return out
