"""Shared traffic generators for the serving gates and benches.

Uniform stream ids cannot exercise an LRU: every stream is equally cold, the
working set IS the tenant count, and a pager either thrashes or never fires.
Real multi-tenant traffic is skewed — a few hot tenants dominate while a long
tail trickles — so the stream-sharding/paging bench, the chaos plan, and the
paging tests all draw stream ids from ONE seeded Zipfian sampler defined
here. Sharing the sampler is what keeps the three gates honest about the same
workload: a plan change moves bench, chaos, and tests in lockstep.

Values are dyadic rationals (multiples of 1/64), the repo-wide convention
that makes float accumulation exact under ANY grouping, routing, or paging
order — bit-identical parity claims quantify over exactly this traffic.
"""
from typing import List, Tuple

import numpy as np

__all__ = ["zipf_stream_ids", "zipf_traffic"]


def zipf_stream_ids(
    num_streams: int, n: int, alpha: float = 1.1, seed: int = 0
) -> np.ndarray:
    """``n`` stream ids in ``[0, num_streams)`` drawn from a bounded Zipf.

    Rank ``r`` (0-based) has probability proportional to ``1/(r+1)^alpha``;
    rank maps to stream id through a seeded permutation, so the hot set is
    spread across the id space (and therefore across shards under the
    ``sid % world`` routing rule) instead of clustering on shard 0.
    Deterministic in ``(num_streams, n, alpha, seed)``.
    """
    if num_streams <= 0 or n < 0:
        raise ValueError(f"need num_streams > 0 and n >= 0, got {num_streams}, {n}")
    rng = np.random.RandomState(seed)
    weights = 1.0 / np.power(np.arange(1, num_streams + 1, dtype=np.float64), float(alpha))
    weights /= weights.sum()
    ranks = rng.choice(num_streams, size=int(n), p=weights)
    perm = np.random.RandomState(seed ^ 0x5A1F).permutation(num_streams)
    return perm[ranks].astype(np.int32)


def zipf_traffic(
    num_streams: int,
    n_batches: int,
    alpha: float = 1.1,
    seed: int = 0,
    max_rows: int = 24,
) -> List[Tuple[int, np.ndarray, np.ndarray]]:
    """``(stream_id, preds, target)`` batches under the Zipfian stream law:
    ragged dyadic-float preds and 0/1 int targets (the Accuracy/MSE input
    shape every serving gate drives). One batch carries one stream's rows —
    cross-stream mixing happens in the engine's coalescer, same as
    production ingest."""
    rng = np.random.RandomState(seed ^ 0x7AFF)
    sids = zipf_stream_ids(num_streams, n_batches, alpha=alpha, seed=seed)
    out: List[Tuple[int, np.ndarray, np.ndarray]] = []
    for sid in sids:
        rows = int(rng.randint(1, max(2, max_rows + 1)))  # inclusive max_rows
        preds = (rng.randint(0, 65, size=rows) / 64.0).astype(np.float32)
        target = (rng.rand(rows) > 0.5).astype(np.int32)
        out.append((int(sid), preds, target))
    return out
