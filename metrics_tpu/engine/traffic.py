"""Shared traffic generators for the serving gates and benches.

Uniform stream ids cannot exercise an LRU: every stream is equally cold, the
working set IS the tenant count, and a pager either thrashes or never fires.
Real multi-tenant traffic is skewed — a few hot tenants dominate while a long
tail trickles — so the stream-sharding/paging bench, the chaos plan, the
elastic-overload gate, and the paging tests all draw stream ids from ONE
seeded Zipfian sampler defined here. Sharing the sampler is what keeps the
gates honest about the same workload: a plan change moves bench, chaos,
elastic, and tests in lockstep.

The HOT-SPOT SHIFT mode (ISSUE 11) models the overload scenario the
degradation ladder exists for: at a given batch index the hot set moves —
the rank→stream permutation rotates (head rotation) and/or the Zipf exponent
changes — so a pager sized for the old working set suddenly faults on every
batch. With ``shift_at=None`` the sequence is BIT-IDENTICAL to the
pre-ISSUE-11 generator (same draws, same order), so the existing smokes'
seeded workloads are unchanged.

The LABEL/SCORE DRIFT mode (ISSUE 13) models the scenario the windowed
engine's drift detector exists for: from ``drift_at`` onward the traffic's
DISTRIBUTION shifts gradually — scores ramp upward by dyadic increments
(``drift_score``) and/or labels flip with a ramping probability
(``drift_flip``), both reaching full strength over ``drift_ramp`` batches —
so a per-pane accuracy/error series visibly walks away from its baseline.
Same determinism contract as PR 11's hot-spot shift: the drift TRANSFORMS
already-drawn batches (score shifts are pure functions of the drawn values;
label flips draw from a per-batch-index seeded side stream), so the
pre-drift prefix of a drifted call is BIT-IDENTICAL to the undrifted call,
and two same-seed drifted runs are identical everywhere (pinned in
``tests/engine/test_traffic.py``).

Values are dyadic rationals (multiples of 1/64), the repo-wide convention
that makes float accumulation exact under ANY grouping, routing, or paging
order — bit-identical parity claims quantify over exactly this traffic.
Batch values and row counts draw from a stream-id-independent RNG, so the
shift moves WHICH stream a batch lands on, never its contents: a shifted and
an unshifted run stay row-for-row comparable.
"""
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["zipf_stream_ids", "zipf_traffic"]

_DRIFT_SEED_SALT = 0xD21F7


def _drift_strength(i: int, drift_at: int, drift_ramp: int) -> float:
    """Ramp from 0 (before ``drift_at``) to 1 (``drift_ramp`` batches later),
    piecewise-linear — the GRADUAL shift a hysteresis-guarded detector must
    ride out, then alarm on."""
    if i < drift_at:
        return 0.0
    return min(1.0, (i - drift_at + 1) / max(1, int(drift_ramp)))


def zipf_stream_ids(
    num_streams: int,
    n: int,
    alpha: float = 1.1,
    seed: int = 0,
    shift_at: Optional[int] = None,
    shift_rotation: Optional[int] = None,
    shift_alpha: Optional[float] = None,
) -> np.ndarray:
    """``n`` stream ids in ``[0, num_streams)`` drawn from a bounded Zipf.

    Rank ``r`` (0-based) has probability proportional to ``1/(r+1)^alpha``;
    rank maps to stream id through a seeded permutation, so the hot set is
    spread across the id space (and therefore across shards under the
    ``sid % world`` routing rule) instead of clustering on shard 0.

    ``shift_at`` arms the hot-spot shift: draws at indices >= ``shift_at``
    use a ROTATED rank→id permutation (``shift_rotation`` positions, default
    ``num_streams // 2`` — the head moves to previously-cold ids) and, when
    ``shift_alpha`` is given, a different Zipf exponent (a flatter/steeper
    tail). The rank STREAM itself is unchanged — one draw sequence, two
    mappings — so the pre-shift prefix of a shifted call equals the
    unshifted call exactly. Deterministic in every argument.
    """
    if num_streams <= 0 or n < 0:
        raise ValueError(f"need num_streams > 0 and n >= 0, got {num_streams}, {n}")
    if shift_at is not None and not (0 <= shift_at):
        raise ValueError(f"shift_at must be >= 0, got {shift_at}")
    rng = np.random.RandomState(seed)

    def _weights(a: float) -> np.ndarray:
        w = 1.0 / np.power(np.arange(1, num_streams + 1, dtype=np.float64), float(a))
        return w / w.sum()

    perm = np.random.RandomState(seed ^ 0x5A1F).permutation(num_streams)
    if shift_at is None or shift_at >= n:
        ranks = rng.choice(num_streams, size=int(n), p=_weights(alpha))
        return perm[ranks].astype(np.int32)
    head = rng.choice(num_streams, size=int(shift_at), p=_weights(alpha))
    tail = rng.choice(
        num_streams,
        size=int(n - shift_at),
        p=_weights(alpha if shift_alpha is None else shift_alpha),
    )
    rot = num_streams // 2 if shift_rotation is None else int(shift_rotation)
    perm_shifted = np.roll(perm, rot)
    return np.concatenate(
        [perm[head], perm_shifted[tail]]
    ).astype(np.int32)


def zipf_traffic(
    num_streams: int,
    n_batches: int,
    alpha: float = 1.1,
    seed: int = 0,
    max_rows: int = 24,
    shift_at: Optional[int] = None,
    shift_rotation: Optional[int] = None,
    shift_alpha: Optional[float] = None,
    drift_at: Optional[int] = None,
    drift_ramp: int = 8,
    drift_score: float = 0.0,
    drift_flip: float = 0.0,
    label_acc: Optional[float] = None,
) -> List[Tuple[int, np.ndarray, np.ndarray]]:
    """``(stream_id, preds, target)`` batches under the Zipfian stream law:
    ragged dyadic-float preds and 0/1 int targets (the Accuracy/MSE input
    shape every serving gate drives). One batch carries one stream's rows —
    cross-stream mixing happens in the engine's coalescer, same as
    production ingest. ``shift_at``/``shift_rotation``/``shift_alpha`` pass
    through to :func:`zipf_stream_ids` (batch CONTENTS draw from an
    id-independent RNG, so the shift reroutes batches without changing
    their rows).

    ``drift_at`` arms the LABEL/SCORE drift (ISSUE 13): batches at indices
    >= ``drift_at`` transform — preds shift upward by
    ``round(64 * drift_score * strength) / 64`` (clipped to [0, 1], so
    values stay dyadic) and each target row flips with probability
    ``drift_flip * strength``, where ``strength`` ramps linearly from 0 to 1
    over ``drift_ramp`` batches. The base draws are UNCHANGED (score drift
    is a pure remap; label flips draw from a per-batch-index seeded side
    stream), so the pre-drift prefix is bit-identical to the undrifted call
    and the whole sequence is deterministic in its arguments.

    ``label_acc`` correlates targets with predictions: each target agrees
    with ``preds > 0.5`` with that probability (same RNG budget as the
    default independent draw — one uniform per row — so arming it changes
    only the MAPPING of the draws). Without it, targets are independent of
    preds and a label flip cannot move accuracy — set it (e.g. 0.9) when
    the drift detector should see a real accuracy signal."""
    rng = np.random.RandomState(seed ^ 0x7AFF)
    sids = zipf_stream_ids(
        num_streams, n_batches, alpha=alpha, seed=seed,
        shift_at=shift_at, shift_rotation=shift_rotation, shift_alpha=shift_alpha,
    )
    if drift_at is not None and drift_at < 0:
        raise ValueError(f"drift_at must be >= 0, got {drift_at}")
    out: List[Tuple[int, np.ndarray, np.ndarray]] = []
    for i, sid in enumerate(sids):
        rows = int(rng.randint(1, max(2, max_rows + 1)))  # inclusive max_rows
        preds = (rng.randint(0, 65, size=rows) / 64.0).astype(np.float32)
        u = rng.rand(rows)
        if label_acc is None:
            target = (u > 0.5).astype(np.int32)
        else:
            pred_label = (preds > 0.5).astype(np.int32)
            agree = u < float(label_acc)
            target = np.where(agree, pred_label, 1 - pred_label).astype(np.int32)
        if drift_at is not None and i >= drift_at:
            strength = _drift_strength(i, drift_at, drift_ramp)
            if drift_score:
                # dyadic shift on the 1/64 grid: exact float32 arithmetic,
                # and a pure remap of the already-drawn values
                shift64 = int(round(64.0 * float(drift_score) * strength))
                preds = np.minimum(
                    np.round(preds * 64).astype(np.int64) + shift64, 64
                ).astype(np.float32) / np.float32(64.0)
            if drift_flip:
                # the flip stream is keyed by (seed, batch index) alone —
                # independent of the prefix draws, so arming the drift can
                # never shift the base sequence
                flip_rng = np.random.RandomState(
                    (seed ^ _DRIFT_SEED_SALT ^ (i * 2654435761)) & 0x7FFFFFFF
                )
                flips = flip_rng.rand(rows) < float(drift_flip) * strength
                target = np.where(flips, 1 - target, target).astype(np.int32)
        out.append((int(sid), preds, target))
    return out
