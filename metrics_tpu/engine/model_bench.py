"""Embedded-model serving bench: ``python -m metrics_tpu.engine.model_bench``.

The ``model_serving`` entry (bench.py / BENCH.md): imgs/s (InceptionV3
features) and pairs/s (text-encoder forwards) through the resident
:class:`~metrics_tpu.engine.model_host.ModelHost` vs the monolithic
per-metric forward it replaces, measured under the pinned ratios-in-one-run
protocol — one process, one fixed-seed ragged stream, warmup pays every
compile, then interleaved (monolithic, host) timed passes so host-load drift
cancels in the ratio. The ZERO-steady-compile assertion is HARD on the host
path (a violation raises, the entry reports an error — same contract as
every engine gate), and the monolithic path's open program set is reported
next to the host's closed one (one program per DISTINCT raw batch shape vs
one per bucket). MFU attribution comes from the PR 1 cost walk
(``ops/profiling.attribution_table``): analytic FLOPs of the served bucket
program, cross-checked against XLA's own count, with the structural MXU
ceiling the graph's shapes permit. On CPU the absolute rates carry
``liveness_only``; the durable facts are the ratio, the program-set sizes,
and the zero-steady-compile assertion. Prints one JSON document on stdout.
"""
import json
import sys
import time

INPUT_SIZE = 75  # smallest viable InceptionV3 input: CPU-cheap compiles


def _interleaved(paths, trials):
    """{name: [seconds]*trials} with the per-trial order interleaved so host
    drift hits every path alike and cancels in the ratios."""
    times = {name: [] for name, _ in paths}
    for _ in range(trials):
        for name, fn in paths:
            t0 = time.perf_counter()
            fn()
            times[name].append(time.perf_counter() - t0)
    return times


def _rate(rows, seconds):
    ts = sorted(seconds)
    med = ts[len(ts) // 2]
    return round(rows / med, 2), round((ts[-1] - ts[0]) / med, 3)


def bench_inception(trials=3):
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metrics_tpu.engine.model_host import ModelHostConfig, inception_host
    from metrics_tpu.models.inception import InceptionV3, random_inception_params
    from metrics_tpu.ops.profiling import attribution_table

    params = random_inception_params(input_size=INPUT_SIZE, seed=0, fast=True)
    rng = np.random.RandomState(20260807)
    sizes = [int(rng.choice((2, 5, 8))) for _ in range(12)]
    batches = [
        rng.randint(0, 255, size=(n, INPUT_SIZE, INPUT_SIZE, 3)).astype(np.uint8)
        for n in sizes
    ]
    imgs_total = int(sum(sizes))

    # monolithic: the per-metric forward the host replaces — one jitted
    # program per DISTINCT raw batch shape (the open program set)
    module = InceptionV3()
    mono = jax.jit(lambda p, x: module.apply(p, x)["2048"])

    def run_mono():
        for imgs in batches:
            np.asarray(mono(params, jnp.asarray(imgs)))

    host = inception_host(
        "2048", params,
        config=ModelHostConfig(buckets=(8,), coalesce_window_ms=0.0),
        shared=False,
    )

    def run_host():
        for imgs in batches:
            host.infer(imgs)

    run_mono()  # warmup: one compile per distinct size
    run_host()  # warmup: one compile per bucket signature
    warm_misses = host.aot.misses
    times = _interleaved((("monolithic", run_mono), ("host", run_host)), trials)
    steady = host.aot.misses - warm_misses
    if steady != 0:
        raise RuntimeError(
            f"model_serving[inception] host compiled {steady} programs in steady "
            "state; the closed-program contract is broken"
        )

    mono_rate, mono_spread = _rate(imgs_total, times["monolithic"])
    host_rate, host_spread = _rate(imgs_total, times["host"])

    # MFU attribution (PR 1 cost walk) over the served bucket-8 program
    pad = np.zeros((8, INPUT_SIZE, INPUT_SIZE, 3), np.uint8)
    attr = attribution_table(host._fwd, params, jnp.asarray(pad), depth=1)
    flops_per_img = attr["total_flops"] / 8.0
    host.close()
    return {
        "imgs_per_s": host_rate,
        "monolithic_imgs_per_s": mono_rate,
        "vs_monolithic": round(host_rate / mono_rate, 3) if mono_rate else None,
        "spread_frac": {"host": host_spread, "monolithic": mono_spread},
        "programs": {
            "host": len(host.aot),
            "host_compiles": warm_misses,
            "monolithic_distinct_shapes": len(set(sizes)),
        },
        "compiles_steady_state": steady,
        "flops_per_img_gflops": round(flops_per_img / 1e9, 3),
        "achieved_tflops": round(flops_per_img * host_rate / 1e12, 4),
        "xla_cost_flops_per_img_gflops": (
            round(attr["xla_cost_flops"] / 8.0 / 1e9, 3)
            if attr.get("xla_cost_flops") else None
        ),
        "structural_mfu_ceiling": (
            round(attr["structural_mfu_ceiling"], 4)
            if attr.get("structural_mfu_ceiling") else None
        ),
        "stream": {
            "batches": len(batches), "imgs": imgs_total,
            "raw_sizes": sorted(set(sizes)), "buckets": [8],
            "input_size": INPUT_SIZE, "trials": trials,
        },
    }


def bench_encoder(trials=5):
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metrics_tpu.engine.model_host import ModelHostConfig, encoder_host
    from metrics_tpu.ops.profiling import attribution_table
    from metrics_tpu.text.bert import _derive_length_buckets

    dim, vocab = 64, 4096
    rng = np.random.RandomState(20260807)
    emb = rng.randn(vocab, dim).astype(np.float32) * 0.1
    w1 = rng.randn(dim, 4 * dim).astype(np.float32) * 0.1
    w2 = rng.randn(4 * dim, dim).astype(np.float32) * 0.1

    def enc(ids, mask):
        x = jnp.asarray(emb)[ids] * mask[..., None]
        x = jnp.tanh(x @ jnp.asarray(w1)) @ jnp.asarray(w2)
        return x * mask[..., None]

    max_length = 32
    length_buckets = _derive_length_buckets(max_length)  # the BERTScore fix
    lengths = [int(rng.choice((5, 9, 13, 17, 21, 25, 29))) for _ in range(24)]
    batch_rows = [int(rng.choice((3, 6, 8))) for _ in lengths]
    batches = []
    for L, B in zip(lengths, batch_rows):
        ids = rng.randint(0, vocab, size=(B, L)).astype(np.int32)
        mask = (rng.rand(B, L) > 0.1).astype(np.float32)
        batches.append((ids, mask))
    # one encoded sentence per row; a BERTScore pair encodes pred + target
    pairs_total = sum(batch_rows) / 2.0

    # monolithic: jit at every RAW (B, L) — per-call-max padding, the
    # unbounded trace cache the length buckets bound (text/bert.py satellite)
    mono = jax.jit(enc)

    def run_mono():
        for ids, mask in batches:
            np.asarray(mono(ids, mask))

    host = encoder_host(
        forward_fn=enc,
        config=ModelHostConfig(buckets=(8,), coalesce_window_ms=0.0),
        fingerprint="model-bench-encoder", shared=False,
    )

    def bucket_pad(ids, mask):
        L = ids.shape[1]
        target = next((b for b in length_buckets if b >= L), L)
        pad = ((0, 0), (0, target - L))
        return np.pad(ids, pad), np.pad(mask, pad)

    def run_host():
        for ids, mask in batches:
            host.infer(*bucket_pad(ids, mask))

    run_mono()
    run_host()
    warm_misses = host.aot.misses
    times = _interleaved((("monolithic", run_mono), ("host", run_host)), trials)
    steady = host.aot.misses - warm_misses
    if steady != 0:
        raise RuntimeError(
            f"model_serving[encoder] host compiled {steady} programs in steady "
            "state; the closed-program contract is broken"
        )

    mono_rate, mono_spread = _rate(pairs_total, times["monolithic"])
    host_rate, host_spread = _rate(pairs_total, times["host"])
    ids8 = np.zeros((8, max_length), np.int32)
    mask8 = np.ones((8, max_length), np.float32)
    attr = attribution_table(lambda i, m: enc(i, m), ids8, mask8, depth=1)
    flops_per_pair = attr["total_flops"] / 4.0  # 8 rows = 4 pairs
    host.close()
    return {
        "pairs_per_s": host_rate,
        "monolithic_pairs_per_s": mono_rate,
        "vs_monolithic": round(host_rate / mono_rate, 3) if mono_rate else None,
        "spread_frac": {"host": host_spread, "monolithic": mono_spread},
        "programs": {
            "host_compiles": warm_misses,
            "monolithic_distinct_shapes": len({(b, l) for b, l in zip(batch_rows, lengths)}),
            "length_buckets": list(length_buckets),
        },
        "compiles_steady_state": steady,
        "flops_per_pair_gflops": round(flops_per_pair / 1e9, 4),
        "achieved_tflops": round(flops_per_pair * host_rate / 1e12, 4),
        "structural_mfu_ceiling": (
            round(attr["structural_mfu_ceiling"], 4)
            if attr.get("structural_mfu_ceiling") else None
        ),
        "stream": {
            "batches": len(batches), "pairs": pairs_total,
            "raw_lengths": sorted(set(lengths)), "raw_rows": sorted(set(batch_rows)),
            "max_length": max_length, "trials": trials,
        },
    }


def run_bench() -> dict:
    import jax

    platform = jax.devices()[0].platform
    doc = {
        "inception": bench_inception(),
        "encoder": bench_encoder(),
        "platform": platform,
        "protocol": (
            "ratios-in-one-run: fixed-seed ragged streams (inception: 12 uint8 "
            f"batches of 2/5/8 imgs at {INPUT_SIZE}px; encoder: 24 token batches, "
            "rows 3/6/8, lengths 5..29 under max_length 32), warmup pays every "
            "compile, then interleaved (monolithic, host) timed passes — medians, "
            "(max-min)/median spread; host = single-device ModelHost, batch "
            "buckets (8,), encoder lengths padded to the BERTScore bucket edges; "
            "monolithic = jit at every raw shape (the per-metric forward / "
            "per-call-max padding the host replaces); zero steady compiles "
            "asserted HARD on the host path; MFU attribution = PR 1 cost walk "
            "(analytic FLOPs + XLA cross-check + structural MXU ceiling) over "
            "the served bucket program"
        ),
    }
    if platform == "cpu":
        doc["liveness_only"] = True
        doc["note"] = (
            "CPU rates are liveness, not accelerator throughput; the durable "
            "facts are the host-vs-monolithic RATIO (shared run), the closed "
            "program set, and the zero-steady-compile assertion"
        )
    return doc


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    print(json.dumps(run_bench()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
