"""Mesh-engine smoke check: ``python -m metrics_tpu.engine.mesh_smoke``.

The CPU-safe gate for BOTH mesh sync modes (``make mesh-smoke``), on an
8-device mesh it bootstraps itself (virtual CPU devices via
``--xla_force_host_platform_device_count`` when the host has fewer than 8 —
the ``__graft_entry__.dryrun_multichip`` recipe):

1. parity — a delta MetricCollection streamed through a step-sync engine AND
   a deferred-sync engine equals the single-device eager loop (int states
   bit-exact, floats to tolerance), with both engines sharing ONE AotCache
   (program keys carry the sync mode — no executable can cross modes);
2. cat/scan on mesh — ``AUROC(capacity=N)`` (scan strategy, cat-state
   buffers), which step-sync mode refuses outright, serves under deferred
   sync and matches the single-device engine exactly;
3. collective placement — the compiled deferred steady-state step's HLO
   contains ZERO cross-chip collectives (the merge program contains them
   all); the step-sync step's HLO contains at least one all-reduce;
4. compile cap — each engine stays within its closed program set
   (update-per-bucket + compute, + one merge program for deferred) and a
   repeat stream after ``reset()`` compiles NOTHING.

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys

NUM_DEVICES = 8


def _collective_count(hlo_text: str) -> int:
    # the HLO collective walk lives once in the rule engine (which itself
    # consumes the canonical parallel/collectives.py::HLO_COLLECTIVE_RE)
    from metrics_tpu.analysis import hlo_collective_counts

    return sum(hlo_collective_counts(hlo_text).values())


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.mesh_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import AUROC, Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import AotCache, EngineConfig, StreamingEngine

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    buckets = (32,)
    rng = np.random.RandomState(0)
    batches = [
        ((rng.randint(0, 65, size=n) / 64.0).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in (13, 32, 7, 29, 18)
    ]

    def col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    eager = col()
    for b in batches:
        eager.update(*b)
    want = {k: np.asarray(v) for k, v in eager.compute().items()}

    cache = AotCache()  # SHARED across modes: keys must keep them apart
    ok = True

    def run(engine) -> dict:
        nonlocal ok
        with engine:
            for b in batches:
                engine.submit(*b)
            got = {k: np.asarray(v) for k, v in engine.result().items()}
            warm = engine.aot_cache.misses
            engine.reset()
            for b in batches:
                engine.submit(*b)
            got2 = {k: np.asarray(v) for k, v in engine.result().items()}
            steady = engine.aot_cache.misses - warm
        if steady != 0:
            print(f"FAIL: repeat stream compiled {steady} programs (expected 0)")
            ok = False
        for k in got:
            if not (np.array_equal(got[k], got2[k]) or np.allclose(got[k], got2[k])):
                print(f"FAIL: reset() stream diverged on {k}: {got[k]} vs {got2[k]}")
                ok = False
        return got

    def check_parity(tag: str, got: dict) -> None:
        nonlocal ok
        for k in want:
            exact = np.array_equal(got[k], want[k])
            close = np.allclose(got[k], want[k], rtol=1e-6, atol=1e-7)
            if not (exact or close):
                print(f"FAIL: {tag} {k}: engine={got[k]} eager={want[k]}")
                ok = False

    def step_hlo(engine) -> str:
        (prog,) = list(engine._program_memo.values())
        return prog.as_text()

    base = cache.misses
    step_eng = StreamingEngine(col(), EngineConfig(buckets=buckets, mesh=mesh, axis="dp"), aot_cache=cache)
    check_parity("step-sync", run(step_eng))
    step_compiles = cache.misses - base
    if step_compiles > len(buckets) + 1:
        print(f"FAIL: step-sync compiled {step_compiles} programs (cap {len(buckets) + 1})")
        ok = False
    n_step = _collective_count(step_hlo(step_eng))
    if n_step < 1:
        print("FAIL: step-sync step HLO carries no collective (psum merge missing?)")
        ok = False

    base = cache.misses
    def_eng = StreamingEngine(
        col(), EngineConfig(buckets=buckets, mesh=mesh, axis="dp", mesh_sync="deferred"),
        aot_cache=cache,
    )
    check_parity("deferred", run(def_eng))
    def_compiles = cache.misses - base
    if def_compiles > len(buckets) + 2:  # update/bucket + merge + compute
        print(f"FAIL: deferred compiled {def_compiles} programs (cap {len(buckets) + 2})")
        ok = False
    # the zero-collective side of the placement contract is the NAMED rule —
    # same code path the CI analyzer runs (no-collectives-in-deferred-step)
    from metrics_tpu.analysis import check_no_collectives

    deferred_findings = check_no_collectives(
        hlo_text=step_hlo(def_eng), where="mesh-smoke/deferred-step"
    )
    if deferred_findings:
        for f in deferred_findings:
            print(f"FAIL: {f.render()}")
        ok = False

    # scan/cat metric on mesh — deferred only; must match the 1-device engine
    au_batches = batches
    single = StreamingEngine(AUROC(capacity=256), EngineConfig(buckets=buckets))
    with single:
        for b in au_batches:
            single.submit(*b)
        want_au = float(single.result())
    au_eng = StreamingEngine(
        AUROC(capacity=256),
        EngineConfig(buckets=buckets, mesh=mesh, axis="dp", mesh_sync="deferred"),
        aot_cache=cache,
    )
    with au_eng:
        for b in au_batches:
            au_eng.submit(*b)
        got_au = float(au_eng.result())
    if abs(got_au - want_au) > 1e-6:
        print(f"FAIL: AUROC(capacity) deferred={got_au} single-device={want_au}")
        ok = False

    if ok:
        print(
            f"mesh-smoke PASS: {len(batches)} ragged batches on the {NUM_DEVICES}-device mesh == "
            f"eager in BOTH sync modes; AUROC(capacity) deferred == single-device "
            f"({got_au:.6f}); deferred step collectives=0 (step-sync: {n_step}); "
            f"compiles step={step_compiles} deferred={def_compiles}, repeat streams compile 0"
        )
    return 0 if ok else 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
