"""Quantized-sync smoke check: ``python -m metrics_tpu.engine.quant_smoke``.

The CPU-safe gate for the ISSUE 10 quantized-sync stack (``make quant-smoke``),
on the bootstrap 8-device virtual mesh:

1. bounded error — a float-heavy collection under ``sync_precision=
   "q8_block"`` streamed through a DEFERRED mesh engine lands within the
   per-metric bounded-error oracle (``Metric.sync_error_bounds`` over the
   actual shard-local states) of the exact-policy engine on the same
   traffic; integer count states are BIT-exact;
2. payload — the analytic per-sync payload (``sync_payload_bytes``) drops
   >= 3x for the quantized policy, and the engine's OpenMetrics
   ``sync_payload_bytes{kind=...}`` counters expose the split through the
   strict parser;
3. program identity — exact and quantized engines SHARE one ``AotCache``
   and never exchange executables (``sync_precision`` is in every program
   key): the second engine compiles its own full program set, and a repeat
   stream after ``reset()`` compiles NOTHING (zero steady compiles);
4. policy audit — the ``quantized-sync-policy-honored`` rule over the built
   engines' step/merge programs reports no findings;
5. kill/resume — the quantized engine snapshots COMPRESSED
   (``compress_payloads``: codec id in meta, sha256 sidecar over the
   compressed bytes), a fresh engine restores through it and replays the
   remainder: counts bit-exact, floats within the oracle bound.

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys

NUM_DEVICES = 8


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.quant_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import tempfile

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, BinnedAveragePrecision, MetricCollection
    from metrics_tpu.engine import AotCache, EngineConfig, StreamingEngine
    from metrics_tpu.parallel.collectives import sync_payload_bytes

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    buckets = (32,)
    ok = True

    def col(prec=None):
        # float-heavy: BinnedAveragePrecision's (C, T) f32 sum accumulators
        # dominate the payload; Accuracy's int32 counts pin the exact path
        c = MetricCollection(
            {"acc": Accuracy(), "bap": BinnedAveragePrecision(num_classes=8, thresholds=101)}
        )
        if prec:
            c.set_sync_precision(prec)
        return c

    rng = np.random.RandomState(0)
    batches = []
    for n in (13, 32, 7, 29, 18, 32):
        p = rng.rand(n, 8).astype(np.float32)
        p /= p.sum(axis=1, keepdims=True)
        batches.append((p, rng.randint(0, 8, n)))

    # ---- payload accounting: >= 3x for the quantized policy
    info_q = col("q8_block").sync_leaf_info()
    info_e = [(fx, leaf, "exact") for fx, leaf, _ in info_q]
    bytes_q = sync_payload_bytes(info_q, NUM_DEVICES)
    bytes_e = sync_payload_bytes(info_e, NUM_DEVICES)
    ratio = bytes_e / max(1, bytes_q)
    if ratio < 3.0:
        print(f"FAIL: sync payload ratio {ratio:.2f}x < 3x ({bytes_e} -> {bytes_q} bytes)")
        ok = False

    cache = AotCache()  # SHARED: policy must keep the engines apart
    snapdir = tempfile.mkdtemp(prefix="quant_smoke_")

    def run(engine):
        nonlocal ok
        with engine:
            for b in batches:
                engine.submit(*b)
            got = {k: np.asarray(v) for k, v in engine.result().items()}
            state = engine.state()
            warm = engine.aot_cache.misses
            engine.reset()
            for b in batches:
                engine.submit(*b)
            engine.result()
            steady = engine.aot_cache.misses - warm
        if steady != 0:
            print(f"FAIL: repeat stream compiled {steady} programs (expected 0)")
            ok = False
        return got, state

    exact_eng = StreamingEngine(
        col(), EngineConfig(buckets=buckets, mesh=mesh, axis="dp", mesh_sync="deferred"),
        aot_cache=cache,
    )
    want, want_state = run(exact_eng)

    quant_cfg = EngineConfig(
        buckets=buckets, mesh=mesh, axis="dp", mesh_sync="deferred",
        snapshot_every=3, snapshot_dir=snapdir, compress_payloads=True,
    )
    before_quant = cache.misses
    q_coll = col("q8_block")
    q_eng = StreamingEngine(q_coll, quant_cfg, aot_cache=cache)
    got, got_state = run(q_eng)
    q_compiles = cache.misses - before_quant
    if q_compiles < len(buckets) + 2:  # update/bucket + merge + compute, own set
        print(
            f"FAIL: quantized engine compiled only {q_compiles} programs over the "
            "shared cache — an exact-policy executable leaked across policies"
        )
        ok = False

    # ---- bounded-error oracle over the ACTUAL shard-local states
    # (exact engine's locals: quantization error <= bound of either run's
    # locals; use the exact engine's as the reference magnitude source)
    def locals_of(metric, batches, world):
        shards = [metric.init_state() for _ in range(world)]
        order = []  # round-robin rows over shards like the padded P("dp") split
        for p, t in batches:
            n = p.shape[0]
            per = -(-n // world)
            for w in range(world):
                rows = slice(w * per, min(n, (w + 1) * per))
                if rows.start < n:
                    shards[w] = metric.update_state(shards[w], p[rows], t[rows])
        return shards

    # the oracle does not need the engine's exact shard split — the bound is
    # monotone in per-block magnitude, so locals from ANY split of the same
    # traffic bound the error direction we assert (plus f32-sum slack below)
    shards = locals_of(col(), batches, NUM_DEVICES)
    stacked = jax.tree.map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *shards)
    bounds = q_coll.sync_error_bounds(stacked)
    for name in ("acc",):
        for k in ("correct", "total"):
            if not np.array_equal(np.asarray(got_state[name][k]), np.asarray(want_state[name][k])):
                print(f"FAIL: count state {name}.{k} not bit-exact under quantized sync")
                ok = False
    for k in ("TPs", "FPs", "FNs"):
        err = np.abs(np.asarray(got_state["bap"][k]) - np.asarray(want_state["bap"][k]))
        bound = bounds[f"bap.{k}"] + 1e-4 * np.abs(np.asarray(want_state["bap"][k])) + 1e-6
        if not bool((err <= 2.0 * bound).all()):  # 2x: engine split != oracle split
            print(
                f"FAIL: bap.{k} exceeds the bounded-error oracle: "
                f"max err {float(err.max()):.5f} vs bound {float(bound.max()):.5f}"
            )
            ok = False

    # ---- policy audit (the named rule, same code path as make analyze)
    from metrics_tpu.analysis import EngineAnalysis

    for tag, eng in (("exact", exact_eng), ("quantized", q_eng)):
        findings = EngineAnalysis().check(eng, label=f"quant-smoke/{tag}").findings
        if findings:
            for f in findings:
                print(f"FAIL: {f.render()}")
            ok = False

    # ---- OpenMetrics payload counters through the strict parser
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from tools.trace_export import parse_openmetrics

    fams = parse_openmetrics(q_eng.metrics_text())
    payload_fam = fams.get("metrics_tpu_engine_sync_payload_bytes")
    kinds = (
        {s["labels"].get("kind") for s in payload_fam["samples"]} if payload_fam else set()
    )
    if kinds != {"exact", "quantized"}:
        print(f"FAIL: sync_payload_bytes counters missing/wrong kinds: {kinds}")
        ok = False

    # ---- kill/resume through the COMPRESSED snapshot
    fresh = StreamingEngine(col("q8_block"), quant_cfg, aot_cache=cache)
    meta = fresh.restore(snapdir)
    if str(meta.get("codec", "")) == "":
        print("FAIL: snapshot meta carries no codec id despite compress_payloads")
        ok = False
    with fresh:
        for b in batches[int(meta["batches_done"]):]:
            fresh.submit(*b)
        resumed = {k: np.asarray(v) for k, v in fresh.result().items()}
    for k in resumed:
        if not np.allclose(resumed[k], want[k], atol=0.05, rtol=1e-3):
            print(f"FAIL: kill/resume through compressed snapshot diverged on {k}: "
                  f"{resumed[k]} vs {want[k]}")
            ok = False

    if ok:
        print(
            f"quant-smoke PASS: {ratio:.2f}x sync payload reduction "
            f"({bytes_e} -> {bytes_q} B/sync), quantized deferred engine within the "
            f"per-metric error oracle (counts bit-exact), {q_compiles} own programs "
            f"over the shared cache (no cross-policy executables), policy audit "
            f"clean, kill/resume through a compressed (codec={meta.get('codec')}) "
            "snapshot exact-within-bounds, zero steady compiles"
        )
    return 0 if ok else 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
