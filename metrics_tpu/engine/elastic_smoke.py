"""Elastic-overload smoke: ``python -m metrics_tpu.engine.elastic_smoke``.

The CI-shaped proof of the overload-proof serving layer (ISSUE 11) on the
8-device virtual CPU mesh (bootstraps itself via
``--xla_force_host_platform_device_count``, the ``streams_smoke`` recipe):

1. **Overload → ladder → shed.** A stream-sharded MultiStreamEngine (S=16
   streams over world=4, resident=2 slots/shard) serves seeded Zipfian
   traffic whose hot set SHIFTS mid-run (``engine/traffic.py``'s hot-spot
   mode): the pager starts faulting on every batch, the overload detector
   (spill rate) trips, and the degradation ladder walks its declared rungs —
   widen ``coalesce_window_ms`` → defer cold-stream reads → SHED the lowest
   priority class — each transition a ``ladder`` trace event. A probe submit
   for a shed-class stream must raise the typed
   :class:`~metrics_tpu.engine.admission.AdmissionRejected` with
   ``shed=True``.
2. **Shard death → live reshard.** A scheduled non-transient ``shard_loss``
   fault kills a shard mid-stream; with ``elastic_min_world=2`` armed the
   engine reshards IN PLACE to the surviving world (snapshot-through-the-
   restore-matrix: rows re-home via the spill-seeded pager), and serving
   continues — a dead shard degrades to a smaller world, never a dead
   engine. A manual ``reshard(world=4)`` later GROWS back under traffic.
3. **Recovery.** A cold-free recovery tail drains the overload signal: the
   ladder de-escalates to level 0 (the detector's own definition of "p99
   recovered"), the final window shows zero spill-outs, and the shed class
   admits again.
4. **Exactness.** Every NON-shed stream's ``results()`` entry is
   BIT-IDENTICAL to a fault-free, overload-free unsharded oracle fed the
   same admitted traffic (dyadic values; shed-class streams are excluded —
   shedding is the one deliberate data loss, and it is confined to the
   declared lowest class).
5. **Surfaces.** The OpenMetrics exposition (admission families by priority,
   ladder gauge, reshard counter) survives the strict parser, the telemetry
   renders through ``tools/engine_report.py``, and the trace carries
   ``ladder``/``reshard``/``admission_rejected`` events.

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys

NUM_DEVICES = 8
WORLD = 4
S = 16
RESIDENT = 2
BUCKETS = (8, 32)
SHED_CLASS = 2
SHED_STREAMS = (14, 15)  # the declared lowest-priority tenants
N_MAIN = 56
SHIFT_AT = 24


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.elastic_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import (
        AdmissionPolicy,
        AdmissionRejected,
        DegradationLadder,
        EngineConfig,
        FaultInjector,
        FaultSpec,
        MultiStreamEngine,
        OverloadDetector,
        TraceRecorder,
    )
    from metrics_tpu.engine.chaos_smoke import make_checker
    from metrics_tpu.engine.traffic import zipf_traffic

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", "tools"))
    import engine_report
    import trace_export

    _check, _failed = make_checker()
    collection = lambda: MetricCollection([Accuracy(), MeanSquaredError()])  # noqa: E731

    if len(jax.devices()) < NUM_DEVICES:
        print(f"FAIL: bootstrap gave {len(jax.devices())} devices, need {NUM_DEVICES}")
        return 1
    mesh = Mesh(np.asarray(jax.devices()[:WORLD]), ("dp",))

    # seeded hot-spot-shift traffic: the head rotates onto previously-cold
    # streams at SHIFT_AT — the working set the pager was serving evaporates
    traffic = zipf_traffic(
        S, N_MAIN, alpha=1.6, seed=31, max_rows=6,
        shift_at=SHIFT_AT, shift_rotation=S // 2,
    )

    rec = TraceRecorder(capacity=1 << 15)
    admission = AdmissionPolicy(
        rows_per_s=1e9, burst_rows=1e9,  # rate never binds: shedding is the policy under test
        priorities={sid: SHED_CLASS for sid in SHED_STREAMS},
        default_priority=1,
    )
    ladder = DegradationLadder(
        detector=OverloadDetector(
            queue_p99_us=None,            # CPU-CI latency is noise, not signal
            spill_rate=0.25,              # the hot-spot shift's fingerprint
            queue_depth_frac=0.95,
        ),
        rungs=("widen_coalesce", "defer_cold_reads", "shed"),
        up_after=2,
        down_after=2,
    )
    inj = FaultInjector(
        seed=41, plan={"shard_loss": FaultSpec(schedule=(5,), transient=False)}
    )
    engine = MultiStreamEngine(
        collection(), S,
        EngineConfig(
            buckets=BUCKETS, coalesce=8, mesh=mesh, axis="dp", mesh_sync="deferred",
            admission=admission, ladder=ladder, elastic_min_world=2,
            fault_injector=inj, trace=rec,
        ),
        stream_shard=True, resident_streams=RESIDENT,
    )

    fed = []       # every batch the engine actually admitted — the oracle's diet
    shed_drops = 0

    def feed(engine_, batch):
        nonlocal shed_drops
        sid, p, t = batch
        try:
            engine_.submit(sid, p, t)
        except AdmissionRejected as e:
            _check(e.shed, f"non-shed admission rejection mid-run: {e}")
            shed_drops += 1
            return False
        fed.append(batch)
        return True

    shed_level = len(ladder.rungs)
    with engine:
        for b in traffic:
            feed(engine, b)
        engine.flush()
        # the shard death landed early (scheduled occurrence): serving must
        # have continued on the surviving world
        _check(
            engine.stats.reshards >= 1 and engine._world == 2,
            f"shard_loss did not reshard (reshards={engine.stats.reshards}, "
            f"world={engine._world})",
        )
        last = engine.stats.reshard_last or {}
        _check(
            last.get("auto") is True and last.get("from_world") == WORLD,
            f"auto-reshard provenance wrong: {last}",
        )
        # pump deterministic spill pressure (three streams homed on one
        # shard, resident=2 — every touch evicts) until the ladder's walk
        # reaches the shed rung; bounded so a broken ladder fails loudly
        pump = zipf_traffic(3, 40, seed=77, max_rows=4)
        pumps = 0
        while engine.stats.ladder_level < shed_level and pumps < 40:
            sid3, p, t = pump[pumps]
            feed(engine, (4 + 4 * sid3, p, t))  # streams 4/8/12: one shard pre-loss
            engine.flush()
            pumps += 1
        _check(
            engine.stats.ladder_level == shed_level,
            f"ladder never reached the shed rung (level {engine.stats.ladder_level} "
            f"after {pumps} pumps)",
        )
        # the shed probe: a lowest-class submit must be refused, typed
        probe = (SHED_STREAMS[1], np.asarray([0.5], np.float32), np.asarray([1], np.int32))
        try:
            engine.submit(*probe)
            _check(False, "shed-class submit was admitted while the shed rung is engaged")
        except AdmissionRejected as e:
            _check(
                e.shed and e.priority == SHED_CLASS and e.retry_after_s == float("inf"),
                f"shed rejection malformed: shed={e.shed} prio={e.priority} "
                f"retry_after_s={e.retry_after_s}",
            )
        # a deferred (stale) read while overloaded: cold stream, cached value
        cold_probe_sid = 3
        engine.result(cold_probe_sid)   # populates the cache
        engine.result(cold_probe_sid)   # cold + cached -> served stale
        _check(engine.stats.deferred_reads >= 1, "defer_cold_reads rung never deferred a read")
        # recovery tail: a resident-sized working set drains the spill signal;
        # the ladder must walk all the way back down
        recovery = zipf_traffic(2, 24, seed=91, max_rows=4)
        for sid2, p, t in recovery:
            feed(engine, (sid2, p, t))  # streams 0 and 1 only
            engine.flush()
        _check(
            engine.stats.ladder_level == 0,
            f"ladder did not de-escalate after recovery (level {engine.stats.ladder_level})",
        )
        # shed released: the lowest class admits again
        ok = feed(engine, probe)
        _check(ok, "shed class still rejected after de-escalation")
        # grow back under traffic: the manual reshard half of elasticity
        engine.reshard(world=WORLD)
        _check(
            engine._world == WORLD and engine.stats.reshards >= 2,
            f"manual grow reshard failed (world={engine._world})",
        )
        outs_before_final = engine.stats.page_outs
        tail = zipf_traffic(4, 8, seed=13, max_rows=4)
        for sid4, p, t in tail:
            feed(engine, (sid4, p, t))
        got = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in engine.results().items()
        }
        spill_free_tail = engine.stats.page_outs - outs_before_final
        metrics_text = engine.metrics_text()
        telemetry = engine.telemetry()
        queue_hist = next(
            (h for h in rec.histograms() if h.name == "queue_wait_us"), None
        )
        p99_us = queue_hist.quantile(0.99) if queue_hist is not None else 0.0

    # ---------------------------------------------- fault-free unsharded oracle
    oracle = MultiStreamEngine(collection(), S, EngineConfig(buckets=BUCKETS))
    with oracle:
        for sid, p, t in fed:
            oracle.submit(sid, p, t)
        want = {
            sid: {k: np.asarray(v) for k, v in r.items()}
            for sid, r in oracle.results().items()
        }
    for sid in want:
        if sid in SHED_STREAMS:
            continue  # shedding is the one deliberate, declared data loss
        for k in want[sid]:
            _check(
                np.array_equal(got[sid][k], want[sid][k], equal_nan=True),
                f"non-shed stream {sid} {k} diverged: {got[sid][k]} != {want[sid][k]}",
            )
    shed_total = sum(admission.counters()["shed"].values())
    _check(shed_total >= 1, "the shed rung never actually rejected a submit")

    # ------------------------------------------------------------------ surfaces
    try:
        families = trace_export.parse_openmetrics(metrics_text)
    except ValueError as e:
        families = {}
        _check(False, f"OpenMetrics exposition invalid: {e}")
    for fam in ("admission_admitted", "admission_shed", "ladder_level", "reshards"):
        _check(
            f"metrics_tpu_engine_{fam}" in " ".join(families),
            f"family {fam} missing from the exposition",
        )
    adm = telemetry["summary"].get("admission") if "summary" in telemetry else None
    adm = adm or telemetry.get("admission")
    _check(bool(adm), "telemetry has no admission block")
    rendered = engine_report.render(telemetry if "summary" in telemetry else {"summary": telemetry})
    _check("admission" in rendered and "elastic reshards" in rendered,
           "engine_report does not render the admission/reshard blocks")
    n_ladder = len(rec.events("ladder"))
    n_reshard = len(rec.events("reshard"))
    _check(n_ladder == engine.stats.ladder_transitions,
           f"ladder events {n_ladder} != transitions {engine.stats.ladder_transitions}")
    _check(n_reshard == engine.stats.reshards,
           f"reshard events {n_reshard} != reshards {engine.stats.reshards}")
    _check(len(rec.events("admission_rejected")) >= 1, "no admission_rejected trace event")
    _check(spill_free_tail == 0,
           f"recovery window still spilling ({spill_free_tail} page-outs after recovery)")

    if _failed:
        return 1
    adm_counts = admission.counters()
    print(
        "elastic-smoke PASS: "
        f"hot-spot shift overloaded the pager, ladder walked to shed "
        f"({engine.stats.ladder_transitions} transitions, {shed_drops} shed drops, "
        f"{engine.stats.deferred_reads} deferred reads); shard death auto-resharded "
        f"world {WORLD}->2 and traffic grew it back ->{engine._world} "
        f"({engine.stats.reshards} reshards, all state through the restore matrix); "
        f"ladder recovered to level 0 with a spill-free tail "
        f"(queue residency p99 {p99_us:.0f}us); {len(fed)} admitted batches "
        f"bit-identical on every non-shed stream vs the unsharded oracle; "
        f"admission counters {adm_counts['admitted']} admitted / "
        f"{adm_counts['shed']} shed; OpenMetrics + engine_report surfaces valid"
    )
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
