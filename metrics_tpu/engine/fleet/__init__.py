"""Multi-host SPMD fleet serving (ISSUE 15, ROADMAP item 1).

:mod:`~metrics_tpu.engine.fleet.runtime` — :class:`FleetConfig` /
:class:`FleetEngine`: per-host ingestion pipelines (the existing engines,
untouched) under a collective-free steady state, boundary folds over a
one-device-per-host fleet mesh, and the globally consistent snapshot-cut
protocol (barrier-on-batch-boundary, no wall clock) with a typed
fleet ↔ single-process restore matrix.

:mod:`~metrics_tpu.engine.fleet.harness` — the two-process CPU CI harness
(``make fleet-smoke``): gloo collectives over local sockets, seeded Zipfian
traffic split per host, bit-identical to a single-process oracle,
kill-one-host → restore → exact replay.
"""
from metrics_tpu.engine.fleet.runtime import (
    FleetBarrierError,
    FleetConfig,
    FleetEngine,
    FleetHostLostError,
    FleetTopologyError,
    fleet_mesh,
    last_consistent_cut,
    restore_fleet_into,
)

__all__ = [
    "FleetBarrierError",
    "FleetConfig",
    "FleetEngine",
    "FleetHostLostError",
    "FleetTopologyError",
    "fleet_mesh",
    "last_consistent_cut",
    "restore_fleet_into",
]
