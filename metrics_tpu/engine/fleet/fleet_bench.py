"""BENCH.fleet_sync: 2-host boundary-fold latency, exact vs q8_block.

``python -m metrics_tpu.engine.fleet.fleet_bench`` spawns the harness's
two-process bench scenario (gloo CPU collectives over loopback) and prints
one JSON line:

* per ``sync_precision`` policy — ``exact`` and a blanket ``q8_block`` (only
  ELIGIBLE float-sum states quantize; counters stay exact, per the ISSUE 10
  policy contract) — the fleet boundary fold's latency (wall p50 + the
  stats-attributed collective mean) and the analytic per-fold payload bytes
  (``fused_sync_plan`` over the (S, ...)-stacked host state at world=2);
* ``streams_per_host`` — the tenancy observable the fleet adds (S streams
  homed ``sid % num_hosts``);
* RATIOS-IN-ONE-RUN: both policies measured by the same worker process in
  one runtime bring-up, so the payload ratio and the latency pair share
  every confounder.

``liveness_only`` is stamped on every rate: gloo over loopback sockets on a
timeshared CPU measures the PROTOCOL (program count, payload bytes, fold
shape), not an interconnect — the durable facts are the payload ratio and
the zero-steady-compile program set, same honesty contract as every other
virtual-topology bench entry.
"""
import json
import sys
import tempfile


def run() -> dict:
    from metrics_tpu.engine.fleet.harness import (
        BUCKETS,
        NUM_HOSTS,
        S,
        _run_pair,
    )

    workdir = tempfile.mkdtemp(prefix="metrics_tpu_fleet_bench_")
    rcs, outs = _run_pair("bench", workdir, "bench")
    if any(rc != 0 for rc in rcs) or any("error" in o for o in outs):
        return {
            "error": next(
                (o.get("error", "")[-400:] for o in outs if "error" in o),
                f"worker exit codes {rcs}",
            )
        }
    host0 = outs[0]
    pol = host0["policies"]
    exact_b = pol["exact"]["payload_bytes_per_fold"]
    quant_b = pol["q8_block"]["payload_bytes_per_fold"]
    return {
        "num_hosts": host0["num_hosts"],
        "streams_per_host": host0["streams_per_host"],
        "buckets": list(BUCKETS),
        "num_streams": S,
        "policies": pol,
        "sync_payload_ratio": round(exact_b / quant_b, 2) if quant_b else None,
        "liveness_only": True,
        "note": (
            "2 local processes, gloo CPU collectives over loopback — protocol "
            "measurement, no interconnect; durable facts: the payload ratio, "
            "the per-policy program identity, and the single-collective fold"
        ),
        "harness": f"NUM_HOSTS={NUM_HOSTS} via metrics_tpu.engine.fleet.harness",
    }


def main() -> int:
    print(json.dumps(run()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
