"""BENCH.fleet_sync + BENCH.fleet_tenancy: the fleet's wire and memory claims.

``python -m metrics_tpu.engine.fleet.fleet_bench`` spawns the harness's
two-process bench scenario (gloo CPU collectives over loopback) and prints
one JSON line; ``... fleet_bench tenancy`` instead runs the single-process
tenancy protocol (BENCH.fleet_tenancy, ISSUE 20):

* a stream-sharded windowed host swept over growing ``S`` with a FIXED
  resident arena — device-resident bytes per host must stay FLAT while the
  spilled rows grow (tenant capacity = fleet HBM + fleet host RAM);
* the hierarchical fold's per-leg byte accounting at 2 hosts, exact vs
  ``q8_block``, from ``hierarchical_fold_bytes`` over the engine's own
  ``_fleet_leaf_info`` (the same source the runtime's stats record — the
  bench can never drift from the wire);
* the bounded-error oracle: the q8 fold of the engine's REAL post-traffic
  state vs the exact f32 sum, elementwise within ``q8_sum_error_bound`` —
  asserted, with the measured max error and bound recorded.

The fleet_sync half:

* per ``sync_precision`` policy — ``exact`` and a blanket ``q8_block`` (only
  ELIGIBLE float-sum states quantize; counters stay exact, per the ISSUE 10
  policy contract) — the fleet boundary fold's latency (wall p50 + the
  stats-attributed collective mean) and the analytic per-fold payload bytes
  (``fused_sync_plan`` over the (S, ...)-stacked host state at world=2);
* ``streams_per_host`` — the tenancy observable the fleet adds (S streams
  homed ``sid % num_hosts``);
* RATIOS-IN-ONE-RUN: both policies measured by the same worker process in
  one runtime bring-up, so the payload ratio and the latency pair share
  every confounder.

``liveness_only`` is stamped on every rate: gloo over loopback sockets on a
timeshared CPU measures the PROTOCOL (program count, payload bytes, fold
shape), not an interconnect — the durable facts are the payload ratio and
the zero-steady-compile program set, same honesty contract as every other
virtual-topology bench entry.
"""
import json
import sys
import tempfile


def run() -> dict:
    from metrics_tpu.engine.fleet.harness import (
        BUCKETS,
        NUM_HOSTS,
        S,
        _run_pair,
    )

    workdir = tempfile.mkdtemp(prefix="metrics_tpu_fleet_bench_")
    rcs, outs = _run_pair("bench", workdir, "bench")
    if any(rc != 0 for rc in rcs) or any("error" in o for o in outs):
        return {
            "error": next(
                (o.get("error", "")[-400:] for o in outs if "error" in o),
                f"worker exit codes {rcs}",
            )
        }
    host0 = outs[0]
    pol = host0["policies"]
    exact_b = pol["exact"]["payload_bytes_per_fold"]
    quant_b = pol["q8_block"]["payload_bytes_per_fold"]
    return {
        "num_hosts": host0["num_hosts"],
        "streams_per_host": host0["streams_per_host"],
        "buckets": list(BUCKETS),
        "num_streams": S,
        "policies": pol,
        "sync_payload_ratio": round(exact_b / quant_b, 2) if quant_b else None,
        "liveness_only": True,
        "note": (
            "2 local processes, gloo CPU collectives over loopback — protocol "
            "measurement, no interconnect; durable facts: the payload ratio, "
            "the per-policy program identity, and the single-collective fold"
        ),
        "harness": f"NUM_HOSTS={NUM_HOSTS} via metrics_tpu.engine.fleet.harness",
    }


def _sharded_windowed_fleet(num_streams: int, sync_precision: str = "exact"):
    """One degenerate (1-host) stream-sharded windowed fleet, post-traffic:
    the arena/pager/leaf-info facts it exposes are exactly what a 2-host
    member would hold — host count only enters the ANALYTIC fold legs."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import EngineConfig, WindowPolicy
    from metrics_tpu.engine.fleet import FleetConfig, FleetEngine
    from metrics_tpu.engine.fleet.harness import (
        BUCKETS, RESIDENT, _collection,
    )
    from metrics_tpu.engine.traffic import zipf_traffic

    col = _collection()
    if sync_precision != "exact":
        col.set_sync_precision(sync_precision)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("dp",))
    fleet = FleetEngine(
        col,
        FleetConfig(
            num_streams=num_streams,
            stream_shard=True,
            resident_streams=RESIDENT,
            engine=EngineConfig(
                buckets=BUCKETS, mesh=mesh, axis="dp", mesh_sync="deferred",
                window=WindowPolicy.tumbling(pane_batches=16, n_panes=2),
            ),
        ),
    )
    with fleet:
        for sid, p, t in zipf_traffic(num_streams, 64, alpha=1.1, seed=7):
            fleet.ingest(sid, p, t)
        fleet.results()
        return (
            fleet,
            {
                "num_streams": num_streams,
                "device_resident_bytes": int(
                    sum(
                        int(v.size) * v.dtype.itemsize
                        for v in fleet.engine._state.values()
                    )
                ),
                **{
                    k: int(v)
                    for k, v in fleet.engine._pager.tenancy_stats().items()
                },
            },
        )


def run_tenancy() -> dict:
    """BENCH.fleet_tenancy (ISSUE 20): flat device residency, per-leg fold
    bytes exact vs hierarchical q8, and the q8_sum_error_bound oracle."""
    import numpy as np

    import jax
    from metrics_tpu.engine.fleet.harness import NUM_HOSTS, RESIDENT
    from metrics_tpu.parallel.collectives import (
        fused_sync_plan,
        hierarchical_fold_bytes,
        q8_roundtrip,
        q8_sum_error_bound,
    )

    # ---- device residency sweep: S grows 16x, the arena must not move
    sweep = []
    for n_streams in (8, 32, 128):
        fleet, row = _sharded_windowed_fleet(n_streams)
        sweep.append(row)
    flat = len({r["device_resident_bytes"] for r in sweep}) == 1
    spill_grows = (
        sweep[-1]["spilled_rows"] > sweep[0]["spilled_rows"] > 0
    )

    # ---- hierarchical fold legs at NUM_HOSTS, exact vs q8_block, from the
    # engine's own leaf info (`fleet` is the last, largest sweep member)
    legs = {}
    for policy in ("exact", "q8_block"):
        f = fleet if policy == "exact" else _sharded_windowed_fleet(
            sweep[-1]["num_streams"], sync_precision=policy
        )[0]
        legs[policy] = hierarchical_fold_bytes(
            f.engine._fleet_leaf_info(), NUM_HOSTS
        )
    exact_cross = legs["exact"]["cross_exact_bytes"] + legs["exact"]["cross_quant_bytes"]
    q8_cross = legs["q8_block"]["cross_exact_bytes"] + legs["q8_block"]["cross_quant_bytes"]

    # ---- bounded-error oracle on the REAL state: stack the host-logical
    # q8-eligible leaves into a fake 2-host fold (second host = half the
    # first — dyadic, so the EXACT sum is representable) and check the q8
    # fold lands elementwise within q8_sum_error_bound
    f_q8 = _sharded_windowed_fleet(sweep[-1]["num_streams"], "q8_block")[0]
    info = f_q8.engine._fleet_leaf_info()
    plan = fused_sync_plan(info, NUM_HOSTS)
    leaves = jax.tree.leaves(f_q8.engine.state())
    max_err = 0.0
    max_bound = 0.0
    holds = True
    checked = 0
    for i in plan["quantized"]:
        piece = np.asarray(leaves[i], np.float32)
        stacked = np.stack([piece, 0.5 * piece])
        got = sum(np.asarray(q8_roundtrip(s)) for s in stacked)
        err = np.abs(got - stacked.sum(axis=0))
        bound = np.asarray(q8_sum_error_bound(stacked))
        holds = holds and bool((err <= bound).all())
        max_err = max(max_err, float(err.max()))
        max_bound = max(max_bound, float(bound.max()))
        checked += 1
    return {
        "num_hosts": NUM_HOSTS,
        "resident_streams": RESIDENT,
        "residency_sweep": sweep,
        "device_resident_bytes_flat": bool(flat),
        "spill_rows_grow_with_streams": bool(spill_grows),
        "fold_legs": legs,
        "cross_bytes_exact": int(exact_cross),
        "cross_bytes_q8": int(q8_cross),
        "cross_payload_ratio": (
            round(exact_cross / q8_cross, 2) if q8_cross else None
        ),
        "q8_error_oracle": {
            "leaves_checked": checked,
            "max_abs_error": max_err,
            "max_bound": max_bound,
            "bound_holds": bool(holds),
        },
        "note": (
            "single-process protocol: residency measured on a degenerate "
            "sharded member (arena identical per host), fold legs analytic "
            "via hierarchical_fold_bytes over the engine's _fleet_leaf_info "
            "(the runtime's own accounting source), error oracle on the "
            "real post-traffic state"
        ),
    }


def main() -> int:
    which = sys.argv[1] if len(sys.argv) > 1 else "sync"
    print(json.dumps(run_tenancy() if which == "tenancy" else run()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
