"""Fleet runtime: multi-host SPMD serving over ``jax.distributed`` (ISSUE 15).

Everything below ROADMAP item 1's fold: the engines so far run ONE process
(virtual 8-device meshes); a real fleet is H processes, each owning its own
accelerators and its own ingest traffic. The fleet layers on the existing
engine instead of forking it:

* **Per-host ingestion.** Every host runs one ordinary local engine
  (:class:`~metrics_tpu.engine.multistream.MultiStreamEngine` when
  ``FleetConfig.num_streams`` is set, else a
  :class:`~metrics_tpu.engine.pipeline.StreamingEngine`) — host-local submit
  queues, bucketing, megabatch coalescing, AOT program set, the whole PR 2–13
  pipeline, untouched. Streams home by ``stream_id % num_hosts``; a host
  folds ONLY its own streams' rows, in submission order, so per-stream
  results are bit-identical to a single-process engine serving the same
  stream (pinned by ``make fleet-smoke``).
* **Deferred-only, collective-free steady state.** The carried state is
  host-local by construction — the steady step NEVER crosses hosts (the
  same contract as PR 5's deferred shard-local step, and pinned by the same
  ``no-collectives-in-deferred-step`` analysis rule over the fleet entry of
  the bootstrap matrix). A local mesh, when configured, must be
  ``mesh_sync="deferred"``: a step-sync local mesh would put collectives in
  the steady state, which is exactly what the fleet contract forbids.
* **Boundary folds over the fleet mesh.** ``result()``/``results()`` is a
  COLLECTIVE boundary: every host enters it at the same logical point of its
  ingest plan, each host's merged local state rides ONE
  ``fused_axis_sync`` bundle over the (num_hosts,)-device fleet mesh
  (``parallel/embedded.py::sharded_state_merge`` — one representative device
  per process), and every host gets the replicated global value locally.
  No coordinator round-trip: the fold IS the SPMD program. Because a
  non-home host holds the metric's INIT state (the reduction identity) for
  foreign streams, the cross-host fold of per-stream states is exact.
* **Globally consistent snapshots.** The cut schedule is a property of the
  SHARED ingest plan, never of wall clocks: hosts cut at agreed plan
  positions (``FleetConfig.snapshot_every`` global batches when driving
  through :meth:`FleetEngine.ingest`, or explicit
  :meth:`FleetEngine.fleet_snapshot` calls at plan-defined boundaries).
  Each cut is a barrier-on-batch-boundary: hosts enter a tiny fleet-mesh
  ``all_gather`` carrying their cut cursor, verify EVERY host presented the
  same cut (disagreement is a typed :class:`FleetBarrierError`), then write
  their host piece (``<dir>/host_<pid>/``) with host-topology provenance
  (num_hosts, process_id, host→stream homing, fleet_cut) and a cut marker.
  A cut is CONSISTENT when every host's piece exists — restore picks the
  newest such cut, so a host that died mid-cut degrades the fleet to the
  previous consistent generation, never to a torn one.
* **Restore matrix.** fleet → same-topology fleet: each host restores its
  own piece verbatim (replay from the cut is exact); fleet → single-process:
  :func:`restore_fleet_into` folds every host piece through
  ``merge_stacked_states``; single-process → fleet:
  :meth:`FleetEngine.adopt_single` embeds the snapshot into host 0 with
  init state elsewhere. Every cross-topology mismatch (host counts, host
  ids, cut indices) refuses LOUDLY with a typed error — a fleet piece is
  PARTIAL state and must never silently serve as the whole.

The CPU CI harness (two local processes over ``jax.distributed`` with gloo
collectives, ``engine/fleet/harness.py``, ``make fleet-smoke``) proves the
whole contract without an accelerator — with the honest caveat that CPU
loopback sockets measure the protocol, not an interconnect.
"""
import os
import re
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = [
    "FleetBarrierError",
    "FleetConfig",
    "FleetEngine",
    "FleetHostLostError",
    "FleetTopologyError",
    "fleet_mesh",
    "last_consistent_cut",
    "restore_fleet_into",
]

_HOST_DIR_RE = re.compile(r"^host_(\d{3})$")
_CUT_MARKER_RE = re.compile(r"^fleet_cut_(\d{6})$")


class FleetTopologyError(MetricsTPUUserError):
    """A fleet/host topology mismatch: wrong host count, wrong host id,
    inconsistent cut indices, or a single-process snapshot where a fleet
    piece was required (and vice versa)."""


class FleetBarrierError(RuntimeError):
    """Hosts entered a snapshot-cut barrier with DIFFERENT cut cursors —
    the ingest plans have diverged; serving must not write a generation
    that mixes two cuts."""


class FleetHostLostError(RuntimeError):
    """A fleet host was lost at a boundary (the non-transient ``host_loss``
    fault, or a real peer failure surfaced by the runtime): the fleet's
    steady state is host-local and intact, but cross-host boundaries cannot
    complete — restore the fleet from the last consistent snapshot cut."""


@dataclass
class FleetConfig:
    """Topology + per-host ingestion config for :class:`FleetEngine`.

    Args:
        num_processes: fleet size H. 1 (default) is the DEGENERATE fleet —
            no ``jax.distributed`` init, a 1-device fleet mesh, every stream
            homed locally. The degenerate fleet runs the identical boundary
            programs (merge/barrier with world 1), which is what keeps the
            fleet code path tier-1-testable in one process.
        process_id: this host's id in ``[0, num_processes)``.
        coordinator_address: ``host:port`` of process 0's coordinator
            (required when ``num_processes > 1`` unless ``jax.distributed``
            is already initialized by the launcher).
        engine: the per-host ingestion :class:`~metrics_tpu.engine.pipeline.
            EngineConfig`. A local mesh, if set, must be
            ``mesh_sync="deferred"`` (the fleet steady state is
            collective-free by contract); ``snapshot_dir``/``snapshot_every``
            must be unset — fleet snapshots follow the CUT protocol below,
            not a per-host cadence.
        num_streams: serve S independent streams (one
            ``MultiStreamEngine`` per host, stream ``sid`` homed on host
            ``sid % num_processes``). None serves a single accumulation
            (batches home by global plan position).
        stream_shard: run each host's engine STREAM-SHARDED (ISSUE 20): the
            host's paged arena carries ``resident_streams`` rows per local
            shard and its pager owns the spill rows for the host's HOME
            streams (``sid % num_processes`` homing — a non-home stream is
            never touched, so the fleet boundary fold reads its reduction-
            identity init row, exactly as it already does for non-home
            hosts). Tenant capacity then scales with fleet HBM + fleet host
            RAM instead of per-host HBM. Requires ``num_streams`` and an
            inner ``EngineConfig(mesh=..., mesh_sync="deferred",
            use_arena=True)``.
        resident_streams: per-local-shard paged-arena slot budget under
            ``stream_shard`` (0 = the engine's default: every local stream
            resident). An HBM budget, not a coordinate — restore re-homes
            across different residencies through the spill store.
        snapshot_dir: the FLEET snapshot directory (shared storage); host
            pieces land under ``host_<pid>/``.
        snapshot_every: cut cadence in GLOBAL plan batches for the
            :meth:`FleetEngine.ingest` driver (0 = explicit
            :meth:`FleetEngine.fleet_snapshot` calls only). Global-plan
            cadence — never per-host counts, never wall clocks — is what
            makes every host reach the same cut at the same plan position
            deterministically.
        fleet_axis: the fleet mesh axis name.
    """

    num_processes: int = 1
    process_id: int = 0
    coordinator_address: Optional[str] = None
    engine: Any = None
    num_streams: Optional[int] = None
    stream_shard: bool = False
    resident_streams: int = 0
    snapshot_dir: Optional[str] = None
    snapshot_every: int = 0
    fleet_axis: str = "fleet"


def _ensure_distributed(cfg: FleetConfig) -> None:
    """Idempotent ``jax.distributed`` bring-up for a real (H > 1) fleet.

    On CPU backends the gloo collectives implementation is selected first —
    without it a multi-process CPU fleet initializes but every cross-host
    collective aborts. Already-initialized runtimes (an external launcher,
    a prior FleetEngine in this process) are left untouched.
    """
    import jax

    from metrics_tpu.utils.compat import distributed_client

    if cfg.num_processes <= 1:
        return
    # already-initialized probe WITHOUT touching a backend (the shared
    # side-effect-free client-handle tell — utils/compat.py): process_count()
    # and friends lazily initialize XLA, after which jax.distributed refuses
    # to start. If the probe degrades (internals moved) we fall through to
    # initialize(), whose own RuntimeError is still a clear message.
    if distributed_client() is not None:
        return  # launcher (or a previous fleet) already brought the runtime up
    if cfg.coordinator_address is None:
        raise FleetTopologyError(
            "num_processes > 1 needs coordinator_address (process 0's "
            "host:port) unless jax.distributed is already initialized"
        )
    if os.environ.get("JAX_PLATFORMS", "cpu").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - older jaxlibs lack the flag
            pass
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=int(cfg.num_processes),
        process_id=int(cfg.process_id),
    )


def fleet_mesh(num_hosts: int, axis: str = "fleet"):
    """The (num_hosts,)-device fleet mesh: ONE representative device per
    process. Boundary folds move whole accumulated states, not activations —
    one device per host carries the host's merged state onto the wire, and
    the remaining local devices stay dedicated to the steady-state step."""
    import jax
    from jax.sharding import Mesh

    if num_hosts <= 0:
        raise FleetTopologyError(f"num_hosts must be positive, got {num_hosts}")
    if num_hosts == 1:
        return Mesh(np.asarray(jax.devices()[:1]), (axis,))
    devs = []
    for p in range(num_hosts):
        owned = [d for d in jax.devices() if d.process_index == p]
        if not owned:
            raise FleetTopologyError(
                f"process {p} of {num_hosts} exposes no devices — is "
                "jax.distributed initialized with the same num_processes?"
            )
        devs.append(owned[0])
    return Mesh(np.asarray(devs), (axis,))


def _host_dirs(fleet_dir: str) -> Dict[int, str]:
    """``{process_id: host dir}`` under a fleet snapshot directory."""
    try:
        names = os.listdir(fleet_dir)
    except (FileNotFoundError, NotADirectoryError):
        return {}
    out: Dict[int, str] = {}
    for n in sorted(names):
        m = _HOST_DIR_RE.match(n)
        if m:
            out[int(m.group(1))] = os.path.join(fleet_dir, n)
    return out


def _host_cuts(host_dir: str) -> Dict[int, str]:
    """``{cut index: snapshot basename}`` from one host dir's cut markers
    (markers referencing a GC'd or never-completed snapshot are skipped)."""
    out: Dict[int, str] = {}
    try:
        names = os.listdir(host_dir)
    except (FileNotFoundError, NotADirectoryError):
        return out
    for n in names:
        m = _CUT_MARKER_RE.match(n)
        if not m:
            continue
        try:
            with open(os.path.join(host_dir, n)) as f:
                snap = f.read().strip()
        except OSError:
            continue
        if snap and os.path.exists(os.path.join(host_dir, snap)):
            out[int(m.group(1))] = snap
    return out


def last_consistent_cut(fleet_dir: str, num_hosts: int) -> Optional[int]:
    """The newest cut index EVERY host completed, or None.

    A cut is consistent when all ``num_hosts`` host dirs carry its marker
    AND the referenced snapshot still exists — a host that died between the
    barrier and its save leaves the cut incomplete, and restore falls back
    to the previous consistent generation (replay from its older cursor is
    exact, same degradation contract as the snapshot generation ring).
    Raises :class:`FleetTopologyError` when the directory was written by a
    DIFFERENT host count: a 3-host fleet's pieces must never be read as a
    2-host fleet's.
    """
    dirs = _host_dirs(fleet_dir)
    if not dirs:
        return None
    if set(dirs) != set(range(num_hosts)):
        raise FleetTopologyError(
            f"fleet snapshot dir {fleet_dir!r} holds host pieces "
            f"{sorted(dirs)} but this fleet has num_hosts={num_hosts} "
            f"(expected exactly hosts 0..{num_hosts - 1}); restore it with a "
            "same-size fleet, or merge it into a single-process engine with "
            "restore_fleet_into()"
        )
    per_host = [set(_host_cuts(dirs[p])) for p in range(num_hosts)]
    common = set.intersection(*per_host) if per_host else set()
    return max(common) if common else None


class FleetEngine:
    """H-host SPMD serving of one metric/collection (ISSUE 15).

    Construction initializes ``jax.distributed`` (idempotently), builds the
    fleet mesh, and brings up this host's LOCAL engine — the per-host
    ingestion pipeline. The steady state is purely host-local;
    ``result()``/``results()``/``fleet_snapshot()``/``restore()`` are
    COLLECTIVE boundaries every host must enter at the same logical point of
    its ingest plan (the SPMD contract — there is no coordinator to order
    them). See the module docstring for the full protocol.
    """

    def __init__(self, metric: Any, config: Optional[FleetConfig] = None, aot_cache: Any = None):
        import jax

        from metrics_tpu.engine.multistream import MultiStreamEngine
        from metrics_tpu.engine.pipeline import EngineConfig, StreamingEngine

        self._fcfg = replace(config) if config is not None else FleetConfig()
        H, pid = int(self._fcfg.num_processes), int(self._fcfg.process_id)
        if H <= 0:
            raise FleetTopologyError(f"num_processes must be positive, got {H}")
        if not 0 <= pid < H:
            raise FleetTopologyError(
                f"process_id must be in [0, {H}), got {pid}"
            )
        inner = self._fcfg.engine if self._fcfg.engine is not None else EngineConfig()
        if not isinstance(inner, EngineConfig):
            raise MetricsTPUUserError(
                f"FleetConfig.engine must be an EngineConfig, got {type(inner).__name__}"
            )
        if inner.mesh is not None and inner.mesh_sync != "deferred":
            raise MetricsTPUUserError(
                "a fleet host's local mesh must run mesh_sync='deferred': the "
                "fleet steady state is collective-free by contract, and a "
                "step-sync local mesh would psum inside every step"
            )
        if inner.snapshot_dir or inner.snapshot_every:
            raise MetricsTPUUserError(
                "set FleetConfig.snapshot_dir/snapshot_every, not the inner "
                "EngineConfig's: fleet snapshots follow the globally "
                "consistent cut protocol (barrier-on-batch-boundary), not a "
                "per-host cadence"
            )
        if int(self._fcfg.snapshot_every) > 0 and not self._fcfg.snapshot_dir:
            raise MetricsTPUUserError(
                "FleetConfig.snapshot_every > 0 requires snapshot_dir — the "
                "first auto-cut would otherwise fail MID-PLAN, after real "
                "serving work (same construction-time contract as "
                "EngineConfig.snapshot_every)"
            )
        if self._fcfg.stream_shard and self._fcfg.num_streams is None:
            raise MetricsTPUUserError(
                "FleetConfig(stream_shard=True) needs num_streams: stream "
                "sharding partitions the per-stream paged arena, and a "
                "single-accumulation fleet has no stream axis to shard — set "
                "num_streams=S, or drop stream_shard and serve the single "
                "accumulation with plan-position homing"
            )
        if int(self._fcfg.resident_streams or 0) and not self._fcfg.stream_shard:
            raise MetricsTPUUserError(
                "FleetConfig.resident_streams only applies with "
                "stream_shard=True (it is the per-shard paged-arena slot "
                "budget) — set stream_shard=True, or drop resident_streams"
            )
        win = inner.window
        self._windowed = (
            win is not None and getattr(win, "kind", "cumulative") != "cumulative"
        )
        if self._windowed:
            # the fleet window contract (ISSUE 20): rotations ride the SHARED
            # plan cursor through the snapshot-cut protocol — the policy's own
            # fleet-eligibility check refuses wall-clock cadence, ewma, and
            # cat-state metrics, each naming the sanctioned alternative
            reason = win.fleet_unsupported_reason(metric)
            if reason is not None:
                raise MetricsTPUUserError(f"windowed fleet serving: {reason}")
            self._pane_batches = int(win.pane_batches)
            every = int(self._fcfg.snapshot_every)
            if every > 0 and self._pane_batches % every != 0:
                raise MetricsTPUUserError(
                    "fleet pane rotations ride the snapshot-cut protocol: "
                    "window.pane_batches must be a multiple of "
                    "FleetConfig.snapshot_every so every rotation lands on a "
                    "barriered, fleet-consistent cut boundary (got "
                    f"pane_batches={self._pane_batches}, "
                    f"snapshot_every={every})"
                )
        else:
            self._pane_batches = 0
        _ensure_distributed(self._fcfg)
        if H > 1:
            live = int(jax.process_count())
            if live != H:
                raise FleetTopologyError(
                    f"jax.distributed runtime has {live} processes but "
                    f"FleetConfig says num_processes={H}"
                )
        self._H, self._pid = H, pid
        self._axis = self._fcfg.fleet_axis
        self._mesh = fleet_mesh(H, self._axis)
        if H > 1:
            mine = [
                d for d in self._mesh.devices.flat
                if d.process_index == jax.process_index()
            ]
            if len(mine) != 1:  # pragma: no cover - fleet_mesh guarantees one
                raise FleetTopologyError(
                    f"fleet mesh carries {len(mine)} devices for this process "
                    "(expected exactly 1)"
                )
            self._fleet_device = mine[0]
        else:
            self._fleet_device = self._mesh.devices.flat[0]

        S = self._fcfg.num_streams
        if S is None:
            self._engine = StreamingEngine(metric, inner, aot_cache=aot_cache)
        else:
            ms_kwargs: Dict[str, Any] = {}
            if self._fcfg.stream_shard:
                ms_kwargs["stream_shard"] = True
                if int(self._fcfg.resident_streams or 0):
                    ms_kwargs["resident_streams"] = int(self._fcfg.resident_streams)
            self._engine = MultiStreamEngine(
                metric, int(S), inner, aot_cache=aot_cache, **ms_kwargs
            )
        if self._windowed:
            # pane rotations fire ONLY from the shared plan cursor (ingest):
            # the local batch cadence counts owned batches, which differ per
            # host — it must stay silent or hosts would rotate at different
            # ring positions
            self._engine._fleet_rotation = True
        # stamp the host topology onto the local engine: every snapshot it
        # writes now carries (num_hosts, process_id) provenance, and its
        # restore path refuses cross-topology commits (pipeline.py)
        self._engine._fleet_hosts = H
        self._engine._fleet_pid = pid
        st = self._engine.stats
        st.fleet_hosts = H
        st.fleet_process_id = pid
        st.fleet_streams_owned = len(self.streams_owned)
        self._metric = metric
        self._global_cursor = 0
        self._next_cut = 0
        self._payload_split: Optional[Tuple[int, int]] = None
        self._intra_bytes: Optional[int] = None
        if self._fcfg.snapshot_dir:
            self._host_dir = os.path.join(
                self._fcfg.snapshot_dir, f"host_{pid:03d}"
            )
            # the local engine owns the piece writes; its config gets the
            # host subdir (the fleet dir itself holds only host_*/)
            self._engine._cfg.snapshot_dir = self._host_dir
        else:
            self._host_dir = None

    # ------------------------------------------------------------------ topology

    @property
    def engine(self):
        """The host-local ingestion engine (the audit/telemetry target)."""
        return self._engine

    @property
    def mesh(self):
        return self._mesh

    @property
    def num_hosts(self) -> int:
        return self._H

    @property
    def process_id(self) -> int:
        return self._pid

    @property
    def num_streams(self) -> Optional[int]:
        return self._fcfg.num_streams

    @property
    def streams_owned(self) -> List[int]:
        """Stream ids homed on THIS host (``sid % num_hosts == process_id``)."""
        S = self._fcfg.num_streams
        if S is None:
            return []
        return [sid for sid in range(int(S)) if sid % self._H == self._pid]

    @property
    def global_cursor(self) -> int:
        """Plan position of the :meth:`ingest` driver (shared-plan batches
        seen, owned or not) — the coordinate snapshot cuts are defined in."""
        return self._global_cursor

    def home(self, stream_id: int) -> int:
        """The host that owns ``stream_id``."""
        return int(stream_id) % self._H

    # ------------------------------------------------------------------ lifecycle

    def start(self) -> "FleetEngine":
        self._engine.start()
        return self

    def stop(self) -> None:
        self._engine.stop()

    def __enter__(self) -> "FleetEngine":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        return False

    # -------------------------------------------------------------------- ingest

    def submit(self, *args: Any, **kwargs: Any) -> None:
        """Strict per-host submit: the batch must be homed HERE.

        Multi-stream fleets take ``(stream_id, *batch)`` and refuse foreign
        streams loudly (typed, naming the home host) — per-host ingestion
        means a host's front-end only ever accepts its own tenants'
        traffic. Single-metric fleets accept any batch (the caller owns the
        split; :meth:`ingest` is the plan-driven alternative).
        """
        if self._windowed:
            raise MetricsTPUUserError(
                "a windowed fleet is driven through FleetEngine.ingest(): "
                "pane rotations fire at shared-plan positions, and a direct "
                "submit() has no plan cursor to rotate against — drive the "
                "shared global plan through ingest() on every host"
            )
        if self._fcfg.num_streams is not None:
            sid = int(args[0])
            if sid % self._H != self._pid:
                raise FleetTopologyError(
                    f"stream {sid} homes on host {sid % self._H} "
                    f"(sid % num_hosts), not this host {self._pid}: route it "
                    "to its home host's ingestion pipeline (or drive the "
                    "shared plan through FleetEngine.ingest, which skips "
                    "foreign batches)"
                )
        self._engine.submit(*args, **kwargs)

    def ingest(self, *args: Any, **kwargs: Any) -> bool:
        """Drive one batch of the SHARED global plan through this host.

        Every host iterates the same deterministic plan and calls this for
        every batch; the fleet submits the batch only when it is homed here
        (stream home for multi-stream fleets, plan-position round-robin for
        single-metric ones) and ALWAYS advances the global cursor — which is
        what makes the automatic cut cadence (``snapshot_every`` global
        batches) land every host on the same barrier at the same plan
        position with no clock. Returns True when the batch was submitted
        locally.
        """
        pos = self._global_cursor
        if self._fcfg.num_streams is not None:
            owned = int(args[0]) % self._H == self._pid
        else:
            owned = pos % self._H == self._pid
        if owned:
            self._engine.submit(*args, **kwargs)
        self._engine.stats.record_fleet_ingest(owned)
        self._global_cursor = pos + 1
        # pane rotation BEFORE the cut at the same plan position — the same
        # ordering the single-process engine pins (a boundary snapshot
        # carries the post-rotation ring), so a restore at the cut never
        # re-rotates the boundary on replay. Both cadences are pure
        # functions of the shared cursor: every host rotates and cuts at
        # identical plan positions with no clock anywhere.
        if self._pane_batches > 0 and self._global_cursor % self._pane_batches == 0:
            self._engine.rotate_pane()
        every = int(self._fcfg.snapshot_every)
        if every > 0 and self._global_cursor % every == 0:
            self.fleet_snapshot()
        return owned

    def flush(self) -> None:
        """Host-local flush (no collective): every locally submitted batch
        folds into the host-local state."""
        self._engine.flush()

    def reset(self) -> None:
        """Host-local fresh accumulation (compiled programs kept) + fresh
        plan/cut cursors. NOT a collective — but a fleet whose hosts don't
        all reset at the same plan point serves mixed epochs, so drivers
        reset symmetrically like every other boundary."""
        self._engine.reset()
        self._global_cursor = 0
        self._next_cut = 0

    # ---------------------------------------------------------- fleet mesh programs

    def _host_abstract(self) -> Any:
        """This host's LOGICAL state template — what ``engine.state()``
        returns: the merged-global-within-host tree under a local deferred
        mesh, the (S, ...)-stream-stacked tree for multi-stream engines, and
        the ``(panes, S, ...)`` pane-EXTENDED tree for a windowed stream-
        sharded host (``state()`` regroups the pager's ext-id rows by pane;
        ``_win_stacked`` is off under stream_shard, so the generic pane
        stacking never applies and the lead axis is added here)."""
        import jax

        eng = self._engine
        if getattr(eng, "_stream_shard", False):
            pane_rows = int(getattr(eng, "_pane_rows", 1))
            lead = (int(eng._num_streams),)
            if pane_rows > 1:
                lead = (pane_rows,) + lead
            return jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(lead + tuple(s.shape), s.dtype),
                eng._metric.abstract_state(),
            )
        if eng._deferred:
            return eng._merged_abstract()
        return eng._abstract_state_tree()

    def _stacked_abstract(self) -> Any:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self._mesh, P(self._axis))
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                (self._H,) + tuple(s.shape), s.dtype, sharding=sh
            ),
            self._host_abstract(),
        )

    def _fleet_stack(self, host_tree: Any) -> Any:
        """Lift this host's logical tree into the global ``(H, ...)``-leaved
        fleet arrays: row ``pid`` lives on this host's fleet device, the
        other rows on their owners' — the standard multi-host global-array
        construction (each process contributes exactly its addressable
        shard)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self._mesh, P(self._axis))
        if self._H == 1:
            return jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x)[None], sh), host_tree
            )

        def one(x):
            local = jax.device_put(jnp.asarray(x)[None], self._fleet_device)
            return jax.make_array_from_single_device_arrays(
                (self._H,) + tuple(np.shape(x)), sh, [local]
            )

        return jax.tree.map(one, host_tree)

    def _stack_scalar(self, value: int):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self._mesh, P(self._axis))
        row = jnp.asarray([int(value)], jnp.int32)
        if self._H == 1:
            return jax.device_put(row, sh)
        local = jax.device_put(row, self._fleet_device)
        return jax.make_array_from_single_device_arrays((self._H,), sh, [local])

    def _replicated_scalar(self, value: int):
        """A fleet-replicated 0-d int32 — the runtime pane-cursor argument of
        a tumbling fleet's result program (every host holds the same cursor:
        rotations are pure functions of the shared plan cursor)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self._mesh, P())
        x = jnp.asarray(int(value), jnp.int32)
        if self._H == 1:
            return jax.device_put(x, sh)
        local = jax.device_put(x, self._fleet_device)
        return jax.make_array_from_single_device_arrays((), sh, [local])

    def _merge_program(self):
        """AOT: host-stacked logical states -> replicated GLOBAL state, one
        ``fused_axis_sync`` bundle over the fleet axis (the existing
        boundary-merge builder, pointed at the fleet mesh)."""
        import jax

        from metrics_tpu.parallel.embedded import sharded_state_merge

        eng = self._engine
        key = eng._aot.program_key(
            "fleet_state_merge", eng._metric_fp,
            arg_tree=self._stacked_abstract(), mesh=self._mesh, donate=False,
            sync="fleet", precision=eng._precision_tag,
        )

        def build():
            merge = sharded_state_merge(
                self._metric, self._mesh, self._axis,
                state_template=self._host_abstract(), unpack=None,
            )
            return jax.jit(merge).lower(self._stacked_abstract()).compile()

        return eng._aot.get_or_compile(key, build)

    def _result_program(self):
        """AOT: host-stacked states -> replicated metric VALUES — the merge
        and the compute fused into ONE SPMD program per boundary read (a
        vmapped per-stream compute for multi-stream fleets). Windowed fleets
        add the window fold AFTER the host merge: sliding folds the live
        pane set through ``merge_stacked_states``, tumbling indexes the
        current pane with a RUNTIME replicated cursor (one program across
        rotations — the window tag is in the key, the cursor is data)."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from metrics_tpu.parallel.embedded import sharded_state_merge

        eng = self._engine
        multistream = self._fcfg.num_streams is not None
        windowed = self._windowed
        tumbling = windowed and eng._window.kind == "tumbling"
        name = f"fleet_result{'_all' if multistream else ''}+k.{eng._kernel_tag()}"
        if windowed:
            name += f"+w.{eng._window_tag()}"
        key = eng._aot.program_key(
            name, eng._metric_fp,
            arg_tree=self._stacked_abstract(), mesh=self._mesh, donate=False,
            sync="fleet", precision=eng._precision_tag,
        )
        metric = self._metric

        def build():
            merge = sharded_state_merge(
                metric, self._mesh, self._axis,
                state_template=self._host_abstract(), unpack=None,
            )

            def run(stacked, *extra):
                merged = merge(stacked)
                if tumbling:
                    merged = jax.tree.map(
                        lambda x: lax.dynamic_index_in_dim(
                            x, extra[0], 0, keepdims=False
                        ),
                        merged,
                    )
                elif windowed:  # sliding: fold the live pane set
                    merged = metric.merge_stacked_states(merged)
                if multistream:
                    return jax.vmap(metric.compute_from)(merged)
                return metric.compute_from(merged)

            abs_args = (self._stacked_abstract(),)
            if tumbling:
                abs_args += (
                    jax.ShapeDtypeStruct(
                        (), np.int32, sharding=NamedSharding(self._mesh, P())
                    ),
                )
            with eng._kernel_scope():
                return jax.jit(run).lower(*abs_args).compile()

        return eng._aot.get_or_compile(key, build)

    def _barrier_program(self):
        """AOT: the cut barrier — every host contributes its (1,) cut cursor,
        an ``all_gather`` over the fleet axis returns all H cursors
        replicated. The gather IS the rendezvous; the agreement check is
        host-side."""
        import jax
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        eng = self._engine
        sh = NamedSharding(self._mesh, P(self._axis))
        abs_in = jax.ShapeDtypeStruct((self._H,), np.int32, sharding=sh)
        key = eng._aot.program_key(
            "fleet_barrier", "fleet",
            arg_tree=abs_in, mesh=self._mesh, donate=False, sync="fleet",
        )

        def build():
            def body(x):
                return lax.all_gather(x, self._axis, tiled=True)

            fn = jax.shard_map(
                body, mesh=self._mesh, in_specs=P(self._axis), out_specs=P(),
                check_vma=False,
            )
            return jax.jit(fn).lower(abs_in).compile()

        return eng._aot.get_or_compile(key, build)

    def _fleet_payload_split(self) -> Tuple[int, int]:
        """(exact, quantized) bytes one host contributes per fleet fold —
        the same analytic accounting as the deferred boundary merge
        (``fused_sync_plan``), over the HOST-stacked leaf shapes (a
        multi-stream host syncs (S, ...)-stacked leaves, so the payload
        scales by S exactly like the unsharded multistream merge's)."""
        if self._payload_split is None:
            # the engine's own accounting formula at world = the host count:
            # _fleet_leaf_info keeps fx <-> leaf pairing and multistream
            # S-scaling (and pane-scaling, and the stream-shard LOGICAL
            # shapes) correct, and sharing _payload_split_for means the
            # split convention can never diverge from the mesh surface's
            self._payload_split = self._engine._payload_split_for(
                self._H, leaf_info=self._engine._fleet_leaf_info()
            )
        return self._payload_split

    def _fleet_intra_bytes(self) -> int:
        """Bytes of the host-LOCAL logical tree each boundary folds before
        anything crosses the wire — the hierarchical fold's intra-host leg
        (scales with this host's stream residency; the cross legs above
        scale with hosts). One number per fold, analytic like the split."""
        if self._intra_bytes is None:
            from metrics_tpu.parallel.collectives import hierarchical_fold_bytes

            info = self._engine._fleet_leaf_info() or []
            self._intra_bytes = hierarchical_fold_bytes(info, self._H)[
                "intra_bytes"
            ]
        return self._intra_bytes

    def _refresh_tenancy(self) -> None:
        """Mirror the stream pager's residency/spill gauges into the fleet
        stats block (stream-sharded hosts only) — the observable that pins
        per-host device residency FLAT while the stream universe grows."""
        if not getattr(self._engine, "_stream_shard", False):
            return
        t = self._engine._pager.tenancy_stats()
        self._engine.stats.record_fleet_tenancy(
            t["resident_rows"], t["spilled_rows"], t["spill_bytes"]
        )

    # ------------------------------------------------------------------ boundaries

    def _boundary_collective(self, program, args: Tuple, site: str = "host_loss"):
        """Run one fleet-mesh collective with the fault-site/retry contract:
        ``site`` is consulted BEFORE the dispatch (a transient retries the
        whole collective cleanly — on a degenerate or symmetric-planned
        fleet every host retries in lockstep), and a non-transient
        ``host_loss`` surfaces as the typed :class:`FleetHostLostError`."""
        import time as _time

        import jax

        from metrics_tpu.engine.faults import InjectedFault

        eng = self._engine

        def once():
            eng._fault(site)
            t0 = _time.perf_counter()
            out = program(*args)
            jax.block_until_ready(out)
            return out, (_time.perf_counter() - t0) * 1e6

        try:
            return eng._retry_transient(once)
        except InjectedFault as e:
            if e.site == "host_loss" and not e.transient:
                raise FleetHostLostError(
                    f"host lost at a fleet boundary (process {self._pid} of "
                    f"{self._H}): the host-local steady state is intact; "
                    "restore the fleet from the last consistent snapshot cut"
                ) from e
            raise

    def fleet_state(self) -> Any:
        """The replicated GLOBAL logical state: flush, then one fleet-mesh
        fold of every host's local state. A collective boundary — every
        host must call."""
        self._engine.flush()
        host_tree = self._engine.state()
        out, us = self._boundary_collective(
            self._merge_program(), (self._fleet_stack(host_tree),)
        )
        self._engine.stats.record_fleet_merge(
            us, *self._fleet_payload_split(), intra_bytes=self._fleet_intra_bytes()
        )
        self._refresh_tenancy()
        return out

    def _boundary_values(self) -> Any:
        self._engine.flush()
        host_tree = self._engine.state()
        args: Tuple[Any, ...] = (self._fleet_stack(host_tree),)
        if self._windowed and self._engine._window.kind == "tumbling":
            args += (self._replicated_scalar(int(self._engine._pane_cursor)),)
        vals, us = self._boundary_collective(self._result_program(), args)
        st = self._engine.stats
        st.record_fleet_merge(
            us, *self._fleet_payload_split(), intra_bytes=self._fleet_intra_bytes()
        )
        self._refresh_tenancy()
        tr = self._engine.trace
        if tr is not None:
            from metrics_tpu.engine.trace import ENGINE_TRACE

            tr.complete("fleet_merge", trace=ENGINE_TRACE, dur_us=us)
        return vals

    def result(self, stream_id: Optional[int] = None) -> Any:
        """The globally folded metric value (all hosts' contributions), on
        ANY host — one fleet-mesh collective, no coordinator round-trip.
        A collective boundary: every host calls at the same plan point.
        Multi-stream fleets pass ``stream_id``; the fold moves the whole
        stacked state either way (one bundle, however many streams), so
        prefer :meth:`results` when reading many."""
        import jax

        vals = self._boundary_values()
        if self._fcfg.num_streams is None:
            if stream_id is not None:
                raise MetricsTPUUserError(
                    "stream_id is only valid for multi-stream fleets "
                    "(FleetConfig.num_streams)"
                )
            return vals
        if stream_id is None:
            raise MetricsTPUUserError(
                "a multi-stream fleet's result() needs a stream_id "
                "(or use results() for every stream)"
            )
        sid = int(stream_id)
        S = int(self._fcfg.num_streams)
        if not 0 <= sid < S:
            raise MetricsTPUUserError(f"stream_id {sid} out of range [0, {S})")
        return jax.tree.map(lambda x: x[sid], vals)

    def results(self) -> Dict[int, Any]:
        """Every stream's globally folded value — ONE fleet collective and
        one batched compute for any S, sliced host-side."""
        import jax

        if self._fcfg.num_streams is None:
            raise MetricsTPUUserError(
                "results() is the multi-stream surface; single-metric fleets "
                "read result()"
            )
        vals = jax.device_get(self._boundary_values())
        S = int(self._fcfg.num_streams)
        return {
            sid: jax.tree.map(lambda x: x[sid], vals) for sid in range(S)
        }

    # ------------------------------------------------------------------- snapshots

    def _barrier(self, cut: int) -> None:
        out, _us = self._boundary_collective(
            self._barrier_program(), (self._stack_scalar(cut),),
            site="fleet_barrier",
        )
        import jax

        np_out = np.asarray(jax.device_get(out))
        if not bool(np.all(np_out == int(cut))):
            raise FleetBarrierError(
                f"hosts disagree on the snapshot cut cursor: this host "
                f"presented cut {int(cut)} but the barrier gathered "
                f"{np_out.tolist()} — the ingest plans have diverged; no "
                "generation was written"
            )
        self._engine.stats.record_fleet_barrier()

    def fleet_snapshot(self, cut: Optional[int] = None) -> str:
        """Write this host's piece of globally consistent cut ``cut``
        (default: the next cut index).

        Protocol, in order: local flush (the cut lands on a batch boundary
        by construction), the cut BARRIER (all hosts gather their cut
        cursors over the fleet mesh and must agree — no wall clock
        anywhere), the host piece (the local engine's crash-safe snapshot,
        stamped with host topology + cut provenance), then the cut marker.
        The cut becomes fleet-consistent only once EVERY host's marker
        lands; a host dying anywhere in between leaves the previous
        consistent cut authoritative. A collective boundary — every host
        calls with the same cut at the same plan position.
        """
        if not self._host_dir:
            raise MetricsTPUUserError(
                "fleet_snapshot() requires FleetConfig.snapshot_dir"
            )
        k = self._next_cut if cut is None else int(cut)
        if k < 0:
            raise MetricsTPUUserError(f"cut must be >= 0, got {k}")
        self._engine.flush()
        self._barrier(k)
        eng = self._engine
        eng._fleet_cut = k
        eng._fleet_plan_cursor = self._global_cursor
        try:
            path = eng.snapshot()
        finally:
            eng._fleet_cut = None
        os.makedirs(self._host_dir, exist_ok=True)
        marker = os.path.join(self._host_dir, f"fleet_cut_{k:06d}")
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            f.write(os.path.basename(path))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, marker)
        self._next_cut = k + 1
        eng.stats.record_fleet_cut()
        return path

    def restore(self) -> Dict[str, Any]:
        """Resume this host from the last CONSISTENT fleet cut.

        Every host scans the shared fleet dir (a pure function of the same
        bytes, so every host derives the same cut), agrees on it through the
        barrier, and restores its OWN piece verbatim — replay each host's
        remaining plan from ``meta['fleet_plan_cursor']`` and the fleet's
        results are exactly the uninterrupted ones. Typed refusals for
        host-count/host-id/cut mismatches come from the restore matrix
        (``pipeline.py::_restore_commit`` + the checks here).
        """
        if not self._fcfg.snapshot_dir:
            raise MetricsTPUUserError("restore() requires FleetConfig.snapshot_dir")
        k = last_consistent_cut(self._fcfg.snapshot_dir, self._H)
        if k is None:
            raise FileNotFoundError(
                f"no consistent fleet snapshot cut under {self._fcfg.snapshot_dir!r}"
            )
        self._barrier(k)
        name = _host_cuts(self._host_dir).get(k)
        if name is None:  # pragma: no cover - consistency scan guarantees it
            raise FleetTopologyError(
                f"host {self._pid} has no piece for consistent cut {k}"
            )
        meta = self._engine.restore(os.path.join(self._host_dir, name))
        snap_cut = int(meta.get("fleet_cut", -1))
        if snap_cut != k:
            raise FleetTopologyError(
                f"host {self._pid}'s piece for cut {k} carries fleet_cut="
                f"{snap_cut} — the marker and the snapshot disagree; the "
                "fleet dir is torn"
            )
        self._global_cursor = int(meta.get("fleet_plan_cursor", 0))
        self._next_cut = k + 1
        return meta

    def adopt_single(self, path_or_dir: str) -> Dict[str, Any]:
        """Embed a SINGLE-PROCESS snapshot into this fleet: host 0 adopts
        the accumulated state (and its replay cursor), every other host
        resets to init — the cross-host fold then reproduces the adopted
        value exactly (init rows are reduction identities). The single →
        fleet entry of the restore matrix; a fleet host piece refuses here
        (restore it through :meth:`restore`). Every host calls."""
        from metrics_tpu.engine.snapshot import load_snapshot

        state, meta = load_snapshot(path_or_dir, fallback=True)
        snap_hosts = int(meta.get("num_hosts", 1) or 1)
        if snap_hosts != 1:
            raise FleetTopologyError(
                f"adopt_single() takes a single-process snapshot; this one is "
                f"host {meta.get('process_id')} of a {snap_hosts}-host fleet — "
                "restore fleet pieces through FleetEngine.restore()"
            )
        if self._pid == 0:
            patched = dict(meta)
            patched["num_hosts"] = self._H
            patched["process_id"] = 0
            self._engine._restore_commit(state, patched)
        else:
            self._engine.reset()
        self._global_cursor = 0
        self._next_cut = 0
        return meta

    # ------------------------------------------------------------------- telemetry

    def telemetry(self) -> Dict[str, Any]:
        """The local engine's telemetry document; its summary carries the
        ``fleet`` block (host id, streams owned, barrier/cut/merge counts,
        per-fold sync payload bytes, tenancy gauges)."""
        self._refresh_tenancy()
        return self._engine.telemetry()

    def export_telemetry(self, path: str) -> None:
        self._engine.export_telemetry(path)

    def metrics_text(self) -> str:
        """OpenMetrics: the local engine's exposition PLUS the
        ``host``-labeled fleet families. Single-process engines never emit
        a ``fleet_*`` family, so their expositions stay byte-stable."""
        from metrics_tpu.engine.trace import render_openmetrics

        self._refresh_tenancy()
        base = self._engine.metrics_text()
        st = self._engine.stats
        h = str(self._pid)
        labeled = {
            "fleet_ingested": ("host", {h: st.fleet_ingested}),
            "fleet_skipped": ("host", {h: st.fleet_skipped}),
            "fleet_merges": ("host", {h: st.fleet_merges}),
            "fleet_barriers": ("host", {h: st.fleet_barriers}),
            "fleet_snapshot_cuts": ("host", {h: st.fleet_cuts}),
            "fleet_sync_payload_bytes": (
                "host",
                {h: st.fleet_payload_exact_bytes + st.fleet_payload_quant_bytes},
            ),
            # the hierarchical fold by leg (ISSUE 20): intra = host-local
            # exact merges (scale with residency), cross = what actually
            # crossed hosts (scales with hosts, not streams, under q8)
            "fleet_payload_bytes": (
                "leg",
                {
                    "intra": st.fleet_payload_intra_bytes,
                    "cross": st.fleet_payload_exact_bytes
                    + st.fleet_payload_quant_bytes,
                },
            ),
        }
        gauges = {
            "fleet_num_hosts": self._H,
            "fleet_process_id": self._pid,
            "fleet_streams_owned": st.fleet_streams_owned,
            "fleet_spill_rows": st.fleet_spill_rows,
            "fleet_spill_bytes": st.fleet_spill_bytes,
            "fleet_resident_rows": st.fleet_resident_rows,
        }
        fleet_text = render_openmetrics({}, (), labeled_counters=labeled, gauges=gauges)
        # one exposition: the base's EOF terminator moves to the end
        assert base.endswith("# EOF\n")
        return base[: -len("# EOF\n")] + fleet_text


def restore_fleet_into(engine: Any, fleet_dir: str) -> Dict[str, Any]:
    """Merge a whole fleet snapshot into ONE single-process engine — the
    fleet → single-process entry of the restore matrix.

    Loads every host's piece at the last consistent cut, folds them with
    ``merge_stacked_states`` (host states stack on a leading axis; each
    state's own reduction folds it — exact for every
    ``dist_reduce_fx``-mergeable state), and commits through the engine's
    own restore path. The merged engine's ``result()`` equals the fleet's
    at the cut; REPLAY, however, needs the fleet's per-host plans — the
    returned meta's ``batches_done`` is the SUM of host cursors and is not
    a single-stream replay cursor (documented, and the reason fleet →
    fleet restore is the kill/resume path).

    Typed refusals: a target that is itself fleet-managed, host pieces from
    a mismatched host count (:func:`last_consistent_cut`), pieces whose
    metas disagree with their directory, and metrics whose states cannot
    stack-merge.
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.engine.snapshot import load_snapshot

    if getattr(engine, "_fleet_hosts", 1) != 1:
        raise FleetTopologyError(
            "restore_fleet_into() targets a SINGLE-PROCESS engine; this one "
            f"is host {engine._fleet_pid} of {engine._fleet_hosts} — use "
            "FleetEngine.restore()"
        )
    dirs = _host_dirs(fleet_dir)
    if not dirs:
        raise FileNotFoundError(f"no host pieces under {fleet_dir!r}")
    H = len(dirs)
    k = last_consistent_cut(fleet_dir, H)
    if k is None:
        raise FileNotFoundError(
            f"no consistent fleet snapshot cut under {fleet_dir!r}"
        )
    metric = engine._metric
    reason_fn = getattr(metric, "stacked_merge_unsupported_reason", None)
    reason = reason_fn() if reason_fn is not None else None
    if reason is not None:
        raise MetricsTPUUserError(
            f"fleet snapshot cannot merge into a single engine: {reason}"
        )
    logicals: List[Any] = []
    metas: List[Dict[str, Any]] = []
    for pid in range(H):
        name = _host_cuts(dirs[pid])[k]
        state, meta = load_snapshot(os.path.join(dirs[pid], name))
        if int(meta.get("num_hosts", 1) or 1) != H or int(meta.get("process_id", 0) or 0) != pid:
            raise FleetTopologyError(
                f"piece under host_{pid:03d} claims num_hosts="
                f"{meta.get('num_hosts')} process_id={meta.get('process_id')} "
                "— the fleet dir is inconsistent with its pieces"
            )
        if int(meta.get("fleet_cut", -1)) != k:
            raise FleetTopologyError(
                f"host {pid}'s piece for cut {k} carries fleet_cut="
                f"{meta.get('fleet_cut')} — marker and snapshot disagree"
            )
        snap_sshard = bool(int(meta.get("stream_shard", 0) or 0))
        if snap_sshard:
            # a stream-sharded host piece is {arena, pager} — resident rows
            # on device, spilled rows in host RAM, init rows implicit. The
            # engine-free static reassembly returns the piece's LOGICAL
            # tree ((panes, S, ...) under a ring window), so the cross-host
            # stack-merge below is shape-blind to how each host paged
            from metrics_tpu.engine.multistream import MultiStreamEngine

            if str(meta.get("codec", "") or "") and str(
                meta.get("codec_fp", "") or ""
            ) != engine._precision_tag:
                raise MetricsTPUUserError(
                    "compressed stream-shard fleet piece was written under "
                    f"sync_precision policy {meta.get('codec_fp')!r}, the "
                    f"target engine's metric declares "
                    f"{engine._precision_tag!r}; restore with the matching "
                    "policy"
                )
            logical = MultiStreamEngine.sshard_piece_logical(metric, state, meta)
            logicals.append(jax.tree.map(jnp.asarray, logical))
            metas.append(meta)
            continue
        if str(meta.get("codec", "") or ""):
            from metrics_tpu.engine.quantize import decode_state_tree

            state = decode_state_tree(state)
        packed = bool(int(meta.get("packed", 0)))
        snap_deferred = str(meta.get("mesh_sync", "") or "") == "deferred"
        snap_world = int(meta.get("world", 1))
        if packed:
            if engine._layout is None:
                raise MetricsTPUUserError(
                    "fleet piece holds a packed arena but the target engine "
                    "runs use_arena=False"
                )
            saved_fp = str(meta.get("arena_fp", "") or "")
            if saved_fp and saved_fp != engine._layout.fingerprint():
                raise MetricsTPUUserError(
                    f"host {pid}'s arena layout does not match the target "
                    "metric's — was the metric reconfigured since the snapshot?"
                )
        if snap_deferred:
            stacked_local = (
                engine._layout.unpack_stacked(state) if packed else state
            )
            logical = metric.merge_stacked_states(stacked_local)
        else:
            logical = engine._unpack(state) if packed else state
        logicals.append(jax.tree.map(jnp.asarray, logical))
        metas.append(meta)
    if str(metas[0].get("window", "") or ""):
        # a windowed fleet rotates at fleet-consistent plan positions, so
        # every piece must agree on the ring coordinates; disagreement means
        # the dir mixes cuts (or a host rotated off-plan) — refuse, the
        # merged pane ring would silently mix window generations
        rings = {
            (
                str(m.get("window", "") or ""),
                int(m.get("pane_cursor", 0) or 0),
                int(m.get("rotations", 0) or 0),
            )
            for m in metas
        }
        if len(rings) > 1:
            raise FleetTopologyError(
                f"host pieces disagree on the pane ring {sorted(rings)} — "
                "fleet rotations are plan-consistent by contract, so the "
                "fleet dir is torn; restore a consistent cut with "
                "FleetEngine.restore()"
            )
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *logicals)
    merged = metric.merge_stacked_states(stacked)
    out_meta = dict(metas[0])
    out_meta.update(
        num_hosts=1,
        process_id=0,
        packed=0,
        mesh_sync="single",
        world=1,
        codec="",
        arena_fp="",
        stream_shard=0,
        resident=0,
        step=sum(int(m.get("step", 0)) for m in metas),
        batches_done=sum(int(m.get("batches_done", 0)) for m in metas),
        rows_in=sum(int(m.get("rows_in", 0)) for m in metas),
        rows_padded=sum(int(m.get("rows_padded", 0)) for m in metas),
        fleet_cut=k,
        merged_from_hosts=H,
    )
    engine._restore_commit(merged, out_meta)
    return out_meta
