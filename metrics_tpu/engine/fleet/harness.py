"""Two-process CPU fleet harness: ``python -m metrics_tpu.engine.fleet.harness``.

The CI-shaped proof of the fleet runtime (ISSUE 15, ``make fleet-smoke``) on
ONE machine: two real OS processes over ``jax.distributed`` (gloo CPU
collectives, loopback sockets — the honest caveat being that this measures
the PROTOCOL, not an interconnect; every rate derived here is
``liveness_only``). Claims, each checked by the parent against artifacts the
workers write:

1. **Oracle parity** — seeded Zipfian traffic (``engine/traffic.py``,
   dyadic values) split per host by the ``sid % num_hosts`` homing rule,
   served by the 2-host fleet with snapshot cuts riding the shared plan;
   every per-stream ``results()`` value read on EITHER host is BIT-IDENTICAL
   to a single-process oracle serving the same plan.
2. **Same-seed determinism** — the whole two-process run executes TWICE:
   per-host per-stream results and per-host canonical span sequences
   (``TraceRecorder.canonical_sequence``, timestamps excluded) are
   identical across the runs.
3. **Closed program set** — after warmup, a reset + full replay on each host
   compiles ZERO new programs (the fleet boundary programs — merge, result,
   barrier — are part of the closed set).
4. **Collective placement** — every compiled steady-step program on every
   host carries ZERO cross-host collectives at jaxpr AND HLO level (the
   ``no-collectives-in-deferred-step`` analysis rule over the host engine,
   whose local mesh is deferred), while the fleet boundary program's HLO
   carries at least one (the fold has to cross hosts somewhere).
5. **Kill one host → restore → exact replay** — a third run serves to a
   mid-plan point past a consistent cut and host 1 dies (``os._exit``); a
   fourth run restores BOTH hosts from the last CONSISTENT cut (the torn
   trailing state is discarded), replays the remaining plan, and the final
   per-stream results equal the oracle bit-exactly.
6. **OpenMetrics** — each host's exposition strict-parses and carries the
   ``host``-labeled fleet families; the single-process oracle's exposition
   carries none (byte-stable vs a fleet-free engine).
7. **Tenancy** (ISSUE 20) — the same plan served by STREAM-SHARDED hosts
   whose paged arenas hold far fewer resident slots than their home
   streams, under a tumbling window whose pane rotations ride the shared
   plan cursor at cut-aligned positions: bit-exact vs the windowed
   single-process oracle THROUGH spills, zero steady compiles across
   paging and rotation, leg-labeled (``intra``/``cross``) fold-payload
   and spill gauges exported, and a kill → restore → replay that crosses
   a spill and still lands on exact oracle parity.

The parent owns WALL-TIME bounds (per-round subprocess deadlines) and
ORPHAN CLEANUP: any worker still alive when its round ends — timeout,
sibling crash, parent interrupt — is killed in a ``finally``. Workers exit
via ``os._exit`` after writing their artifact so a wedged
``jax.distributed`` teardown can never outlive the round.

Prints one PASS line; exits nonzero on any violated claim.
"""
import json
import os
import socket
import subprocess
import sys
import tempfile
import traceback

NUM_HOSTS = 2
S = 16                   # streams, homed sid % 2
N_BATCHES = 120          # global plan length
BUCKETS = (16, 32)
CUT_EVERY = 30           # global-plan batches per snapshot cut
KILL_AT = 75             # plan position where host 1 dies (past cut 1 @ 60)
SEED = 23
KILL_EXIT = 17           # the simulated-death exit code
ROUND_TIMEOUT_S = 420.0
# tenancy phase (ISSUE 20): stream-sharded + windowed fleet — S streams per
# host universe vs a RESIDENT-slot paged arena (S/NUM_HOSTS >> RESIDENT, so
# Zipf traffic genuinely pages through host RAM), pane rotations riding the
# shared plan cursor at cut-aligned positions (PANE_BATCHES % CUT_EVERY == 0)
RESIDENT = 3
PANE_BATCHES = 60
N_PANES = 2


def _collection():
    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection

    return MetricCollection([Accuracy(), MeanSquaredError()])


def _traffic():
    from metrics_tpu.engine.traffic import zipf_traffic

    return zipf_traffic(S, N_BATCHES, alpha=1.1, seed=SEED)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _jsonable_results(results) -> dict:
    import numpy as np

    return {
        str(sid): {k: np.asarray(v).tolist() for k, v in tree.items()}
        for sid, tree in results.items()
    }


def _results_equal(a: dict, b: dict) -> bool:
    """Bitwise per-stream equality with NaN == NaN (a stream the Zipf tail
    never touched computes 0/0 on BOTH sides — that is agreement)."""
    import numpy as np

    if set(a) != set(b):
        return False
    return all(
        set(a[s]) == set(b[s])
        and all(
            np.array_equal(
                np.asarray(a[s][k]), np.asarray(b[s][k]), equal_nan=True
            )
            for k in a[s]
        )
        for s in a
    )


# ---------------------------------------------------------------------- worker


def _build_fleet(spec: dict, pid: int, trace=None, snapshot_every=None):
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu.engine import EngineConfig
    from metrics_tpu.engine.fleet import FleetConfig, FleetEngine
    from metrics_tpu.engine.fleet.runtime import _ensure_distributed

    H = int(spec["num_hosts"])
    base = FleetConfig(
        num_processes=H, process_id=pid, coordinator_address=spec.get("coord")
    )
    # distributed FIRST: the local mesh below needs this process's devices,
    # which exist only once the runtime is up (no-op for the degenerate fleet)
    _ensure_distributed(base)
    # the per-host ingestion engine runs a 1-device LOCAL deferred mesh: the
    # steady step is then the REAL shard-local program the analysis rules pin
    # (a meshless engine would satisfy "no collectives" vacuously)
    mesh = Mesh(np.asarray(jax.local_devices()[:1]), ("dp",))
    tenancy = bool(spec.get("tenancy"))
    window = None
    if tenancy:
        from metrics_tpu.engine import WindowPolicy

        window = WindowPolicy.tumbling(pane_batches=PANE_BATCHES, n_panes=N_PANES)
    ecfg = EngineConfig(
        buckets=BUCKETS,
        coalesce=int(spec.get("coalesce", 1)),
        mesh=mesh,
        axis="dp",
        mesh_sync="deferred",
        window=window,
        trace=trace,
    )
    fcfg = FleetConfig(
        num_processes=H,
        process_id=pid,
        coordinator_address=spec.get("coord"),
        engine=ecfg,
        num_streams=S,
        stream_shard=tenancy,
        resident_streams=RESIDENT if tenancy else 0,
        snapshot_dir=spec.get("snapshot_dir"),
        snapshot_every=(
            int(snapshot_every) if snapshot_every is not None
            else int(spec.get("snapshot_every", 0))
        ),
    )
    return FleetEngine(_collection(), fcfg)


def _scenario_serve(spec: dict, pid: int, out: dict) -> None:
    """Serve the whole plan twice (reset between): parity + determinism +
    zero-steady-compiles + collective placement + OpenMetrics artifacts."""
    from metrics_tpu.analysis import check_no_collectives
    from metrics_tpu.engine import TraceRecorder
    from metrics_tpu.parallel.collectives import HLO_COLLECTIVE_RE

    rec = TraceRecorder(capacity=1 << 15)
    fleet = _build_fleet(spec, pid, trace=rec)
    traffic = _traffic()
    with fleet:
        for b in traffic:
            fleet.ingest(*b)
        res1 = fleet.results()
        warm = fleet.engine.aot_cache.misses
        fleet.reset()
        for b in traffic:
            fleet.ingest(*b)
        res2 = fleet.results()
        steady = fleet.engine.aot_cache.misses - warm
    r1, r2 = _jsonable_results(res1), _jsonable_results(res2)
    out["results"] = r1
    out["repeat_equal"] = _results_equal(r1, r2)
    out["steady_compiles"] = int(steady)
    out["dropped_spans"] = int(rec.dropped)
    out["spans"] = {
        track: [list(map(_canon_json, row)) for row in rows]
        for track, rows in rec.canonical_sequence().items()
    }
    # collective placement, HLO side: every steady-step program clean, the
    # fleet boundary program collective-bearing (H=2 — the fold crosses hosts)
    hlo_findings = []
    for prog in fleet.engine._program_memo.values():
        hlo_findings += [
            f.render()
            for f in check_no_collectives(
                hlo_text=prog.as_text(), where="fleet-harness/steady-step"
            )
        ]
    out["steady_hlo_findings"] = hlo_findings
    boundary_hlo = fleet._result_program().as_text()
    out["boundary_hlo_collectives"] = len(HLO_COLLECTIVE_RE.findall(boundary_hlo))
    # jaxpr side, via the real rule set: the host engine is a deferred-mesh
    # engine, so EngineAnalysis applies no-collectives-in-deferred-step (and
    # the rest of the program plane) to the re-traced steady step
    if pid == 0:
        from metrics_tpu.analysis.program import EngineAnalysis

        report = EngineAnalysis().check(fleet.engine, label=f"fleet-host{pid}")
        out["analysis_findings"] = [f.render() for f in report.findings]
    text = fleet.metrics_text()
    out["metrics_text"] = text
    out["fleet_block"] = fleet.telemetry().get("fleet")
    out["rotations"] = int(fleet.engine.stats.pane_rotations)


def _canon_json(v):
    if isinstance(v, tuple):
        return [_canon_json(x) for x in v]
    if isinstance(v, list):
        return [_canon_json(x) for x in v]
    return v


def _scenario_kill(spec: dict, pid: int, out: dict) -> None:
    """Serve to KILL_AT (cuts at 30/60 ride the plan), then host 1 DIES.

    Host 0 stops ingesting at the same plan position (a fleet that lost a
    host cannot cross its next barrier) and exits cleanly; nothing after
    the last consistent cut survives — which is the point."""
    fleet = _build_fleet(spec, pid)
    traffic = _traffic()
    with fleet:
        for b in traffic[:KILL_AT]:
            fleet.ingest(*b)
        fleet.flush()
        out["cursor"] = fleet.global_cursor
        out["cuts"] = fleet.engine.stats.fleet_cuts
        pager = getattr(fleet.engine, "_pager", None)
        if pager is not None:
            # the death must land PAST a spill for the tenancy claim: the
            # restored piece then re-homes rows out of the host-RAM store
            out["spilled_rows"] = int(pager.tenancy_stats()["spilled_rows"])
    if pid == 1:
        # the simulated host death: no result(), no clean teardown, the
        # process is GONE. The artifact must be DURABLE before os._exit —
        # which skips interpreter shutdown and buffered-file flushing, so
        # close explicitly rather than leaning on refcount timing
        with open(spec["out_paths"][pid], "w") as f:
            json.dump(out, f)
        os._exit(KILL_EXIT)


def _scenario_restore(spec: dict, pid: int, out: dict) -> None:
    """Both hosts restore from the last CONSISTENT cut and replay the rest
    of the plan; final results must equal the uninterrupted oracle."""
    fleet = _build_fleet(spec, pid)
    meta = fleet.restore()
    out["restored_cut"] = int(meta.get("fleet_cut", -1))
    out["restored_cursor"] = int(meta.get("fleet_plan_cursor", -1))
    traffic = _traffic()
    with fleet:
        for b in traffic[fleet.global_cursor:]:
            fleet.ingest(*b)
        out["results"] = _jsonable_results(fleet.results())
        pager = getattr(fleet.engine, "_pager", None)
        if pager is not None:
            # "exact replay PAST a spill": the replayed half must itself have
            # paged rows through host RAM, not just fit in the arena
            out["spilled_rows"] = int(pager.tenancy_stats()["spilled_rows"])


def _scenario_bench(spec: dict, pid: int, out: dict) -> None:
    """BENCH.fleet_sync's measured half: per sync_precision policy, the
    2-host boundary-fold latency (the fleet collective, stats-attributed)
    and the analytic per-fold payload bytes — both policies in ONE worker
    process, so the ratio is a same-process same-runtime fact."""
    import time as _time

    import numpy as np

    folds = int(spec.get("bench_folds", 8))
    traffic = _traffic()
    out["policies"] = {}
    for policy in ("exact", "q8_block"):
        col = _collection()
        if policy != "exact":
            col.set_sync_precision(policy)
        import jax
        from jax.sharding import Mesh

        from metrics_tpu.engine import EngineConfig
        from metrics_tpu.engine.fleet import FleetConfig, FleetEngine

        mesh = Mesh(np.asarray(jax.local_devices()[:1]), ("dp",))
        fleet = FleetEngine(
            col,
            FleetConfig(
                num_processes=int(spec["num_hosts"]), process_id=pid,
                coordinator_address=spec.get("coord"),
                engine=EngineConfig(
                    buckets=BUCKETS, coalesce=8, mesh=mesh, axis="dp",
                    mesh_sync="deferred",
                ),
                num_streams=S,
            ),
        )
        with fleet:
            for b in traffic:
                fleet.ingest(*b)
            fleet.results()  # warmup: compiles the boundary programs
            st = fleet.engine.stats
            wall, merge0 = [], st.fleet_merge_us_total
            for _ in range(folds):
                t0 = _time.perf_counter()
                fleet.results()
                wall.append((_time.perf_counter() - t0) * 1e6)
            merge_us = (st.fleet_merge_us_total - merge0) / folds
            exact_b, quant_b = fleet._fleet_payload_split()
        out["policies"][policy] = {
            "fold_wall_us_p50": float(np.median(wall)),
            "fold_wall_us_spread": [float(min(wall)), float(max(wall))],
            "fleet_merge_us_mean": float(merge_us),
            "payload_bytes_per_fold": int(exact_b + quant_b),
            "payload_bytes_quantized": int(quant_b),
        }
    out["streams_per_host"] = S // int(spec["num_hosts"])
    out["num_hosts"] = int(spec["num_hosts"])


_SCENARIOS = {
    "serve": _scenario_serve,
    "kill": _scenario_kill,
    "restore": _scenario_restore,
    "bench": _scenario_bench,
}


def _worker() -> None:
    """Subprocess entry: run one scenario for one host, write the artifact,
    ``os._exit`` (a wedged distributed teardown must never outlive the
    parent's round deadline)."""
    with open(os.environ["FLEET_WORKER_SPEC"]) as f:
        spec = json.load(f)
    pid = int(os.environ["FLEET_PROC_ID"])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # distributed bring-up BEFORE anything can touch a backend (importing
    # the library or calling process_count() lazily initializes XLA, after
    # which jax.distributed.initialize refuses to run)
    import jax

    jax.config.update("jax_platforms", "cpu")
    if int(spec["num_hosts"]) > 1:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=spec["coord"],
            num_processes=int(spec["num_hosts"]),
            process_id=pid,
        )
    out: dict = {"pid": pid}
    rc = 0
    try:
        _SCENARIOS[spec["scenario"]](spec, pid, out)
    except BaseException:  # noqa: BLE001 - the artifact carries the failure
        out["error"] = traceback.format_exc()
        rc = 1
    with open(spec["out_paths"][pid], "w") as f:
        json.dump(out, f)
    os._exit(rc)


# ---------------------------------------------------------------------- parent


def _run_pair(scenario: str, workdir: str, tag: str, **extra) -> tuple:
    """Spawn the two-host round, bounded and orphan-safe: every worker still
    alive when the round ends — deadline hit, sibling dead, parent
    interrupted — is killed before this function returns."""
    import time

    spec = {
        "scenario": scenario,
        "num_hosts": NUM_HOSTS,
        "coord": f"127.0.0.1:{_free_port()}",
        "out_paths": [
            os.path.join(workdir, f"{tag}_host{p}.json") for p in range(NUM_HOSTS)
        ],
        **extra,
    }
    spec_path = os.path.join(workdir, f"{tag}_spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec, f)
    code = "from metrics_tpu.engine.fleet.harness import _worker; _worker()"
    procs = []
    try:
        for p in range(NUM_HOSTS):
            env = dict(os.environ)
            env["FLEET_WORKER_SPEC"] = spec_path
            env["FLEET_PROC_ID"] = str(p)
            env["JAX_PLATFORMS"] = "cpu"
            # each worker is its own single-device CPU process — never
            # inherit a forced multi-device flag from the caller
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen([sys.executable, "-c", code], env=env))
        deadline = time.monotonic() + ROUND_TIMEOUT_S
        rcs = []
        for p in procs:
            left = max(1.0, deadline - time.monotonic())
            rcs.append(p.wait(timeout=left))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    outs = []
    for path in spec["out_paths"]:
        try:
            with open(path) as f:
                outs.append(json.load(f))
        except (OSError, ValueError):
            outs.append({"error": f"worker artifact missing: {path}"})
    return rcs, outs


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from metrics_tpu.engine import EngineConfig, MultiStreamEngine
    from metrics_tpu.engine.chaos_smoke import make_checker
    from metrics_tpu.engine.fleet import last_consistent_cut

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", "..", "tools"))
    import trace_export

    check, failed = make_checker()
    workdir = tempfile.mkdtemp(prefix="metrics_tpu_fleet_smoke_")
    traffic = _traffic()

    # ------------------------------------------------- single-process oracle
    oracle = MultiStreamEngine(_collection(), S, EngineConfig(buckets=BUCKETS))
    with oracle:
        for sid, p, t in traffic:
            oracle.submit(sid, p, t)
        want = _jsonable_results(oracle.results())
    oracle_text = oracle.metrics_text()
    check(
        "fleet_" not in oracle_text,
        "single-process exposition grew fleet families — must stay byte-stable",
    )
    trace_export.parse_openmetrics(oracle_text)

    def parity(tag, got, ref=None):
        ref = want if ref is None else ref
        for sid in ref:
            for k in ref[sid]:
                check(
                    np.array_equal(
                        np.asarray(got[sid][k]), np.asarray(ref[sid][k]),
                        equal_nan=True,
                    ),
                    f"{tag}: stream {sid} {k} {got[sid][k]} != {ref[sid][k]}",
                )

    # ------------------------------- two-process serve, TWICE (determinism)
    runs = []
    for run_ix in range(2):
        rcs, outs = _run_pair("serve", workdir, f"serve{run_ix}")
        for p, (rc, o) in enumerate(zip(rcs, outs)):
            check(rc == 0 and "error" not in o, f"serve{run_ix} host {p} failed: rc={rc} {o.get('error', '')[-800:]}")
        runs.append(outs)
    if failed:
        return 1
    for p in range(NUM_HOSTS):
        o = runs[0][p]
        parity(f"host {p} results vs oracle", o["results"])
        check(
            o["repeat_equal"],
            f"host {p}: reset+replay results differ within one process",
        )
        check(
            o["steady_compiles"] == 0,
            f"host {p} compiled {o['steady_compiles']} programs after warmup (expected 0)",
        )
        check(o["dropped_spans"] == 0, f"host {p} trace ring dropped spans")
        check(
            not o["steady_hlo_findings"],
            f"host {p} steady-step HLO carries collectives: {o['steady_hlo_findings'][:2]}",
        )
        check(
            o["boundary_hlo_collectives"] >= 1,
            f"host {p} fleet boundary HLO carries no cross-host collective",
        )
        check(
            _results_equal(runs[0][p]["results"], runs[1][p]["results"]),
            f"host {p}: same-seed runs returned different results",
        )
        check(
            runs[0][p]["spans"] == runs[1][p]["spans"],
            f"host {p}: same-seed canonical span sequences differ",
        )
        fams = trace_export.parse_openmetrics(o["metrics_text"])
        for fam in ("fleet_ingested", "fleet_merges", "fleet_barriers"):
            full = f"metrics_tpu_engine_{fam}"
            check(full in fams, f"host {p} exposition lacks {fam}")
            samples = fams[full]["samples"]
            check(
                any(s.get("labels", {}).get("host") == str(p) for s in samples),
                f"host {p} {fam} lacks the host label",
            )
        fb = o["fleet_block"] or {}
        check(
            fb.get("num_hosts") == NUM_HOSTS and fb.get("process_id") == p,
            f"host {p} telemetry fleet block wrong: {fb}",
        )
        check(
            fb.get("streams_owned") == S // NUM_HOSTS,
            f"host {p} owns {fb.get('streams_owned')} streams, expected {S // NUM_HOSTS}",
        )
    check(
        not runs[0][0].get("analysis_findings"),
        f"analysis rules flagged the fleet host engine: {runs[0][0].get('analysis_findings')[:2]}",
    )
    # the two hosts must have split the plan: both ingested and both skipped
    for p in range(NUM_HOSTS):
        fb = runs[0][p]["fleet_block"]
        # the serve scenario runs the plan twice (reset between)
        check(
            fb["ingested"] > 0 and fb["skipped"] > 0
            and fb["ingested"] + fb["skipped"] == 2 * N_BATCHES,
            f"host {p} ingest split wrong: {fb}",
        )

    # ------------------------------------------ kill one host mid-stream
    snapdir = os.path.join(workdir, "fleet_snaps")
    rcs, outs = _run_pair(
        "kill", workdir, "kill",
        snapshot_dir=snapdir, snapshot_every=CUT_EVERY, coalesce=8,
    )
    check(
        rcs[0] == 0 and rcs[1] == KILL_EXIT,
        f"kill round exit codes {rcs} (wanted [0, {KILL_EXIT}])",
    )
    check(
        "error" not in outs[0],
        f"surviving host failed: {outs[0].get('error', '')[-800:]}",
    )
    check(
        outs[0].get("cuts") == KILL_AT // CUT_EVERY,
        f"surviving host took {outs[0].get('cuts')} cuts before the death, "
        f"expected {KILL_AT // CUT_EVERY}",
    )
    k = last_consistent_cut(snapdir, NUM_HOSTS)
    check(
        k == KILL_AT // CUT_EVERY - 1,
        f"last consistent cut {k}, expected {KILL_AT // CUT_EVERY - 1}",
    )

    # ------------------------------------------- restore + exact replay
    rcs, outs = _run_pair(
        "restore", workdir, "restore",
        snapshot_dir=snapdir, snapshot_every=CUT_EVERY, coalesce=8,
    )
    for p, (rc, o) in enumerate(zip(rcs, outs)):
        check(rc == 0 and "error" not in o, f"restore host {p} failed: rc={rc} {o.get('error', '')[-800:]}")
    if failed:
        return 1
    expect_cursor = (KILL_AT // CUT_EVERY) * CUT_EVERY
    for p in range(NUM_HOSTS):
        check(
            outs[p]["restored_cut"] == k
            and outs[p]["restored_cursor"] == expect_cursor,
            f"host {p} restored cut/cursor {outs[p]['restored_cut']}/"
            f"{outs[p]['restored_cursor']}, expected {k}/{expect_cursor}",
        )
        parity(f"post-restore host {p}", outs[p]["results"])

    # ---------------- tenancy phase (ISSUE 20): stream-sharded + windowed
    # Same plan, but each host now runs a stream-sharded paged arena
    # (RESIDENT slots << its S/NUM_HOSTS home streams, so Zipf traffic pages
    # through host RAM) under a tumbling window whose rotations ride the
    # SHARED plan cursor at cut-aligned positions. The oracle is the same
    # single-process engine with the same window and NO sharding.
    from metrics_tpu.engine import WindowPolicy

    worc = MultiStreamEngine(
        _collection(), S,
        EngineConfig(
            buckets=BUCKETS,
            window=WindowPolicy.tumbling(
                pane_batches=PANE_BATCHES, n_panes=N_PANES
            ),
        ),
    )
    with worc:
        for sid, p, t in traffic:
            worc.submit(sid, p, t)
        wwant = _jsonable_results(worc.results())

    rcs, outs = _run_pair("serve", workdir, "tenancy_serve", tenancy=True)
    for p, (rc, o) in enumerate(zip(rcs, outs)):
        check(
            rc == 0 and "error" not in o,
            f"tenancy serve host {p} failed: rc={rc} {o.get('error', '')[-800:]}",
        )
    if failed:
        return 1
    for p in range(NUM_HOSTS):
        o = outs[p]
        parity(f"tenancy host {p} vs windowed oracle", o["results"], ref=wwant)
        check(
            o["repeat_equal"],
            f"tenancy host {p}: reset+replay results differ within one process",
        )
        check(
            o["steady_compiles"] == 0,
            f"tenancy host {p} compiled {o['steady_compiles']} programs after "
            "warmup (expected 0 — paging and rotation reuse the closed set)",
        )
        # the serve scenario runs the plan TWICE; rotations ride the shared
        # plan cursor, so each run rotates exactly N_BATCHES/PANE_BATCHES times
        check(
            o["rotations"] == 2 * (N_BATCHES // PANE_BATCHES),
            f"tenancy host {p} rotated {o['rotations']} times, expected "
            f"{2 * (N_BATCHES // PANE_BATCHES)}",
        )
        fb = o["fleet_block"] or {}
        ten = fb.get("tenancy") or {}
        check(
            0 < ten.get("resident_rows", 0) <= RESIDENT,
            f"tenancy host {p} resident_rows {ten.get('resident_rows')} "
            f"outside (0, {RESIDENT}]",
        )
        check(
            ten.get("spill_rows", 0) > 0 and ten.get("spill_bytes", 0) > 0,
            f"tenancy host {p} never spilled ({ten}) — the phase must "
            "genuinely page through host RAM",
        )
        fams = trace_export.parse_openmetrics(o["metrics_text"])
        for fam in ("fleet_spill_rows", "fleet_spill_bytes", "fleet_resident_rows"):
            check(
                f"metrics_tpu_engine_{fam}" in fams,
                f"tenancy host {p} exposition lacks {fam}",
            )
        legs = {
            s.get("labels", {}).get("leg")
            for s in fams.get(
                "metrics_tpu_engine_fleet_payload_bytes", {}
            ).get("samples", [])
        }
        check(
            {"intra", "cross"} <= legs,
            f"tenancy host {p} fleet_payload_bytes legs {legs} lack intra/cross",
        )

    # kill one host mid-pane, past a spill; restore from the consistent cut
    # (which is ALSO a rotation boundary — PANE_BATCHES % CUT_EVERY == 0) and
    # replay to exact windowed-oracle parity
    tsnapdir = os.path.join(workdir, "tenancy_snaps")
    rcs, outs = _run_pair(
        "kill", workdir, "tenancy_kill", tenancy=True,
        snapshot_dir=tsnapdir, snapshot_every=CUT_EVERY, coalesce=8,
    )
    check(
        rcs[0] == 0 and rcs[1] == KILL_EXIT,
        f"tenancy kill round exit codes {rcs} (wanted [0, {KILL_EXIT}])",
    )
    check(
        "error" not in outs[0],
        f"tenancy surviving host failed: {outs[0].get('error', '')[-800:]}",
    )
    check(
        outs[0].get("spilled_rows", 0) > 0,
        "tenancy kill landed before any spill — the death must strand rows "
        "in the host-RAM store",
    )
    tk = last_consistent_cut(tsnapdir, NUM_HOSTS)
    check(
        tk == KILL_AT // CUT_EVERY - 1,
        f"tenancy last consistent cut {tk}, expected {KILL_AT // CUT_EVERY - 1}",
    )
    rcs, outs = _run_pair(
        "restore", workdir, "tenancy_restore", tenancy=True,
        snapshot_dir=tsnapdir, snapshot_every=CUT_EVERY, coalesce=8,
    )
    for p, (rc, o) in enumerate(zip(rcs, outs)):
        check(
            rc == 0 and "error" not in o,
            f"tenancy restore host {p} failed: rc={rc} {o.get('error', '')[-800:]}",
        )
    if failed:
        return 1
    for p in range(NUM_HOSTS):
        check(
            outs[p]["restored_cut"] == tk
            and outs[p]["restored_cursor"] == expect_cursor,
            f"tenancy host {p} restored cut/cursor {outs[p]['restored_cut']}/"
            f"{outs[p]['restored_cursor']}, expected {tk}/{expect_cursor}",
        )
        check(
            outs[p].get("spilled_rows", 0) > 0,
            f"tenancy host {p} replay never paged a row — the parity claim "
            "must cover the spill path",
        )
        parity(
            f"post-restore tenancy host {p}", outs[p]["results"], ref=wwant
        )

    if failed:
        return 1
    print(
        "fleet-smoke PASS: "
        f"2-process CPU fleet (gloo) served {N_BATCHES} Zipfian batches over "
        f"{S} streams (homed sid % {NUM_HOSTS}) bit-identical to the "
        "single-process oracle on BOTH hosts; same-seed double run "
        "bit-identical (results + canonical span sequences per host); "
        "0 steady compiles after warmup; steady-step HLO/jaxpr collective-"
        "free (analysis rules) while the fleet boundary fold carries "
        f"{runs[0][0]['boundary_hlo_collectives']} collective(s); cuts every "
        f"{CUT_EVERY} plan batches via the barrier protocol; host 1 killed at "
        f"plan {KILL_AT} -> both hosts restored from consistent cut {k} "
        f"(cursor {expect_cursor}) and replayed to exact oracle parity; "
        "host-labeled OpenMetrics strict-parsed, single-process exposition "
        "fleet-free; tenancy phase: stream-sharded hosts "
        f"({RESIDENT} resident slots vs {S // NUM_HOSTS} home streams) under "
        f"a tumbling window rotating every {PANE_BATCHES} plan batches "
        "matched the windowed oracle bit-exactly through spills, 0 steady "
        "compiles, leg-labeled payload families exported, and kill->restore "
        f"from cut {tk} replayed past a spill to exact parity "
        "(CPU harness: no interconnect, rates liveness_only)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
