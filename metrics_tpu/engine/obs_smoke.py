"""Observability smoke: ``python -m metrics_tpu.engine.obs_smoke [trace.json] [metrics.txt]``.

The CI-shaped proof of the flight-recorder contract (PR 8), in seconds on one
CPU device (``make obs-smoke``):

1. **Traced serving run** — a coalescing engine under the recorder: the
   exported Chrome/Perfetto document is schema-valid
   (``tools/trace_export.py``), every megabatch span links EXACTLY the
   submit spans it absorbed (each submit absorbed once, none orphaned), at
   least one genuine megabatch formed, and the telemetry document renders
   through ``tools/engine_report.py --json`` with the trace/SLO section.
2. **OpenMetrics surface** — ``engine.metrics_text()`` parses as a valid
   exposition: counters sample ``_total``, the four latency histograms carry
   cumulative ascending buckets ending in ``+Inf`` with ``_count`` equal to
   the ``+Inf`` bucket, and the document terminates with ``# EOF``. The
   step histogram's totals must conserve: every step observed exactly once
   (bucket sum == count == engine steps). The per-bucket numpy oracle for
   the ``histogram_accumulate`` dogfooding fold is
   ``tests/engine/test_trace.py`` (latencies are nondeterministic here, so
   a value-level cross-check has nothing stable to pin).
3. **Span-sequence determinism** — the SAME seeded chaos plan (all fault
   sites but ``dispatcher_kill``: transactional rollback/retry, kernel
   demotion, watchdog, contained snapshot failure + corruption + fallback
   restore with replay, deferred boundary-merge retry, stream-shard
   ``page_out``/``page_in`` transients under seeded Zipfian traffic, the
   at-rest codec's ``quant_encode``/``quant_decode``, and the ISSUE 11
   elastic sites — ``admission``, a transient suspected ``shard_loss``, and
   ``reshard_snapshot``/``reshard_restore`` under a manual ``reshard()`` —
   plus the ISSUE 13 windowed sites: a ``pane_rotate`` plan transient on a
   sliding ring AND on an ewma decay, and a ``drift_eval`` transient on the
   closing-pane read — plus the ISSUE 15 fleet boundary sites:
   ``fleet_barrier`` on a degenerate 1-host fleet's snapshot cut and
   ``host_loss`` on its first cross-host fold) runs TWICE into fresh recorders; the canonical span sequences
   (timestamps excluded) must be IDENTICAL, and both chaos results
   bit-identical to each other. This is the occurrence-determinism
   contract: a chaos trace replays exactly.
4. **Dead dispatcher** — a fatal ``dispatcher_kill`` under its own recorder
   still produces its fault span event (the last site), completing coverage.

Lock invariants this smoke USED to be the only guard for are now statically
checked by ``make analyze``'s concurrency plane (ISSUE 14,
``analysis/rules/locks.py``): the recorder lock guards the span ring /
trace counter / histogram table, the histogram lock guards the pending
buffer and counts, the two NEVER nest (``FORBIDDEN_NESTINGS`` — what keeps
a scrape's jax fold off the submit path), and neither ever holds across a
jax dispatch. A refactor that deletes one of these locks — or quietly
re-nests them — fails ``make analyze`` before this smoke can flake on the
resulting stall or torn exposition.

Sidecars land under the gitignored ``out/`` per the repo's sidecar-hygiene
convention. Prints one PASS line; exits nonzero on any violated claim.
"""
import json
import os
import sys
import tempfile
import time

import numpy as np


def main(
    trace_path: str = "out/trace_obs.json",
    metrics_path: str = "out/obs_metrics.txt",
) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from metrics_tpu import Accuracy
    from metrics_tpu.engine import (
        EngineConfig,
        EngineDispatchError,
        StreamingEngine,
        TraceRecorder,
    )
    # the scenario AND the failure harness are chaos_smoke's OWN factories —
    # "the same seeded chaos plan" below is the same by construction, not by
    # a copied literal, and the two gates' FAIL-line contract cannot diverge
    from metrics_tpu.engine import MultiStreamEngine
    from metrics_tpu.engine.chaos_smoke import (
        SSHARD_RESIDENT,
        SSHARD_STREAMS,
        chaos_collection as collection,
        chaos_engine_config,
        chaos_injectors,
        chaos_traffic,
        deferred_engine_config,
        elastic_engine_config,
        ewma_engine_config,
        kill_engine_config,
        make_checker,
        quant_engine_config,
        resume_engine_config,
        run_fleet_phase,
        stream_shard_engine_config,
        stream_shard_traffic,
        windowed_engine_config,
    )
    from metrics_tpu.engine.faults import FAULT_SITES

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", "tools"))
    import engine_report
    import trace_export

    _check, _failed = make_checker()

    clean, chaos_batches = chaos_traffic()

    # ------------------------------------------- 1. traced coalescing serving
    rec = TraceRecorder(capacity=1 << 14)
    engine = StreamingEngine(
        collection(),
        EngineConfig(buckets=(8, 32), coalesce=8, coalesce_window_ms=250.0, trace=rec),
    )
    with engine:
        for b in clean:
            engine.submit(*b)
        engine.result()
    _check(engine.stats.megasteps >= 1, "coalescing window formed no megabatch")
    engine.export_trace(trace_path)
    with open(trace_path) as f:
        doc = json.load(f)
    errs = trace_export.validate_chrome_trace(doc)
    _check(not errs, f"trace-event schema invalid: {errs[:3]}")
    errs = trace_export.validate_links(doc)
    _check(not errs, f"megabatch->submit links broken: {errs[:3]}")
    n_submits = len([e for e in doc["traceEvents"] if e.get("ph") == "X" and e["name"] == "submit"])
    _check(n_submits == len(clean), f"expected {len(clean)} submit spans, saw {n_submits}")
    # telemetry document renders the trace/SLO section both ways
    telemetry_path = os.path.join(os.path.dirname(trace_path) or "out", "obs_telemetry.json")
    engine.export_telemetry(telemetry_path)
    with open(telemetry_path) as f:
        tele = json.load(f)
    _check(
        bool(tele.get("trace", {}).get("slowest_traces")),
        "exported telemetry has no slowest-traces trace section",
    )
    rendered = engine_report.render(tele)
    _check("trace / SLO" in rendered, "engine_report does not render the trace section")

    # ------------------------------------------------- 2. OpenMetrics surface
    text = engine.metrics_text()
    parent = os.path.dirname(os.path.abspath(metrics_path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(metrics_path, "w") as f:
        f.write(text)
    try:
        families = trace_export.parse_openmetrics(text)
    except ValueError as e:
        families = {}
        _check(False, f"OpenMetrics exposition invalid: {e}")
    hist_fams = {k for k, v in families.items() if v["type"] == "histogram"}
    for want in ("step_latency_us", "queue_wait_us", "result_latency_us"):
        _check(
            f"metrics_tpu_engine_{want}" in hist_fams,
            f"histogram family {want} missing from the exposition",
        )
    # conservation check on the dogfooded fold: every step observed exactly
    # once (the per-bucket numpy oracle is tests/engine/test_trace.py —
    # live latencies give a value-level comparison nothing stable to pin)
    step_hist = next(h for h in rec.histograms() if h.name == "step_latency_us")
    counts = step_hist.bucket_counts()
    _check(
        int(counts.sum()) == step_hist.count == engine.stats.steps,
        f"step histogram folded {counts.sum()} of {engine.stats.steps} observations",
    )

    # -------------------------------------- 3. same-seed chaos trace, twice
    def chaos_run():
        rec = TraceRecorder(capacity=1 << 15)
        snapdir = tempfile.mkdtemp(prefix="metrics_tpu_obs_")
        injs = chaos_injectors()
        inj = injs["chaos"]
        eng = StreamingEngine(collection(), chaos_engine_config(snapdir, inj, trace=rec))
        with eng:
            for b in chaos_batches:
                eng.submit(*b)
            got = {k: np.asarray(v) for k, v in eng.result().items()}
        # kill + fallback restore past the corrupted LATEST, transient read
        read_inj = injs["snapshot_read"]
        resumed = StreamingEngine(
            collection(), resume_engine_config(snapdir, read_inj, trace=rec)
        )
        meta = resumed.restore()
        with resumed:
            for b in chaos_batches[int(meta["batches_done"]):]:
                resumed.submit(*b)
            resumed.result()
        # deferred boundary-merge retry on a 1-device mesh
        merge_inj = injs["merge"]
        deferred = StreamingEngine(collection(), deferred_engine_config(merge_inj, trace=rec))
        with deferred:
            for b in clean:
                deferred.submit(*b)
            deferred.result()
        # stream-sharded paging transients (ISSUE 9): route/page_out/page_in
        # spans join the canonical sequence — seeded Zipf traffic + coalesce=1
        # keep every page-site occurrence index producer-timing-independent
        page_inj = injs["paging"]
        paged = MultiStreamEngine(
            collection(), SSHARD_STREAMS,
            stream_shard_engine_config(page_inj, trace=rec),
            stream_shard=True, resident_streams=SSHARD_RESIDENT,
        )
        with paged:
            for sid, p, t in stream_shard_traffic():
                paged.submit(sid, p, t)
            paged.results()
        # quantized at-rest codec transients (ISSUE 10): one compressed
        # snapshot (quant_encode retries) + one restore (quant_decode
        # retries) — fixed call counts, so the occurrence indices and the
        # resulting span sequence are producer-timing-independent
        quant_inj = injs["quant"]
        q_dir = tempfile.mkdtemp(prefix="metrics_tpu_obs_quant_")
        qeng = StreamingEngine(collection(), quant_engine_config(quant_inj, q_dir, trace=rec))
        with qeng:
            for b in clean[:4]:
                qeng.submit(*b)
            qeng.snapshot()
        qres = StreamingEngine(collection(), quant_engine_config(quant_inj, q_dir, trace=rec))
        qres.restore()
        # elastic serving transients (ISSUE 11): admission check, suspected
        # shard loss, and a manual reshard's capture/restore — flush after
        # every submit so each site's occurrence index (and therefore the
        # span sequence) is producer-timing-independent
        elastic_inj = injs["elastic"]
        ee = StreamingEngine(collection(), elastic_engine_config(elastic_inj, trace=rec))
        with ee:
            for b in clean[:3]:
                ee.submit(*b)
                ee.flush()
            ee.reshard(world=1)
            for b in clean[3:]:
                ee.submit(*b)
                ee.flush()
            ee.result()
        # windowed rotation + drift-eval transients (ISSUE 13): sliding ring
        # with a wired detector plus the ewma decay probe — pane_rotate and
        # drift_eval join the canonical span sequence; flush-per-submit keeps
        # their occurrence indices producer-timing-independent
        from metrics_tpu.engine import DriftDetector
        from metrics_tpu import MeanMetric

        win_inj = injs["windows"]
        we = StreamingEngine(
            collection(),
            windowed_engine_config(
                win_inj, trace=rec,
                drift=DriftDetector(threshold=0.05, up_after=1, down_after=1),
            ),
        )
        with we:
            for b in clean:
                we.submit(*b)
                we.flush()
            we.result()
        ewma_inj = injs["ewma"]
        em = StreamingEngine(MeanMetric(), ewma_engine_config(ewma_inj, trace=rec))
        with em:
            for p, _t in clean:
                em.submit(p)
                em.flush()
            em.result()
        # fleet boundary transients (ISSUE 15): a degenerate 1-host fleet's
        # snapshot-cut barrier and first cross-host fold both fail
        # transiently — fleet_barrier/host_loss join the canonical span
        # sequence; every boundary is an explicit scripted call, so the
        # occurrence indices are producer-timing-independent by construction
        fleet_inj = injs["fleet"]
        run_fleet_phase(
            fleet_inj, tempfile.mkdtemp(prefix="metrics_tpu_obs_fleet_"), trace=rec
        )
        sites = (
            set(inj.fired) | set(read_inj.fired) | set(merge_inj.fired)
            | set(page_inj.fired) | set(quant_inj.fired) | set(elastic_inj.fired)
            | set(win_inj.fired) | set(ewma_inj.fired) | set(fleet_inj.fired)
        )
        return rec, got, sites

    t0 = time.perf_counter()
    rec_a, got_a, sites_a = chaos_run()
    rec_b, got_b, sites_b = chaos_run()
    chaos_s = time.perf_counter() - t0
    _check(rec_a.dropped == 0 and rec_b.dropped == 0, "chaos trace ring dropped spans")
    for k in got_a:
        _check(
            np.array_equal(got_a[k], got_b[k]),
            f"same-seed chaos results differ: {k} {got_a[k]} != {got_b[k]}",
        )
    seq_a, seq_b = rec_a.canonical_sequence(), rec_b.canonical_sequence()
    _check(
        set(seq_a) == set(seq_b),
        f"same-seed runs used different tracks: {sorted(seq_a)} vs {sorted(seq_b)}",
    )
    for track in seq_a:
        a, b = seq_a[track], seq_b.get(track, [])
        if a == b:
            continue
        detail = next(
            (f"index {i}: {x} != {y}" for i, (x, y) in enumerate(zip(a, b)) if x != y),
            f"lengths {len(a)} vs {len(b)}",
        )
        _check(False, f"span sequence diverged on track {track!r}: {detail}")
    n_spans = sum(len(v) for v in seq_a.values())
    _check(sites_a == sites_b, f"fired site sets differ: {sites_a} vs {sites_b}")

    # ------------------------------------- 4. dead dispatcher's fault event
    kill_rec = TraceRecorder(capacity=1024)
    kill_inj = chaos_injectors()["dispatcher_kill"]
    dead = StreamingEngine(Accuracy(), kill_engine_config(kill_inj, trace=kill_rec))
    p, t = np.asarray([0.9, 0.2], np.float32), np.asarray([1, 0], np.int32)
    dead.start()
    dead.submit(p, t)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and not kill_rec.fault_sites():
        try:
            dead.flush()
        except EngineDispatchError:
            break
        time.sleep(0.01)
    dead.stop()
    _check(
        kill_rec.fault_sites().get("dispatcher_kill", 0) == 1,
        "dispatcher_kill firing left no fault span event",
    )
    # every injector-side firing must have left a recorder-side span event —
    # the per-run wiring check a recorder-only union alone couldn't localize
    unrecorded = sites_a - set(rec_a.fault_sites())
    _check(not unrecorded, f"injector firings without span events: {sorted(unrecorded)}")
    # coverage is RECORDER-side only: unioning the injectors' fired sets here
    # would let a regressed tr.event wiring pass on injector bookkeeping alone
    covered = set(rec.fault_sites()) | set(rec_a.fault_sites()) | set(kill_rec.fault_sites())
    missing = set(FAULT_SITES) - covered
    _check(not missing, f"fault sites never seen as span events: {sorted(missing)}")

    if _failed:
        return 1
    print(
        "obs-smoke PASS: "
        f"Perfetto export valid ({n_submits} submits all linked from megabatches, "
        f"{engine.stats.megasteps} megasteps); OpenMetrics parses "
        f"({len(families)} families, {len(hist_fams)} histograms, counts exact); "
        f"same-seed chaos span sequences identical ({n_spans} canonical records, "
        f"2 runs in {chaos_s:.1f}s, sites {sorted(sites_a)}); dispatcher_kill "
        f"event present; trace -> {trace_path}, metrics -> {metrics_path}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:3]))
