"""Embedded-model serving: a shared, sharded, bucketed model host (ISSUE 19).

FID's InceptionV3 and BERTScore's encoder are inference workloads living
inside a metric — and until now they ignored everything the engine learned:
every ``update()`` ran a monolithic forward at whatever batch shape arrived
(fresh trace per shape), one model copy per metric instance, single device
unless the caller hand-sharded. This module treats them as the serving
problem they are (per "Fine-Tuning and Serving Gemma on Cloud TPU" and the
MPMD pipeline-parallelism paper, PAPERS.md):

* **One resident model, many streams.** A :class:`ModelHost` owns the params
  (placed ONCE on the mesh with the layout its sharding mode needs) and an
  :class:`~metrics_tpu.engine.aot.AotCache`; metric instances route feature
  requests through it. ``shared_host`` dedupes hosts by a structural key
  (kind, params fingerprint, tap, mesh, sharding, precision, buckets — the
  same identity discipline as ``AotCache.program_key``), so FID and KID built
  over the same weights resolve to ONE resident model, params shared not
  copied.
* **Bucketed, coalesced requests.** Incoming batches concatenate across
  requesting streams (megabatch coalescing, same contract as the engine
  dispatcher) and round up to a closed set of batch buckets
  (:class:`~metrics_tpu.engine.bucketing.BucketPolicy` reused); the compiled
  program set is at most ``len(buckets)`` per input signature — zero
  steady-state compiles, observable on the host's cache counters.
* **Sharded forwards.** ``mesh=`` selects the model layout: the
  tensor-parallel stem + data-parallel trunk hybrid for Inception
  (``parallel.embedded.stem_tensor_batch_forward`` — the padded 128-lane stem
  of PR 1 splits evenly over the axis), GPipe ``ppermute`` pipeline stages
  for encoders (``parallel.embedded.pipeline_stage_forward``). Each mode
  declares its collective allowance (``allowed_collectives``) and the
  ``host-collectives-pinned`` analysis rule audits the traced programs
  against it — metric steady steps stay collective-free; only host stage
  programs may carry their declared handoffs.
* **Activation precision paths.** ``precision="f32"`` (default) is the
  bit-exactness oracle — host features are bit-identical to the direct
  forward. ``"bf16"`` runs the model's compute-dtype path; ``"int8"``
  transports activations through the q8_block codec (encode→decode inside
  the compiled program), so the error is EXACTLY the single-shard
  ``q8_roundtrip`` and the analytic ``q8_sum_error_bound`` (W=1) bounds it.

See ``docs/serving.md`` ("Embedded-model serving") for the lifecycle and the
bucketing/precision contract; ``make model-smoke`` gates the whole path on an
8-device virtual mesh.
"""
import hashlib
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from metrics_tpu.engine.aot import AotCache, _fingerprint_value, _mesh_fingerprint
from metrics_tpu.engine.bucketing import BucketPolicy

__all__ = [
    "ModelHost",
    "ModelHostConfig",
    "encoder_host",
    "inception_host",
    "reset_host_registry",
    "shared_host",
]

#: activation precision policies. "f32" is the default and the bit-exactness
#: oracle — nothing degrades unless the config says so, mirroring the
#: SYNC_PRECISIONS contract of parallel/collectives.py.
HOST_PRECISIONS = ("f32", "bf16", "int8")


@dataclass(frozen=True)
class ModelHostConfig:
    """Serving configuration of one resident embedded model.

    Args:
        buckets: allowed padded batch sizes (ascending; oversized requests
            split into max-bucket chunks + a bucketed remainder, exactly like
            the engine's ingest path).
        precision: activation path — ``"f32"`` (bit-exact oracle), ``"bf16"``
            (compute-dtype forward), ``"int8"`` (features ride the q8_block
            codec inside the compiled program; error bounded by
            ``q8_sum_error_bound`` at W=1).
        coalesce: max requests concatenated into one megabatch.
        coalesce_window_ms: how long the worker waits for more compatible
            requests once one is in hand (0 = serve immediately).
        queue_depth: bound on queued requests (blocking submit = backpressure).
        mesh / mesh_axis: run the forward model-sharded over this mesh axis
            (the builder picks the layout: hybrid stem-tensor for Inception,
            ppermute pipeline for encoders). None = single-device.
        cache_dir: optional JAX persistent compilation cache directory.
    """

    buckets: Tuple[int, ...] = (8, 32)
    precision: str = "f32"
    coalesce: int = 8
    coalesce_window_ms: float = 2.0
    queue_depth: int = 64
    mesh: Optional[Any] = None
    mesh_axis: str = "dp"
    cache_dir: Optional[str] = None

    def __post_init__(self):
        if self.precision not in HOST_PRECISIONS:
            raise ValueError(
                f"precision must be one of {HOST_PRECISIONS}, got {self.precision!r}"
            )


def q8_roundtrip_traced(x: Any) -> Any:
    """In-trace q8_block encode→decode of a float array — the activation
    transport of the ``"int8"`` precision path. By construction identical to
    the W=1 quantized sum, so ``q8_sum_error_bound(x[None])`` bounds the
    per-element error analytically (the same oracle the quantized collectives
    check against)."""
    import jax.numpy as jnp

    from metrics_tpu.parallel.collectives import Q8_BLOCK, _q8_encode

    orig_dtype = x.dtype
    codes, scales = _q8_encode(x)
    vals = codes.astype(jnp.float32).reshape(-1, Q8_BLOCK) * scales[:, None]
    return vals.reshape(-1)[: x.size].reshape(x.shape).astype(orig_dtype)


class _Stop:
    pass


_STOP = _Stop()


class _Request:
    __slots__ = ("args", "n", "sig", "future", "enqueued")

    def __init__(self, args: Tuple[np.ndarray, ...], sig: Tuple):
        self.args = args
        self.n = int(args[0].shape[0])
        self.sig = sig
        self.future: "queue.Queue" = queue.Queue(maxsize=1)
        self.enqueued = time.perf_counter()


class HostStats:
    """Thread-safe counters + throughput gauge of one host (the
    ``model_host_*`` OpenMetrics families)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.requests = 0
        self.items = 0
        self.padded_items = 0
        self.coalesced_batches = 0
        self.batches = 0
        self.bucket_hits: Dict[int, int] = {}
        self.busy_seconds = 0.0

    def record(self, requests: int, items: int, padded: int, buckets: Sequence[int],
               busy: float) -> None:
        with self._lock:
            self.requests += requests
            self.items += items
            self.padded_items += padded
            self.batches += 1
            if requests > 1:
                self.coalesced_batches += 1
            for b in buckets:
                self.bucket_hits[b] = self.bucket_hits.get(b, 0) + 1
            self.busy_seconds += busy

    def items_per_s(self) -> float:
        with self._lock:
            return self.items / self.busy_seconds if self.busy_seconds > 0 else 0.0


class ModelHost:
    """One resident embedded model served through the engine's machinery.

    ``forward(params, *batch) -> features`` is a pure traceable callable
    whose positional batch args all carry a leading batch dimension and whose
    outputs are per-row (leading batch dim) — pad rows are sliced off before
    results reach a caller, so no mask plumbing is needed. ``forward`` may be
    a dict ``{precision: callable}``; missing ``"bf16"``/``"int8"`` entries
    fall back to generic wrappers over the ``"f32"`` one (cast-in/cast-out,
    q8 transport).

    ``infer(*batch)`` is the synchronous request path (submit + wait);
    ``submit(*batch)`` returns a waitable handle so many metric streams can
    overlap requests — the worker thread coalesces compatible queued requests
    into megabatches, chunks them through the bucket policy, and serves each
    chunk with a per-(bucket signature, precision, mesh) AOT-compiled
    executable. Steady state compiles NOTHING (the ``aot.misses`` counter is
    the observable, same contract as the engine).
    """

    def __init__(
        self,
        kind: str,
        forward: Any,
        params: Any,
        *,
        config: Optional[ModelHostConfig] = None,
        fingerprint: Optional[str] = None,
        unit: str = "items",
        allowed_collectives: Tuple[str, ...] = (),
        param_shardings: Optional[Any] = None,
        aot: Optional[AotCache] = None,
    ) -> None:
        import jax

        self.kind = str(kind)
        self.config = config or ModelHostConfig()
        self.unit = str(unit)
        self.allowed_collectives = tuple(allowed_collectives)
        self.stats = HostStats()
        # `is not None`: a shared-but-still-empty AotCache is falsy (len 0)
        self.aot = aot if aot is not None else AotCache(cache_dir=self.config.cache_dir)
        self.shared_by = 1  # bumped by shared_host on every dedup hit

        fwd_map = dict(forward) if isinstance(forward, dict) else {"f32": forward}
        base = fwd_map["f32"]
        precision = self.config.precision
        if precision == "bf16" and "bf16" not in fwd_map:
            fwd_map["bf16"] = _bf16_wrap(base)
        if precision == "int8" and "int8" not in fwd_map:
            fwd_map["int8"] = _q8_wrap(base)
        self._fwd = fwd_map[precision]

        if fingerprint is None:
            h = hashlib.sha256()
            _fingerprint_value(jax.tree.leaves(params), h)
            fingerprint = h.hexdigest()[:16]
        self.fingerprint = str(fingerprint)

        mesh = self.config.mesh
        divisor = 1
        if mesh is not None:
            divisor = int(np.prod([mesh.shape[a] for a in (
                self.config.mesh_axis if isinstance(self.config.mesh_axis, (tuple, list))
                else (self.config.mesh_axis,))]))
        self._policy = BucketPolicy(self.config.buckets, divisor=divisor)

        # the params are RESIDENT: placed once, with the sharding mode's
        # layout, and every compiled program reads them as a non-donated arg
        # (rebinding host.params takes effect on the next request)
        if param_shardings is not None:
            params = jax.tree.map(
                lambda x, s: jax.device_put(np.asarray(x), s), params, param_shardings
            )
        self.params = params
        self._param_shardings = param_shardings
        self._programs_abstract: Dict[Tuple, Tuple] = {}

        self._queue: "queue.Queue" = queue.Queue(maxsize=self.config.queue_depth)
        self._carry: Optional[_Request] = None
        self._closed = False
        self._worker_error: Optional[BaseException] = None
        self._worker = threading.Thread(
            target=self._run, name=f"model-host-{kind}", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- request path

    def submit(self, *batch: Any) -> "queue.Queue":
        """Enqueue one feature request; returns a handle whose ``.get()``
        yields the per-row output pytree (numpy) or raises the serving error."""
        if self._closed:
            raise RuntimeError(f"ModelHost({self.kind}) is closed")
        args = tuple(np.asarray(a) for a in batch)
        if not args or any(a.ndim == 0 for a in args):
            raise ValueError("ModelHost.submit needs batch-carried array arguments")
        n = args[0].shape[0]
        if any(a.shape[0] != n for a in args):
            raise ValueError(
                f"ModelHost.submit: inconsistent leading dims {[a.shape for a in args]}"
            )
        sig = tuple((a.shape[1:], str(a.dtype)) for a in args)
        req = _Request(args, sig)
        self._queue.put(req)
        return req.future

    def infer(self, *batch: Any) -> Any:
        """Synchronous feature request: submit, wait, return (or raise)."""
        out = self.submit(*batch).get()
        if isinstance(out, BaseException):
            raise out
        return out

    # ------------------------------------------------------------------ worker

    def _run(self) -> None:
        while True:
            req = self._carry or self._queue.get()
            self._carry = None
            if isinstance(req, _Stop):
                return
            group = [req]
            rows = req.n
            deadline = time.monotonic() + self.config.coalesce_window_ms / 1000.0
            while (
                len(group) < self.config.coalesce
                and rows < self._policy.buckets[-1]
            ):
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=timeout)
                except queue.Empty:
                    break
                if isinstance(nxt, _Stop):
                    self._carry = nxt  # serve this group, then stop
                    break
                if nxt.sig != req.sig:
                    self._carry = nxt  # incompatible: its own group next round
                    break
                group.append(nxt)
                rows += nxt.n
            try:
                self._serve(group)
            except BaseException as e:  # noqa: BLE001 — delivered to waiters
                self._worker_error = e
                for r in group:
                    r.future.put(e)

    def _serve(self, group: List[_Request]) -> None:
        import jax

        n_args = len(group[0].args)
        if len(group) == 1:
            mega = group[0].args
        else:
            mega = tuple(
                np.concatenate([r.args[i] for r in group], axis=0)
                for i in range(n_args)
            )
        total = int(mega[0].shape[0])
        t0 = time.perf_counter()
        chunk_outs: List[Any] = []
        buckets_used: List[int] = []
        padded = 0
        for start, stop, bucket in self._policy.chunks(total):
            a, _kw, _mask = self._policy.pad_chunk(mega, {}, start, stop, bucket)
            padded += bucket - (stop - start)
            buckets_used.append(bucket)
            program = self._program(a)
            a = self._place(a)
            out = program(self.params, *a)
            # blocking conversion: serializes collective-bearing executions on
            # CPU virtual meshes (same rationale as shard_batch_forward) and
            # closes the async dispatch before results are distributed
            out = jax.tree.map(lambda o: np.asarray(o)[: stop - start], out)
            chunk_outs.append(out)
        merged = (
            chunk_outs[0]
            if len(chunk_outs) == 1
            else jax.tree.map(lambda *xs: np.concatenate(xs, axis=0), *chunk_outs)
        )
        self.stats.record(
            len(group), total, padded, buckets_used, time.perf_counter() - t0
        )
        off = 0
        for r in group:
            r.future.put(jax.tree.map(lambda o: o[off:off + r.n], merged))
            off += r.n

    # ---------------------------------------------------------------- programs

    def _program(self, padded_args: Tuple[np.ndarray, ...]):
        import jax

        key = self.aot.program_key(
            f"model_host_{self.kind}",
            self.fingerprint,
            arg_tree=padded_args,
            mesh=self.config.mesh,
            sync="host",
            precision=self.config.precision,
        )

        def build():
            params_abs = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    np.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
                ),
                self.params,
            )
            args_abs = tuple(
                jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=self._replicated())
                for a in padded_args
            )
            self._programs_abstract[key] = (params_abs, args_abs)
            return jax.jit(self._fwd).lower(params_abs, *args_abs).compile()

        return self.aot.get_or_compile(key, build)

    def _replicated(self):
        if self.config.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.config.mesh, P())

    def _place(self, args: Tuple[np.ndarray, ...]) -> Tuple:
        if self.config.mesh is None:
            return args
        import jax

        rep = self._replicated()
        return tuple(jax.device_put(a, rep) for a in args)

    def host_programs(self) -> Dict[Tuple, Tuple[Callable, Tuple]]:
        """``{program_key: (traceable_fn, (params_abs, args_abs))}`` for every
        compiled program — the analysis plane re-traces these to audit the
        collective allowance (``host-collectives-pinned``)."""
        return {
            key: (self._fwd, abstract)
            for key, abstract in self._programs_abstract.items()
        }

    # --------------------------------------------------------------- telemetry

    def counters(self) -> Dict[str, int]:
        s = self.stats
        return {
            "requests": s.requests,
            "items": s.items,
            "padded_items": s.padded_items,
            "batches": s.batches,
            "coalesced_batches": s.coalesced_batches,
            "bucket_hits": self.aot.hits,
            "bucket_compiles": self.aot.misses,
            "shared_by": self.shared_by,
        }

    def telemetry(self) -> Dict[str, Any]:
        """One JSON-able snapshot (the ``model_host`` section of an engine
        telemetry doc — ``tools/engine_report.py`` renders it as a row)."""
        return {
            "kind": self.kind,
            "unit": self.unit,
            "precision": self.config.precision,
            "buckets": list(self._policy.buckets),
            "sharding": "none" if self.config.mesh is None else "mesh",
            "allowed_collectives": list(self.allowed_collectives),
            "counters": self.counters(),
            "bucket_hit_histogram": {str(k): v for k, v in sorted(self.stats.bucket_hits.items())},
            "items_per_s": self.stats.items_per_s(),
            "busy_seconds": self.stats.busy_seconds,
            "aot": self.aot.stats(),
        }

    def metrics_text(self) -> str:
        """OpenMetrics exposition of the ``model_host_*`` families."""
        from metrics_tpu.engine.trace import render_openmetrics

        counters = self.counters()
        requests = counters.pop("requests")
        return render_openmetrics(
            counters,
            labeled_counters={
                # the activation-precision label rides the requests family
                "requests": (
                    "precision", {self.config.precision: requests}
                ),
            },
            gauges={f"{self.unit}_per_s": self.stats.items_per_s()},
            prefix="metrics_tpu_model_host_",
        )

    # --------------------------------------------------------------- lifecycle

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(_STOP)
        self._worker.join(timeout=30)

    def __enter__(self) -> "ModelHost":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _bf16_wrap(base: Callable) -> Callable:
    """Generic bf16 activation path: float inputs cast to bf16 on the way in,
    float outputs restored to their original dtype on the way out (model
    builders that have a native compute-dtype knob pass their own ``"bf16"``
    forward instead — e.g. the Inception host)."""
    import jax
    import jax.numpy as jnp

    def fwd(params, *batch):
        cast = tuple(
            b.astype(jnp.bfloat16) if jnp.issubdtype(b.dtype, jnp.floating) else b
            for b in batch
        )
        out = base(params, *cast)
        return jax.tree.map(
            lambda o: o.astype(jnp.float32)
            if jnp.issubdtype(o.dtype, jnp.floating) else o,
            out,
        )

    return fwd


def _q8_wrap(base: Callable) -> Callable:
    """Generic int8 activation-transport path: the f32 forward runs exactly,
    then every float output rides the q8_block codec (encode→decode) inside
    the compiled program — the error is the single-shard roundtrip, bounded
    by ``q8_sum_error_bound`` at W=1."""
    import jax
    import jax.numpy as jnp

    def fwd(params, *batch):
        out = base(params, *batch)
        return jax.tree.map(
            lambda o: q8_roundtrip_traced(o)
            if jnp.issubdtype(o.dtype, jnp.floating) else o,
            out,
        )

    return fwd


# ------------------------------------------------------------- shared registry

_REGISTRY: Dict[Tuple, ModelHost] = {}
_REGISTRY_LOCK = threading.Lock()


def shared_host(key: Tuple, factory: Callable[[], ModelHost]) -> ModelHost:
    """Resolve ``key`` to ONE resident host: the first caller builds it, every
    later caller with the same structural key gets the SAME instance (params
    shared, not copied) with ``shared_by`` bumped. Closed hosts are evicted
    and rebuilt."""
    with _REGISTRY_LOCK:
        host = _REGISTRY.get(key)
        if host is not None and not host._closed:
            host.shared_by += 1
            return host
        host = factory()
        _REGISTRY[key] = host
        return host


def reset_host_registry() -> None:
    """Close and drop every registered host (test isolation)."""
    with _REGISTRY_LOCK:
        hosts = list(_REGISTRY.values())
        _REGISTRY.clear()
    for h in hosts:
        h.close()


# ------------------------------------------------------------- model builders


def inception_host(
    feature: str = "2048",
    params: Optional[Any] = None,
    *,
    config: Optional[ModelHostConfig] = None,
    input_size: int = 299,
    seed: int = 0,
    stem_lanes: Optional[int] = None,
    shared: bool = True,
) -> ModelHost:
    """Build (or resolve from the registry) the resident InceptionV3 host.

    Single-device: the canonical module forward, jitted per bucket —
    ``precision="f32"`` features are bit-identical to
    ``InceptionFeatureExtractor``'s. With ``config.mesh``: the hybrid layout —
    tensor-parallel stem over PR 1's padded 128-lane params (each leaf
    channel-sharded), data-parallel trunk — whose only collective is
    ``all_gather``. ``precision="bf16"`` uses the module's native
    compute-dtype path; ``"int8"`` transports the tap features through the
    q8_block codec.

    ``shared=True`` routes through :func:`shared_host`: FID and KID built
    over the same (tap, weights, mesh, precision, buckets) get ONE model.
    """
    import jax

    from metrics_tpu.models.inception import FEATURE_DIMS, random_inception_params

    feature = str(feature)
    if feature not in FEATURE_DIMS:
        raise ValueError(
            f"feature must be one of {tuple(FEATURE_DIMS)}, got {feature!r}"
        )
    config = config or ModelHostConfig()
    if params is None:
        from metrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(
            "No pretrained InceptionV3 params provided (no network egress in this"
            " build); the model host is using random initialisation. Pass `params=`"
            " (converted torch-fidelity weights) for meaningful FID/KID values.",
            UserWarning,
        )
        params = random_inception_params(input_size=input_size, seed=seed)
    if config.mesh is not None and stem_lanes is None:
        stem_lanes = 128  # PR 1's MXU layout doubles as the tensor-shard grain

    h = hashlib.sha256()
    _fingerprint_value(jax.tree.leaves(params), h)
    fp = h.hexdigest()[:16]
    key = (
        "inception", feature, fp, _mesh_fingerprint(config.mesh),
        "stem_tensor" if config.mesh is not None else "single",
        config.precision, tuple(config.buckets), stem_lanes,
    )

    def factory() -> ModelHost:
        return _build_inception_host(feature, params, config, stem_lanes, fp)

    return shared_host(key, factory) if shared else factory()


def _build_inception_host(
    feature: str, params: Any, config: ModelHostConfig,
    stem_lanes: Optional[int], fp: str,
) -> ModelHost:
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from metrics_tpu.models.inception import (
        InceptionV3, pad_stem_params, split_stem_variables, stem_apply,
    )

    def _nchw(fwd):
        def wrapped(p, imgs):
            if imgs.ndim == 4 and imgs.shape[1] == 3 and imgs.shape[-1] != 3:
                imgs = jnp.transpose(imgs, (0, 2, 3, 1))
            return fwd(p, imgs)

        return wrapped

    if config.mesh is None:
        def module_fwd(dtype):
            m = InceptionV3(compute_dtype=dtype, stem_lanes=stem_lanes)

            def fwd(p, imgs):
                if stem_lanes is not None:
                    p = pad_stem_params(p, stem_lanes)
                return m.apply(p, imgs)[feature].astype(jnp.float32)

            return _nchw(fwd)

        return ModelHost(
            "inception", {"f32": module_fwd(None), "bf16": module_fwd(jnp.bfloat16)},
            params, config=config, fingerprint=fp, unit="imgs",
            allowed_collectives=(),
        )

    # hybrid stem-tensor + trunk-batch layout: params split host-side ONCE
    # (pad applied eagerly so the resident leaves are the sharded ones)
    from metrics_tpu.parallel.embedded import stem_tensor_batch_forward

    mesh, axis = config.mesh, config.mesh_axis
    stem_v, trunk_v = split_stem_variables(
        jax.tree.map(np.asarray, pad_stem_params(params, stem_lanes))
    )
    host_params = {"stem": stem_v, "trunk": trunk_v}

    def _stem_shard(leaf):
        nd = np.ndim(leaf)
        return NamedSharding(mesh, P(*([None] * (nd - 1) + [axis])) if nd else P())

    shardings = {
        "stem": jax.tree.map(_stem_shard, stem_v),
        "trunk": jax.tree.map(lambda _: NamedSharding(mesh, P()), trunk_v),
    }

    def hybrid_fwd(dtype):
        trunk = InceptionV3(compute_dtype=dtype, stem_input=True)

        def stem_fn(sv, x, gather_axis):
            return stem_apply(
                sv, x, compute_dtype=dtype, stem_lanes=stem_lanes,
                gather_axis=gather_axis,
            )

        def trunk_fn(tv, xl):
            return dict(trunk.apply(tv, xl))

        sharded = stem_tensor_batch_forward(stem_fn, trunk_fn, mesh, axis)

        def fwd(p, imgs):
            return sharded(p["stem"], p["trunk"], imgs)[feature].astype(jnp.float32)

        return _nchw(fwd)

    return ModelHost(
        "inception", {"f32": hybrid_fwd(None), "bf16": hybrid_fwd(jnp.bfloat16)},
        host_params, config=config, fingerprint=fp, unit="imgs",
        allowed_collectives=("all_gather",), param_shardings=shardings,
    )


def encoder_host(
    forward_fn: Optional[Callable] = None,
    *,
    stage_fn: Optional[Callable] = None,
    stage_params: Optional[Any] = None,
    embed_fn: Optional[Callable] = None,
    config: Optional[ModelHostConfig] = None,
    fingerprint: Optional[str] = None,
    shared: bool = True,
) -> ModelHost:
    """Build (or resolve) the resident text-encoder host for BERTScore.

    Two layouts:

    * ``forward_fn(input_ids, attention_mask) -> (B, L, D)`` — any encoder
      callable (the current BERTScore forward contract), served single-device
      through the host's bucketing/coalescing/AOT machinery.
    * ``stage_fn`` + ``stage_params`` (+ optional ``embed_fn(ids, mask)``) —
      a pipeline-decomposed encoder: stage params stacked ``(S, ...)`` and
      dim-0-sharded over ``config.mesh``'s axis, activations handed off with
      ``ppermute`` (``parallel.embedded.pipeline_stage_forward``, the MPMD
      layout). The ONLY collective the host program may carry is
      ``ppermute`` — pinned by the ``host-collectives-pinned`` rule.
    """
    import jax

    config = config or ModelHostConfig()
    if (forward_fn is None) == (stage_fn is None):
        raise ValueError("encoder_host needs exactly one of forward_fn / stage_fn")

    if stage_fn is not None:
        if config.mesh is None:
            raise ValueError("pipeline-staged encoder_host needs config.mesh")
        if stage_params is None:
            raise ValueError("stage_fn needs stage_params (stacked (S, ...) pytree)")
        from jax.sharding import NamedSharding, PartitionSpec as P

        from metrics_tpu.parallel.embedded import pipeline_stage_forward

        mesh, axis = config.mesh, config.mesh_axis
        pipe = pipeline_stage_forward(stage_fn, mesh, axis)

        def fwd(p, ids, mask):
            x = embed_fn(ids, mask) if embed_fn is not None else ids
            return pipe(p, x)

        if fingerprint is None:
            h = hashlib.sha256()
            _fingerprint_value(jax.tree.leaves(stage_params), h)
            if embed_fn is not None:
                h.update(getattr(embed_fn, "__qualname__", repr(embed_fn)).encode())
            fingerprint = h.hexdigest()[:16]
        key = (
            "encoder", fingerprint, _mesh_fingerprint(mesh), "pipeline",
            config.precision, tuple(config.buckets),
        )
        shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, P(axis)), stage_params
        )

        def factory() -> ModelHost:
            return ModelHost(
                "encoder", fwd, stage_params, config=config,
                fingerprint=fingerprint, unit="pairs",
                allowed_collectives=("ppermute",), param_shardings=shardings,
            )

        return shared_host(key, factory) if shared else factory()

    if fingerprint is None:
        fingerprint = getattr(
            forward_fn, "__qualname__", type(forward_fn).__name__
        ) + f"@{id(forward_fn):x}"

    def fwd(_params, ids, mask):
        return forward_fn(ids, mask)

    key = (
        "encoder", fingerprint, _mesh_fingerprint(config.mesh), "single",
        config.precision, tuple(config.buckets),
    )

    def factory() -> ModelHost:
        return ModelHost(
            "encoder", fwd, (), config=config, fingerprint=fingerprint,
            unit="pairs", allowed_collectives=(),
        )

    return shared_host(key, factory) if shared else factory()
