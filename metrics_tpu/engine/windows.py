"""Windowed & time-decayed metric semantics: the pane-ring window layer.

Every metric the engine serves is cumulative-since-reset; the production
observability workload (ROADMAP item 3) wants "AUROC over the last hour":
tumbling and sliding windows, exponential decay, and drift alarms. This
module supplies the POLICY — :class:`WindowPolicy` — and the eligibility
contracts; the mechanics live in ``engine/pipeline.py`` (the ring-of-arenas
and the rotation machinery) and ``engine/tracker.py`` (the drift detector).

The substrate is the repo's own leading-axis-stacking pattern (PR 5's
``ArenaLayout.abstract_stacked``, PR 9's stream-stacked arenas): a window is
just one more leading axis. Concretely:

* **Ring-of-arenas.** A windowed engine's carried state gains a leading PANE
  axis: per-dtype arena buffers become ``(panes, n)`` (``(world, panes, n)``
  under deferred mesh sync). The step updates one runtime-indexed pane row —
  the pane index is a RUNTIME argument in the step signature (a 0-d int32
  payload leaf), and the window shape is in every AOT program key, so a
  rotation is a slot-index bump plus one compiled init-fill, NEVER a retrace
  (zero steady compiles across rotations, pinned by ``make windows-smoke``).
* **Exact pane folds.** ``result()`` folds the live pane set through
  ``Metric.merge_stacked_states`` — the same ``dist_reduce_fx`` fold the
  deferred mesh boundary merge uses, so sliding-window results are exactly
  the fold of the per-pane accumulations (sum/min/max elementwise, ``cat``
  capacity buffers concatenated across panes — scan/cat-strategy metrics
  window via per-pane capacity buffers for free).
* **EWMA.** ``ewma(alpha)`` keeps ONE accumulator and applies the decay
  ``1 - alpha`` at each rotation as one fused scale-accumulate over the
  per-dtype buffers. Eligibility is checked loudly at construction: every
  state must be sum-reducible AND floating (decaying an int counter or
  folding a min/max by a scalar multiply would be silently wrong math).
* **Window x stream.** On the unsharded :class:`MultiStreamEngine` the pane
  axis stacks OUTSIDE the stream axis (``(panes, S, ...)`` logical state);
  under ``stream_shard=True`` the pane instead extends the pager's local
  stream coordinate (``loc * panes + pane``), so COLD PANES spill to host
  RAM through the existing compressed pager and rotation is pure
  bookkeeping — no device work at all.

Rotation cadence is ``pane_batches`` (replay-cursor batches — exact under
kill/resume) or ``pane_seconds`` via the INJECTABLE ``clock`` (tests and the
smoke drive it deterministically). Coalesce groups never cross a
batch-cadence pane boundary, same contract as the snapshot cadence.

See docs/serving.md "Windowed metrics" for the policy table and the
restore-matrix rows (snapshots carry pane-ring provenance; cross-policy
restores refuse loudly).
"""
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

__all__ = ["WINDOW_KINDS", "WindowPolicy"]

WINDOW_KINDS = ("cumulative", "tumbling", "sliding", "ewma")


@dataclass
class WindowPolicy:
    """Declarative window semantics for a streaming engine.

    Args:
        kind: one of :data:`WINDOW_KINDS`.

            * ``"cumulative"`` — the identity policy (since-reset, the
              engine's historical behavior; no pane axis, no rotation).
            * ``"tumbling"`` — the ring holds ``n_panes`` panes; ``result()``
              reads the CURRENT pane only (bit-identical to a fresh engine
              fed that pane's batches); rotation advances the cursor and
              init-fills the incoming pane.
            * ``"sliding"`` — ``result()`` folds the LIVE pane set — the
              open pane plus the ``n_panes - 1`` most recent closed panes
              (the incoming slot clears at each boundary, evicting the
              oldest pane) — via ``merge_stacked_states``: "over the last
              ``n_panes`` x cadence", counting the partially-filled open
              pane.
            * ``"ewma"`` — one accumulator; each rotation scales every state
              by ``1 - alpha`` (sum-reducible float states only, refused
              loudly otherwise). A ratio metric's numerator and denominator
              decay together, so the computed value is the exponentially
              weighted average of the per-pane values.
        pane_batches: rotation cadence in submitted batches (the replay
            cursor — exact under kill/resume and coalescing). Exactly one of
            ``pane_batches``/``pane_seconds`` must be set for rotating kinds.
        pane_seconds: rotation cadence in seconds of the injectable ``clock``.
        n_panes: live panes in the ring (tumbling >= 1, sliding >= 2).
        alpha: EWMA new-data weight in (0, 1); the per-rotation decay applied
            to the carried state is ``1 - alpha``.
        clock: injectable time source for ``pane_seconds`` (default
            ``time.monotonic``); deterministic tests and the windows smoke
            drive rotations through it.
    """

    kind: str = "cumulative"
    pane_batches: int = 0
    pane_seconds: float = 0.0
    n_panes: int = 1
    alpha: float = 0.0
    clock: Optional[Callable[[], float]] = field(default=None, repr=False, compare=False)

    def __post_init__(self):
        if self.kind not in WINDOW_KINDS:
            raise ValueError(
                f"window kind must be one of {WINDOW_KINDS}, got {self.kind!r}"
            )
        self.pane_batches = int(self.pane_batches)
        self.pane_seconds = float(self.pane_seconds)
        self.n_panes = int(self.n_panes)
        self.alpha = float(self.alpha)
        if self.kind == "cumulative":
            if self.pane_batches or self.pane_seconds or self.alpha or self.n_panes != 1:
                raise ValueError(
                    "cumulative windows take no cadence/pane/alpha parameters "
                    "(they ARE the engine's default since-reset semantics)"
                )
            return
        has_batches, has_seconds = self.pane_batches > 0, self.pane_seconds > 0
        if has_batches == has_seconds:
            raise ValueError(
                f"{self.kind} windows need exactly one rotation cadence: "
                f"pane_batches > 0 XOR pane_seconds > 0 "
                f"(got pane_batches={self.pane_batches}, pane_seconds={self.pane_seconds})"
            )
        if self.pane_batches < 0 or self.pane_seconds < 0:
            raise ValueError("rotation cadence must be positive")
        if self.kind == "ewma":
            if not (0.0 < self.alpha < 1.0):
                raise ValueError(f"ewma needs 0 < alpha < 1, got {self.alpha}")
            if self.n_panes != 1:
                raise ValueError("ewma carries one accumulator; n_panes must be 1")
            return
        if self.alpha:
            raise ValueError(f"{self.kind} windows take no alpha")
        if self.kind == "sliding" and self.n_panes < 2:
            raise ValueError(
                f"sliding windows need n_panes >= 2 (a 1-pane slide is tumbling), "
                f"got {self.n_panes}"
            )
        if self.kind == "tumbling" and self.n_panes < 1:
            raise ValueError(f"tumbling windows need n_panes >= 1, got {self.n_panes}")

    # ------------------------------------------------------------- constructors

    @classmethod
    def cumulative(cls) -> "WindowPolicy":
        return cls(kind="cumulative")

    @classmethod
    def tumbling(
        cls,
        pane_batches: int = 0,
        pane_seconds: float = 0.0,
        n_panes: int = 1,
        clock: Optional[Callable[[], float]] = None,
    ) -> "WindowPolicy":
        return cls(
            kind="tumbling", pane_batches=pane_batches, pane_seconds=pane_seconds,
            n_panes=n_panes, clock=clock,
        )

    @classmethod
    def sliding(
        cls,
        n_panes: int,
        pane_batches: int = 0,
        pane_seconds: float = 0.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> "WindowPolicy":
        return cls(
            kind="sliding", pane_batches=pane_batches, pane_seconds=pane_seconds,
            n_panes=n_panes, clock=clock,
        )

    @classmethod
    def ewma(
        cls,
        alpha: float,
        pane_batches: int = 0,
        pane_seconds: float = 0.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> "WindowPolicy":
        return cls(
            kind="ewma", alpha=alpha, pane_batches=pane_batches,
            pane_seconds=pane_seconds, clock=clock,
        )

    # ------------------------------------------------------------------ queries

    @property
    def stacked(self) -> bool:
        """Whether this policy carries a pane AXIS on the state (tumbling and
        sliding rings); ewma decays one accumulator in place and cumulative
        is the identity."""
        return self.kind in ("tumbling", "sliding")

    @property
    def panes(self) -> int:
        """Leading pane-axis length of the carried state (1 when unstacked)."""
        return self.n_panes if self.stacked else 1

    @property
    def decay(self) -> float:
        """The per-rotation scale EWMA applies to every state (``1 - alpha``)."""
        return 1.0 - self.alpha

    def time_source(self) -> Callable[[], float]:
        return self.clock if self.clock is not None else time.monotonic

    def fingerprint(self) -> str:
        """Canonical policy tag: folded into every AOT program key (two
        policies over identical state signatures lower different fold/rotate
        programs) and into snapshot meta (the cross-policy restore refusal —
        a pane ring is only replayable under the policy that built it). The
        clock is deliberately EXCLUDED: it is an injection seam, not
        semantics."""
        if self.kind == "cumulative":
            return "cumulative"
        cadence = (
            f"b{self.pane_batches}" if self.pane_batches > 0
            else f"s{self.pane_seconds:g}"
        )
        if self.kind == "ewma":
            return f"ewma:a{self.alpha:g}:{cadence}"
        return f"{self.kind}:p{self.n_panes}:{cadence}"

    # -------------------------------------------------------------- eligibility

    def unsupported_reason(self, metric: Any, mesh_deferred: bool = False) -> Optional[str]:
        """None when ``metric`` can serve under this policy, else a loud
        human-readable reason (the engine refuses at CONSTRUCTION — a wrong
        window fold must never be discovered in production results).

        * ewma: every state leaf must reduce with ``sum`` AND be floating —
          the decay is a scalar multiply, exact only for linear (sum) folds,
          and an int counter cannot carry a fraction of itself.
        * sliding: the pane fold is ``merge_stacked_states``, so every state
          needs a canonical stacked merge (sum/min/max/cat fixed arrays).
        * stacked windows under DEFERRED mesh sync: ``cat`` states are
          refused — the world boundary merge flattens the shard axis into
          dim 0 of every cat buffer, which under a pane ring is the PANE
          axis, and the interleaving would scramble pane provenance.
        """
        if self.kind == "cumulative":
            return None
        if self.kind == "ewma":
            info_fn = getattr(metric, "sync_leaf_info", None)
            if info_fn is None:
                return "metric does not expose sync_leaf_info (no per-state reductions to check)"
            import jax.numpy as jnp

            for fx, leaf, _prec in info_fn():
                if fx != "sum":
                    return (
                        f"ewma decays are exact only for sum-reducible states; found a "
                        f"state with dist_reduce_fx={fx!r} (min/max/cat states have no "
                        "linear decay)"
                    )
                if not jnp.issubdtype(jnp.dtype(leaf.dtype), jnp.floating):
                    return (
                        f"ewma decay needs floating states; found a {jnp.dtype(leaf.dtype).name} "
                        "sum state (an integer counter cannot carry a fractional decay — "
                        "serve it tumbling/sliding, or use a float-state metric like MeanMetric)"
                    )
            return None
        # stacked (tumbling / sliding) rings
        if self.kind == "sliding":
            r = (
                metric.stacked_merge_unsupported_reason()
                if hasattr(metric, "stacked_merge_unsupported_reason")
                else "metric has no stacked merge (merge_stacked_states)"
            )
            if r is not None:
                return f"sliding folds live panes via merge_stacked_states: {r}"
        if mesh_deferred:
            info_fn = getattr(metric, "sync_leaf_info", None)
            if info_fn is not None and any(fx == "cat" for fx, _l, _p in info_fn()):
                return (
                    "windowed serving under deferred mesh sync refuses cat/scan-strategy "
                    "states: the world boundary merge flattens the shard axis into each "
                    "cat buffer's dim 0, which a pane ring uses for pane provenance — "
                    "serve cat-state metrics windowed on a single device"
                )
        return None

    def fleet_unsupported_reason(self, metric: Any) -> Optional[str]:
        """None when ``metric`` can serve under this policy ACROSS A FLEET,
        else a loud reason naming the sanctioned alternative (ISSUE 20). The
        fleet contract is strictly narrower than single-process serving: a
        pane rotation must land at a FLEET-CONSISTENT cut boundary (the
        shared plan cursor), so only the replay-cursor cadence qualifies, and
        the boundary fold crosses hosts, so cat states hit the same
        pane-provenance scramble the deferred-mesh check refuses.

        * ``pane_seconds``/wall-clock cadence: each host's clock would rotate
          at a different batch position — no fleet-consistent cut, replay
          non-deterministic. Use ``pane_batches`` (exact under the shared
          plan cursor), or serve time-cadence windows single-process.
        * ewma: the decay is a per-host in-place scale with no cut-aligned
          structure event the fleet protocol can order against the fold —
          serve ewma single-process, or tumbling/sliding in the fleet.
        * cat/scan-strategy states: the hierarchical fleet fold stacks host
          pieces on dim 0 of every cat buffer — the pane axis under a ring —
          scrambling pane provenance. Serve cat-state metrics windowed
          single-process, or cumulative in the fleet.
        """
        if self.kind == "cumulative":
            return None
        if self.kind == "ewma":
            return (
                "ewma has no fleet-consistent rotation boundary (the decay is a "
                "per-host in-place scale, not a cut-aligned structure event) — "
                "serve ewma single-process, or tumbling/sliding in the fleet"
            )
        if self.pane_batches <= 0:
            return (
                "fleet pane rotation must ride the shared plan cursor "
                "(pane_batches cadence): a wall-clock cadence rotates each host "
                "at a different batch position with no fleet-consistent cut — "
                "use WindowPolicy with pane_batches, or serve time-cadence "
                "windows single-process"
            )
        if self.kind == "sliding":
            r = (
                metric.stacked_merge_unsupported_reason()
                if hasattr(metric, "stacked_merge_unsupported_reason")
                else "metric has no stacked merge (merge_stacked_states)"
            )
            if r is not None:
                return f"sliding folds live panes via merge_stacked_states: {r}"
        info_fn = getattr(metric, "sync_leaf_info", None)
        if info_fn is not None and any(fx == "cat" for fx, _l, _p in info_fn()):
            return (
                "windowed fleet serving refuses cat/scan-strategy states: the "
                "hierarchical fleet fold stacks host pieces into each cat "
                "buffer's dim 0, which a pane ring uses for pane provenance — "
                "serve cat-state metrics windowed single-process, or cumulative "
                "in the fleet"
            )
        return None

    # ----------------------------------------------------------------- rotation

    def rotations_due(
        self,
        batches_done: int,
        last_rotate_batches: int,
        now: float,
        last_rotate_time: float,
    ) -> int:
        """How many rotations the cadence owes at this batch boundary (0 in
        the steady interior of a pane). Batch cadence is a pure function of
        the replay cursor — kill/resume replays rotations at identical
        boundaries; time cadence reads the injectable clock."""
        if self.kind == "cumulative":
            return 0
        if self.pane_batches > 0:
            return max(0, (batches_done - last_rotate_batches) // self.pane_batches)
        if self.pane_seconds > 0 and now >= last_rotate_time + self.pane_seconds:
            return int((now - last_rotate_time) // self.pane_seconds)
        return 0
